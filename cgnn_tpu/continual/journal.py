"""The label journal: served traffic -> a growing labeled replay set.

Serving answers requests whose ground truth arrives LATER (a DFT run
finishes, an experiment is measured). The journal is the join point:

- every answered request appends a SERVED record — the wire payload
  that produced it, the prediction, the ``param_version`` that computed
  it, the trace id, and (when the serving core computed one) the
  content fingerprint;
- a late ``POST /label`` joins ground truth to that record by trace id
  or fingerprint, EXACTLY ONCE: retried/hedged requests share a trace
  id, so the journal holds at most one record per trace id, and a
  label that already landed answers ``already`` without touching the
  stored value — a retransmitted label can never double-apply.

Durability is an append-only JSONL stream (``served`` and ``label``
lines), bounded by size-capped rotation; the in-memory index is
bounded by record count with oldest-first eviction. The stream is the
CROSS-PROCESS interface: the continual trainer tails the router's
journal file (:class:`JournalTail` survives rotation) and replays the
same join logic to rebuild the labeled replay set — replay goes through
the identical ``_apply`` path as live appends, so exactly-once holds
across process restarts too.

Everything here is host-side bookkeeping: nothing touches the serving
dispatch path beyond one append per answered request, and nothing is
staged into jitted code.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from typing import Callable, Iterator

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.observe.metrics_io import jsonfinite


class LabelJournal:
    """Bounded served-request journal with exactly-once label joins.

    ``capacity`` bounds the in-memory index (oldest records evicted,
    labeled or not — the replay set is a window, not an archive);
    ``max_bytes`` bounds the on-disk stream via single-file rotation
    (``<path>`` -> ``<path>.1``). ``path=None`` keeps the journal
    memory-only (tests, and the serve-side journal when only the
    router's is durable).
    """

    def __init__(self, path: str | None = None, *, capacity: int = 8192,
                 max_bytes: int = 64 * 1024 * 1024):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self.path = path
        self.capacity = int(capacity)
        self.max_bytes = int(max_bytes)
        self._lock = racecheck.make_lock("continual.journal")
        # trace_id -> record dict; insertion order = arrival order, so
        # popitem(last=False) evicts oldest. Fingerprint is a secondary
        # index (many trace ids MAY share a fingerprint — the same
        # structure re-submitted; a fingerprint join lands on the OLDEST
        # unlabeled record with that print).
        self._by_trace: OrderedDict[str, dict] = OrderedDict()
        self._by_fp: dict[str, list] = {}
        self._join_seq = 0
        self.served = 0
        self.joined = 0
        self.duplicate_joins = 0
        self.unmatched_labels = 0
        self.evicted = 0
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    # ---- the shared apply path (live appends AND file replay) ----

    def _apply(self, obj: dict, persist: bool) -> str:
        """Apply one journal line under the lock; returns the join
        status for label lines ('joined'|'already'|'unmatched') and
        'served' for served lines."""
        kind = obj.get("kind")
        with self._lock:
            if kind == "served":
                status = self._apply_served_locked(obj)
            elif kind == "label":
                status = self._apply_label_locked(obj)
            else:
                raise ValueError(f"unknown journal line kind {kind!r}")
            if persist:
                self._write_locked(obj)
        return status

    def _apply_served_locked(self, obj: dict) -> str:
        tid = obj["trace_id"]
        if tid in self._by_trace:
            # a hedged/retried attempt re-reporting the same request:
            # the trace id IS the idempotency key — keep the first
            return "served"
        rec = dict(obj)
        rec.setdefault("label", None)
        rec["labeled"] = bool(rec.get("labeled"))
        self._by_trace[tid] = rec
        fp = rec.get("fingerprint")
        if fp:
            self._by_fp.setdefault(fp, []).append(tid)
        self.served += 1
        while len(self._by_trace) > self.capacity:
            old_tid, old = self._by_trace.popitem(last=False)
            ofp = old.get("fingerprint")
            if ofp and ofp in self._by_fp:
                tids = [t for t in self._by_fp[ofp] if t != old_tid]
                if tids:
                    self._by_fp[ofp] = tids
                else:
                    del self._by_fp[ofp]
            self.evicted += 1
        return "served"

    def _find_locked(self, trace_id: str | None,
                     fingerprint: str | None) -> dict | None:
        if trace_id is not None:
            return self._by_trace.get(trace_id)
        if fingerprint is not None:
            for tid in self._by_fp.get(fingerprint, ()):
                rec = self._by_trace.get(tid)
                if rec is not None and not rec["labeled"]:
                    return rec
            # all labeled (or none left): report the first for the
            # 'already' classification
            for tid in self._by_fp.get(fingerprint, ()):
                rec = self._by_trace.get(tid)
                if rec is not None:
                    return rec
        return None

    def _apply_label_locked(self, obj: dict) -> str:
        rec = self._find_locked(obj.get("trace_id"), obj.get("fingerprint"))
        if rec is None:
            self.unmatched_labels += 1
            return "unmatched"
        if rec["labeled"]:
            # exactly-once: the stored label is immutable; a re-sent
            # (or double-emitted) label is acknowledged, never applied
            self.duplicate_joins += 1
            return "already"
        rec["label"] = float(obj["label"])
        rec["labeled"] = True
        self._join_seq += 1
        rec["join_seq"] = self._join_seq
        self.joined += 1
        return "joined"

    # ---- live API ----

    def note_served(self, *, trace_id: str, payload: dict | None,
                    prediction: float | None, param_version: str,
                    fingerprint: str | None = None,
                    ts: float | None = None) -> None:
        """Append one answered request. ``payload`` is the wire body
        that produced it (what the trainer replays); None is allowed
        when the caller only needs join accounting."""
        self._apply(
            {
                "kind": "served",
                "trace_id": str(trace_id),
                "fingerprint": fingerprint,
                "payload": payload,
                "prediction": (None if prediction is None
                               else float(prediction)),
                "param_version": param_version,
                "ts": ts,
            },
            persist=self.path is not None,
        )

    def join(self, label: float, *, trace_id: str | None = None,
             fingerprint: str | None = None) -> str:
        """Join ground truth -> 'joined' | 'already' | 'unmatched'."""
        if trace_id is None and fingerprint is None:
            raise ValueError("join needs a trace_id or a fingerprint")
        return self._apply(
            {
                "kind": "label",
                "trace_id": trace_id,
                "fingerprint": fingerprint,
                "label": float(label),
            },
            persist=self.path is not None,
        )

    def apply_line(self, obj: dict) -> str:
        """Replay one parsed journal line WITHOUT re-persisting it (the
        tail-follower path; identical join semantics as live calls)."""
        return self._apply(obj, persist=False)

    # ---- consumption ----

    def labeled_records(self, after_seq: int = 0) -> list:
        """Joined records with ``join_seq > after_seq`` (join order) —
        copies of the record dicts, so callers mutate nothing shared."""
        with self._lock:
            recs = [dict(r) for r in self._by_trace.values()
                    if r["labeled"] and r.get("join_seq", 0) > after_seq]
        recs.sort(key=lambda r: r["join_seq"])
        return recs

    @property
    def join_seq(self) -> int:
        with self._lock:
            return self._join_seq

    def stats(self) -> dict:
        with self._lock:
            return {
                "served": self.served,
                "joined": self.joined,
                "duplicate_joins": self.duplicate_joins,
                "unmatched_labels": self.unmatched_labels,
                "evicted": self.evicted,
                "resident": len(self._by_trace),
            }

    # ---- persistence ----

    def _write_locked(self, obj: dict) -> None:
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
        # non-finite predictions/labels -> null: a diverging model must
        # not make the stream unparseable (graftcheck GC-JSONFINITE)
        self._fh.write(json.dumps(jsonfinite(obj), allow_nan=False) + "\n")
        self._fh.flush()
        if self._fh.tell() >= self.max_bytes:
            self._fh.close()
            self._fh = None
            os.replace(self.path, self.path + ".1")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    @classmethod
    def replay(cls, path: str, **kwargs) -> "LabelJournal":
        """Rebuild a journal's in-memory state from its stream (restart
        path). Reads the rotated predecessor first when present. The
        returned journal keeps appending to ``path``."""
        j = cls(path=None, **kwargs)
        for p in (path + ".1", path):
            try:
                with open(p, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            j.apply_line(json.loads(line))
            except FileNotFoundError:
                continue
        j.path = path
        return j


class JournalTail:
    """Incremental reader of a journal JSONL stream (cross-process).

    ``poll()`` returns newly appended parsed lines since the last call,
    surviving the writer's rotation: the open handle keeps reading the
    renamed file to EOF (POSIX semantics), and a changed inode at EOF
    reopens the new stream from offset 0 — no line is skipped and none
    is delivered twice. A torn trailing line (writer mid-append) stays
    buffered until its newline lands.

    The no-skip guarantee assumes the tail polls at least once per
    rotation (a second ``os.replace`` overwrites ``<path>.1`` for
    good); with the 64 MiB default rotation size and second-scale poll
    cadences that holds by many orders of magnitude.
    """

    def __init__(self, path: str):
        self.path = path
        self._fh = None
        self._ino = None
        self._buf = ""

    def _open(self) -> bool:
        try:
            self._fh = open(self.path, encoding="utf-8")
        except FileNotFoundError:
            self._fh = None
            return False
        self._ino = os.fstat(self._fh.fileno()).st_ino
        self._buf = ""
        return True

    def _rotated(self) -> bool:
        try:
            return os.stat(self.path).st_ino != self._ino
        except FileNotFoundError:
            return False

    def poll(self, on_error: Callable | None = None) -> list:
        """Newly appended parsed line objects (possibly empty)."""
        out: list = []
        if self._fh is None and not self._open():
            return out
        for _round in range(2):  # current handle, then post-rotation
            chunk = self._fh.read()
            if chunk:
                self._buf += chunk
                while "\n" in self._buf:
                    line, self._buf = self._buf.split("\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError as e:
                        if on_error is not None:
                            on_error(f"journal tail: bad line: {e}")
            if not self._rotated():
                break
            # writer rotated underneath us: old handle is drained (read
            # returned ''), switch to the new stream from the top
            self._fh.close()
            if not self._open():
                break
        return out

    def follow_into(self, journal: LabelJournal,
                    on_error: Callable | None = None) -> int:
        """Apply every new line into ``journal``; returns lines applied."""
        lines = self.poll(on_error=on_error)
        for obj in lines:
            journal.apply_line(obj)
        return len(lines)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def iter_labeled_graphs(records) -> Iterator:
    """Journal records -> (CrystalGraph with the TRUE target, record).

    Featurized-wire records replay through the same
    ``graph_from_json`` path the HTTP handler uses; records without a
    payload (accounting-only journals) are skipped.
    """
    import dataclasses

    import numpy as np

    from cgnn_tpu.serve.http import graph_from_json

    for rec in records:
        payload = rec.get("payload")
        if not payload or not rec.get("labeled"):
            continue
        graph_json = payload.get("graph")
        if graph_json is None:
            continue
        try:
            g = graph_from_json(graph_json)
        except ValueError:
            continue
        g = dataclasses.replace(
            g, target=np.asarray([rec["label"]], np.float32))
        yield g, rec
