"""Bond featurization: Gaussian basis expansion of interatomic distance.

Replaces the reference's ``GaussianDistance`` (SURVEY.md §2 component 4):
``exp(-(d - mu_k)^2 / sigma^2)`` over a mu grid [dmin, dmax] with spacing
``step``. Default grid (dmin=0, dmax=radius=8, step=0.2) gives 41 features,
matching the lineage's nbr_fea_len.
"""

from __future__ import annotations

import numpy as np


class GaussianDistance:
    """Expand scalar distances into a Gaussian radial basis."""

    def __init__(self, dmin: float = 0.0, dmax: float = 8.0, step: float = 0.2,
                 var: float | None = None):
        if dmin >= dmax:
            raise ValueError(f"dmin={dmin} must be < dmax={dmax}")
        if step <= 0:
            raise ValueError(f"step={step} must be positive")
        self.filter = np.arange(dmin, dmax + step, step, dtype=np.float32)
        self.var = float(var if var is not None else step)

    @property
    def num_features(self) -> int:
        return len(self.filter)

    def expand(self, distances: np.ndarray) -> np.ndarray:
        """[...] distances -> [..., K] expanded features (float32)."""
        return gaussian_expand(distances, self.filter, self.var)


def gaussian_expand(distances, filter: np.ndarray, var: float) -> np.ndarray:
    """The one radial-basis formula (numpy form): shared by
    ``GaussianDistance.expand`` and the compact-staging per-graph probe
    (data/compact.py), so a change here cannot desynchronize them. The
    jit-side twin lives in ``compact.make_expander`` (jnp)."""
    d = np.asarray(distances, dtype=np.float32)
    return np.exp(
        -((d[..., None] - np.asarray(filter, np.float32)) ** 2)
        / np.float32(var) ** 2
    ).astype(np.float32)
