"""In-tree CIF parser (pymatgen unavailable — SURVEY.md §7 phase 0).

Supports the subset the pipeline needs: cell parameters, atom-site loops
(type symbol or label), fractional coordinates, mmCIF-style dotted tags
(folded to underscores), and symmetry expansion via
``_symmetry_equiv_pos_as_xyz`` / ``_space_group_symop_operation_xyz`` loops
(affine x,y,z expression strings applied and deduplicated). There is no
space-group-symbol engine: files declaring a non-P1 Hermann-Mauguin symbol
or IT number WITHOUT an explicit operator loop are REFUSED loudly (reading
only the asymmetric unit as P1 would silently drop atoms). Hostile-corpus
fixtures: tests/fixtures/cif/.

Out of scope (errors loudly, per SURVEY.md §7 "hard parts" #6): partial
occupancies < 1, disordered sites.
"""

from __future__ import annotations

import re
import shlex

import numpy as np

from cgnn_tpu.data.structure import Structure, lattice_from_parameters
from cgnn_tpu.data.elements import SYMBOL_TO_Z


class CIFError(ValueError):
    pass


def _strip_comment(line: str) -> str:
    # '#' starts a comment unless inside quotes; cheap scan.
    out, in_q = [], None
    for ch in line:
        if in_q:
            out.append(ch)
            if ch == in_q:
                in_q = None
        elif ch in "'\"":
            in_q = ch
            out.append(ch)
        elif ch == "#":
            break
        else:
            out.append(ch)
    return "".join(out)


def _tokenize(text: str) -> list[str]:
    """CIF token stream: handles quotes, semicolon text fields, comments."""
    tokens: list[str] = []
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = lines[i]
        if line.startswith(";"):  # multi-line text field
            field = [line[1:]]
            i += 1
            while i < len(lines) and not lines[i].startswith(";"):
                field.append(lines[i])
                i += 1
            tokens.append("\n".join(field))
            i += 1
            continue
        line = _strip_comment(line).strip()
        if line:
            try:
                lexer = shlex.shlex(line, posix=True)
                lexer.whitespace_split = True
                lexer.quotes = "'\""
                lexer.commenters = ""
                tokens.extend(list(lexer))
            except ValueError as e:
                raise CIFError(f"unparseable CIF line {i + 1}: {line!r}") from e
        i += 1
    return tokens


_NUM_RE = re.compile(
    r"^([+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?)(?:\(\d+\))?$"
)


def _parse_number(tok: str) -> float:
    """CIF numeric value, stripping the '(esd)' suffix, e.g. '4.0521(3)'."""
    m = _NUM_RE.match(tok)
    if not m:
        raise CIFError(f"expected a number, got {tok!r}")
    return float(m.group(1))


_SYMBOL_RE = re.compile(r"^([A-Za-z]{1,2})")


def _symbol_from_label(label: str) -> str:
    """'Fe2+', 'O1', 'FE1', 'Ca_a' -> element symbol.

    Case-insensitive: all-caps labels ('FE1', 'CA2') are common in legacy
    CIFs. The two-letter reading is preferred when it is a valid element
    ('FE'->Fe, not F), matching pymatgen's resolution of the ambiguity.
    """
    m = _SYMBOL_RE.match(label.strip())
    if not m:
        raise CIFError(f"cannot extract element symbol from {label!r}")
    raw = m.group(1)
    two = raw.capitalize() if len(raw) == 2 else None
    one = raw[0].upper()
    if two and two in SYMBOL_TO_Z:
        return two
    if one in SYMBOL_TO_Z:
        return one
    raise CIFError(f"unknown element in site label {label!r}")


def _norm_tag(tag: str) -> str:
    """Lowercase a data name and fold mmCIF's category.item dots to
    underscores: '_atom_site.fract_x' -> '_atom_site_fract_x'."""
    return tag.lower().replace(".", "_")


def _parse_blocks(tokens: list[str]) -> list[dict]:
    """All data_ blocks -> [{"items": {tag: value}, "loops": [...]}, ...].

    Selection policy lives in ``parse_cif``: the first block carrying an
    atom-site loop with fractional coordinates wins (publication CIFs often
    lead with a metadata-only block); with no such block, the first block
    is used so its specific failure (Cartesian-only sites, no sites) is
    reported.
    """
    blocks: list[dict] = []
    items: dict[str, str] = {}
    loops: list[tuple[list[str], list[list[str]]]] = []
    i = 0
    n = len(tokens)
    seen_data = False
    while i < n:
        tok = tokens[i]
        low = tok.lower()
        if low.startswith("data_"):
            if seen_data:
                blocks.append({"items": items, "loops": loops})
                items, loops = {}, []
            seen_data = True
            i += 1
        elif low == "loop_":
            i += 1
            headers = []
            while i < n and tokens[i].startswith("_"):
                headers.append(_norm_tag(tokens[i]))
                i += 1
            values = []
            while i < n and not tokens[i].startswith("_") and \
                    not tokens[i].lower().startswith(("loop_", "data_")):
                values.append(tokens[i])
                i += 1
            if headers and len(values) % len(headers) == 0:
                rows = [
                    values[j : j + len(headers)]
                    for j in range(0, len(values), len(headers))
                ]
                loops.append((headers, rows))
            elif headers:
                raise CIFError(
                    f"loop with {len(headers)} columns has {len(values)} values"
                )
        elif tok.startswith("_"):
            if i + 1 < n and not tokens[i + 1].startswith("_") and \
                    not tokens[i + 1].lower().startswith(("loop_", "data_")):
                items[_norm_tag(tok)] = tokens[i + 1]
                i += 2
            else:
                items[_norm_tag(tok)] = ""
                i += 1
        else:
            i += 1
    blocks.append({"items": items, "loops": loops})
    return blocks


def _has_fract_sites(block: dict) -> bool:
    return any(
        h.startswith("_atom_site_fract")
        for headers, _ in block["loops"]
        for h in headers
    )


_FRAC_RE = re.compile(r"(\d+)\s*/\s*(\d+)")


def parse_symmetry_op(op: str) -> tuple[np.ndarray, np.ndarray]:
    """'x,y,z'-style affine operator string -> (rotation [3,3], translation [3]).

    Handles terms like '-x', '1/2+y', 'x-y', '0.25+z'. Implemented as a hand
    parser (no eval) over '+'/'-'-separated terms.
    """
    rot = np.zeros((3, 3), dtype=np.float64)
    trans = np.zeros(3, dtype=np.float64)
    parts = op.lower().replace(" ", "").split(",")
    if len(parts) != 3:
        raise CIFError(f"bad symmetry op {op!r}")
    axis = {"x": 0, "y": 1, "z": 2}
    for row, expr in enumerate(parts):
        # split into signed terms
        terms = re.findall(r"[+-]?[^+-]+", expr)
        if not terms:
            raise CIFError(f"bad symmetry expression {expr!r} in {op!r}")
        for term in terms:
            sign = -1.0 if term.startswith("-") else 1.0
            body = term.lstrip("+-")
            if body in axis:
                rot[row, axis[body]] += sign
            else:
                m = _FRAC_RE.fullmatch(body)
                if m:
                    trans[row] += sign * int(m.group(1)) / int(m.group(2))
                else:
                    try:
                        trans[row] += sign * float(body)
                    except ValueError as e:
                        raise CIFError(
                            f"bad symmetry term {term!r} in {op!r}"
                        ) from e
    return rot, trans


_SYMOP_TAGS = (
    "_symmetry_equiv_pos_as_xyz",
    "_space_group_symop_operation_xyz",
)


def parse_cif(text: str, occupancy_tol: float = 0.999) -> Structure:
    """CIF text -> Structure (symmetry-expanded to the full cell, P1).

    Multi-block files: the FIRST block with fractional atom sites is the
    structure (see _parse_blocks for the policy rationale).
    """
    blocks = _parse_blocks(_tokenize(text))
    parsed = next((b for b in blocks if _has_fract_sites(b)), blocks[0])
    items, loops = parsed["items"], parsed["loops"]

    try:
        cell = [
            _parse_number(items[k])
            for k in (
                "_cell_length_a",
                "_cell_length_b",
                "_cell_length_c",
                "_cell_angle_alpha",
                "_cell_angle_beta",
                "_cell_angle_gamma",
            )
        ]
    except KeyError as e:
        raise CIFError(f"missing cell parameter {e}") from e
    lattice = lattice_from_parameters(*cell)

    # Atom-site loop.
    site_loop = None
    for headers, rows in loops:
        if any(h.startswith("_atom_site_fract") for h in headers):
            site_loop = (headers, rows)
            break
    if site_loop is None:
        if any(
            h.startswith("_atom_site_cartn")
            for headers, _ in loops for h in headers
        ):
            raise CIFError(
                "atom sites give only Cartesian (_atom_site_Cartn_*) "
                "coordinates (mmCIF convention); fractional coordinates "
                "are required"
            )
        raise CIFError("no _atom_site_ loop with fractional coordinates")
    headers, rows = site_loop

    def col(name: str) -> int | None:
        return headers.index(name) if name in headers else None

    ix = col("_atom_site_fract_x")
    iy = col("_atom_site_fract_y")
    iz = col("_atom_site_fract_z")
    if None in (ix, iy, iz):
        raise CIFError("atom-site loop lacks fract_x/y/z")
    isym = col("_atom_site_type_symbol")
    ilab = col("_atom_site_label")
    iocc = col("_atom_site_occupancy")
    if isym is None and ilab is None:
        raise CIFError("atom-site loop lacks both type_symbol and label")

    symbols, fracs = [], []
    for row in rows:
        if iocc is not None and row[iocc] not in (".", "?"):
            occ = _parse_number(row[iocc])
            if occ < occupancy_tol:
                raise CIFError(
                    f"partial occupancy {occ} unsupported (site {row})"
                )
        raw = row[isym] if isym is not None else row[ilab]
        symbols.append(_symbol_from_label(raw))
        fracs.append([_parse_number(row[i]) for i in (ix, iy, iz)])

    # Symmetry operators (default: identity only == P1).
    ops: list[tuple[np.ndarray, np.ndarray]] = []
    for headers2, rows2 in loops:
        for tag in _SYMOP_TAGS:
            if tag in headers2:
                j = headers2.index(tag)
                ops = [parse_symmetry_op(r[j]) for r in rows2]
                break
        if ops:
            break
    for tag in _SYMOP_TAGS:  # non-loop single op
        if not ops and tag in items and items[tag]:
            ops = [parse_symmetry_op(items[tag])]
    if not ops:
        # No explicit operators: refuse files that DECLARE a non-P1 space
        # group by Hermann-Mauguin symbol or IT number — silently reading
        # them as P1 would drop all but the asymmetric unit's atoms
        # (SURVEY.md §7 hard parts #6: error loudly, no HM engine).
        hm = next(
            (
                items[t]
                for t in (
                    "_symmetry_space_group_name_h-m",
                    "_space_group_name_h-m_alt",
                )
                if items.get(t)
            ),
            "",
        )
        it_number = items.get(
            "_space_group_it_number",
            items.get("_symmetry_int_tables_number", ""),
        )
        hm_flat = hm.replace(" ", "").replace("_", "").upper()
        # '.'/'?' are CIF placeholders for inapplicable/unknown, not a
        # declared space group — fall through to the IT-number check
        hm_declared = hm and hm_flat not in (".", "?")
        if hm_declared and hm_flat != "P1":
            raise CIFError(
                f"space group {hm!r} declared without an explicit symmetry-"
                f"operator loop ({'/'.join(_SYMOP_TAGS)}); this parser has "
                f"no Hermann-Mauguin engine — re-export the file with "
                f"explicit operators or symmetry-expanded (P1) sites"
            )
        # checked regardless of a (possibly mislabeled) 'P 1' H-M value: a
        # declared non-1 IT number with no operators means the sites are an
        # asymmetric unit either way
        if it_number and it_number not in ("1", ".", "?"):
            raise CIFError(
                f"space group IT number {it_number} declared without an "
                f"explicit symmetry-operator loop; cannot expand (no "
                f"space-group table in this parser)"
            )
        # Hall symbols declare a group just as firmly as H-M/IT-number do
        # (advisor r3: a Hall-only non-P1 CIF silently parsed as P1 and
        # dropped the symmetry-equivalent atoms). P1's Hall symbol is 'P 1'.
        hall = next(
            (
                items[t]
                for t in (
                    "_space_group_name_hall",
                    "_symmetry_space_group_name_hall",
                )
                if items.get(t)
            ),
            "",
        )
        hall_flat = hall.replace(" ", "").replace("_", "").upper()
        if hall and hall_flat not in ("P1", ".", "?"):
            raise CIFError(
                f"Hall symbol {hall!r} declared without an explicit "
                f"symmetry-operator loop; this parser has no Hall engine — "
                f"re-export with explicit operators or P1 sites"
            )
        ops = [(np.eye(3), np.zeros(3))]

    # Expand and deduplicate (wrap to [0,1), merge within tolerance).
    out_fracs: list[np.ndarray] = []
    out_numbers: list[int] = []
    tol = 1e-3
    for sym, frac in zip(symbols, fracs):
        z = SYMBOL_TO_Z[sym]
        base = np.asarray(frac, dtype=np.float64)
        for rot, trans in ops:
            pos = (rot @ base + trans) % 1.0
            dup = False
            for existing in out_fracs:
                delta = np.abs(pos - existing)
                delta = np.minimum(delta, 1.0 - delta)  # periodic distance
                if np.all(delta < tol):
                    dup = True
                    break
            if not dup:
                out_fracs.append(pos)
                out_numbers.append(z)

    return Structure(lattice, np.array(out_fracs), np.array(out_numbers))


def parse_cif_file(path) -> Structure:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return parse_cif(f.read())


def structure_to_cif(structure: Structure, name: str = "structure") -> str:
    """Minimal P1 CIF text for a Structure (round-trips through parse_cif).

    Inverse of the parser for the subset the pipeline needs: P1 cells with
    explicit sites (symmetry-expanded output, no symmetry operations).
    """
    from cgnn_tpu.data.elements import Z_TO_SYMBOL

    a, b, c, alpha, beta, gamma = structure.lattice_parameters()
    lines = [
        f"data_{name}",
        f"_cell_length_a {a:.6f}",
        f"_cell_length_b {b:.6f}",
        f"_cell_length_c {c:.6f}",
        f"_cell_angle_alpha {alpha:.6f}",
        f"_cell_angle_beta {beta:.6f}",
        f"_cell_angle_gamma {gamma:.6f}",
        "loop_",
        "_atom_site_label",
        "_atom_site_type_symbol",
        "_atom_site_fract_x",
        "_atom_site_fract_y",
        "_atom_site_fract_z",
    ]
    fracs = structure.wrapped().frac_coords
    for i, (z, f) in enumerate(zip(structure.numbers, fracs)):
        sym = Z_TO_SYMBOL[int(z)]
        lines.append(
            f"{sym}{i + 1} {sym} {f[0]:.6f} {f[1]:.6f} {f[2]:.6f}"
        )
    return "\n".join(lines) + "\n"


def write_cif_file(structure: Structure, path, name: str = "structure") -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(structure_to_cif(structure, name))
