"""Raw wire format: stage (positions, lattice, species), build graphs
ON DEVICE (ISSUE 11, ROADMAP item 5).

The compact form (data/compact.py) killed the featurized-array bytes but
still ships a HOST-BUILT graph: the periodic neighbor search
(data/neighbors.py) burns host cores per request and the wire carries
per-edge distances. This module is the next rung down: the wire carries
only what a structure IS —

    positions [N, 3] f32 (fractional), lattice [3, 3] f32, species [N] i32

— ~100x fewer bytes than featurized arrays (~516 B vs ~70 KB for a
30-atom MP cell) and near-zero host work per request (slot copies, no
radius search, no expansion). The in-program front of the pipeline
(ops/neighbor_search.py) then runs the periodic radius search, the
max_num_nbr truncation, and the Gaussian featurization INSIDE the
compiled program, emitting the exact dense-layout ``GraphBatch`` the
models consume.

Padded-capacity discipline (the repo's one batching idea, applied to
structures): a :class:`RawBatch` holds ``graph_cap`` structure slots of
``snode_cap`` atom slots each — per-STRUCTURE caps, not the flat
concatenated packing, because the neighbor search is per-structure
(atoms only neighbor atoms of their own crystal) and a block layout
makes it a dense vmapped candidate matrix instead of a masked
cross-graph scatter. The periodic image range is capped per rung too
(``RawSpec.images``): a fixed lexicographic offset grid, calibrated
from data like every other capacity.

Cap overflow contract (INVARIANTS.md): a structure whose lattice needs
MORE periodic images than the rung provides would silently lose true
edges — silently different predictions. The host pre-checks at
admission (``RawSpec.admits``, f64), and the compiled program
RE-DERIVES the needed image counts from the staged lattice and flags
per-structure overflow in its output (the safety net that still works
when positions are device-resident — relaxation/MD, ROADMAP item 2).
A flagged structure is never answered from the truncated graph; serving
routes it to the host-featurized fallback form.

Parity contract vs the host featurizer (pinned in
tests/test_rawwire.py): graph CONSTRUCTION is bit-exact — identical
edge sets, neighbor indices, canonical edge order (center, then
distance, then source atom, then lexicographic image), masks, and atom
feature rows. Scalar distances and Gaussian features agree to f32
roundoff (the host search works in f64 and XLA contracts multiply-adds
into FMAs), the same ≤1-ulp class as the compact expander's ``exp``.
``raw_neighbor_graph_host`` below is the numpy mirror of the device
arithmetic used by tests and by nothing on the serving path.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
from typing import Any, Sequence

import numpy as np
from flax import struct

from cgnn_tpu.data.elements import MAX_Z


class RawUnsupported(ValueError):
    """The dataset/calibration cannot plan a raw wire spec (caller
    should fall back to featurized wire — a capability probe, not a
    failure)."""


@dataclasses.dataclass
class RawStructure:
    """One structure in wire form (host-side, f64 for fidelity with the
    legacy parse path; ``pack_raw`` casts to the f32 wire dtypes)."""

    frac_coords: np.ndarray  # [N, 3] f64, any range (wrapped at pack)
    lattice: np.ndarray  # [3, 3] f64 row-vector
    numbers: np.ndarray  # [N] i32 atomic numbers
    target: np.ndarray | None = None  # [T] f32 (zeros when serving)
    cif_id: str = ""
    target_mask: np.ndarray | None = None

    def __post_init__(self):
        self.frac_coords = np.asarray(self.frac_coords,
                                      np.float64).reshape(-1, 3)
        self.lattice = np.asarray(self.lattice, np.float64).reshape(3, 3)
        self.numbers = np.asarray(self.numbers, np.int32).ravel()
        if len(self.numbers) != len(self.frac_coords):
            # checked at CONSTRUCTION so every entry point (HTTP json,
            # in-proc submit, offline) fails this structure ALONE — a
            # mismatch reaching pack_raw would poison its whole flush
            raise ValueError(
                f"{len(self.numbers)} species but "
                f"{len(self.frac_coords)} coordinate rows"
            )

    @property
    def num_nodes(self) -> int:
        return len(self.numbers)

    @property
    def num_edges(self) -> int:
        # structural slot accounting only (the true count is what the
        # in-program search determines); admission under the dense
        # layout budgets nodes * dense_m through ShapeSet.graph_counts,
        # which never reads this
        return 0

    @property
    def wire_nbytes(self) -> int:
        """Bytes this structure occupies in the f32 wire encoding:
        positions [N,3] f32 + lattice [3,3] f32 + species [N] i32."""
        n = self.num_nodes
        return n * 3 * 4 + 9 * 4 + n * 4

    @classmethod
    def from_structure(cls, s, target=None, cif_id: str = "",
                       target_mask=None) -> "RawStructure":
        return cls(s.frac_coords, s.lattice, s.numbers, target=target,
                   cif_id=cif_id or "", target_mask=target_mask)


def raw_from_graph(g) -> RawStructure | None:
    """Geometry-carrying CrystalGraph -> wire form, or None when the
    graph lacks geometry/species (featurize with keep_geometry=True).
    Fractional coordinates are recovered from the stored wrapped f32
    cartesians — the same f32 fidelity a wire client ships."""
    if (getattr(g, "positions", None) is None
            or getattr(g, "lattice", None) is None
            or getattr(g, "numbers", None) is None):
        return None
    lat = np.asarray(g.lattice, np.float64)
    frac = np.asarray(g.positions, np.float64) @ np.linalg.inv(lat)
    return RawStructure(frac, lat, g.numbers, target=g.target,
                        cif_id=g.cif_id, target_mask=g.target_mask)


def raw_fingerprint(rs: RawStructure) -> str:
    """Content hash of the f32 wire encoding (the result-cache key for
    raw-wire requests; 'raw:'-prefixed so a raw-served row can never
    collide with a featurized-array fingerprint). blake2b to match
    serve/cache.structure_fingerprint — in-memory key only, no persisted
    state, so the hash family can change without migration."""
    h = hashlib.blake2b(digest_size=20)
    for arr, dt in ((rs.frac_coords, np.float32),
                    (rs.lattice, np.float32),
                    (rs.numbers, np.int32)):
        a = np.ascontiguousarray(np.asarray(arr, dt))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return "raw:" + h.hexdigest()


def host_image_counts(lattice: np.ndarray, radius: float) -> tuple:
    """Needed periodic images per axis (f64, the admission pre-check
    twin of data/neighbors._image_counts)."""
    inv = np.linalg.inv(np.asarray(lattice, np.float64))
    return tuple(
        int(math.ceil(radius * np.linalg.norm(inv[:, k]) - 1e-12))
        for k in range(3)
    )


@dataclasses.dataclass(frozen=True)
class RawSpec:
    """Everything the in-program search needs: the per-structure atom
    slot cap, the periodic image caps, and the featurization constants.

    ``snode_cap`` and ``images`` are shared by every rung of a ladder
    (the plan_shape_set floor rule: ANY admitted structure must fit
    EVERY rung, so a deadline flush holding one lone structure still
    has a rung to land in); per-rung capacity scaling lives in the
    ladder's ``graph_cap`` — rung r's raw program holds
    ``graph_cap_r x snode_cap`` atom slots and
    ``graph_cap_r x snode_cap x dense_m`` edge slots.
    """

    snode_cap: int  # atom slots per structure (S)
    images: tuple  # (na, nb, nc) periodic image caps per axis
    radius: float
    dense_m: int  # max_num_nbr == the dense layout M
    gauss_filter: Any  # [G] f32 mu grid
    gauss_var: float

    @property
    def n_images(self) -> int:
        na, nb, nc = self.images
        return (2 * na + 1) * (2 * nb + 1) * (2 * nc + 1)

    def offsets_grid(self) -> np.ndarray:
        """[K, 3] i32 image offsets in lexicographic (ia, ib, ic) order
        — the canonical tie-break order, identical to the host search's
        ``np.mgrid`` enumeration restricted to any sub-grid."""
        na, nb, nc = self.images
        return (np.mgrid[-na:na + 1, -nb:nb + 1, -nc:nc + 1]
                .reshape(3, -1).T.astype(np.int32))

    @property
    def home_image(self) -> int:
        na, nb, nc = self.images
        return (na * (2 * nb + 1) + nb) * (2 * nc + 1) + nc

    # ---- admission ----

    def admits(self, rs: RawStructure) -> bool:
        """Host pre-check (f64): can THIS structure be staged raw
        without the in-program search losing true edges? Never raises."""
        try:
            if rs.num_nodes < 1 or rs.num_nodes > self.snode_cap:
                return False
            z = rs.numbers
            if z.min(initial=1) < 1 or z.max(initial=1) > MAX_Z:
                return False
            need = host_image_counts(rs.lattice, self.radius)
        except (ValueError, np.linalg.LinAlgError):
            return False
        return all(n <= c for n, c in zip(need, self.images))

    def oversize_detail(self, rs: RawStructure) -> str:
        try:
            need = host_image_counts(rs.lattice, self.radius)
        except (ValueError, np.linalg.LinAlgError):
            need = ("?",) * 3
        return (
            f"structure has {rs.num_nodes} atoms (cap {self.snode_cap}) "
            f"and needs {need} periodic images (caps {self.images})"
        )

    def template(self) -> RawStructure:
        """A trivially admissible warmup structure (1 H atom, cubic
        cell sized so one image per axis suffices)."""
        a = max(self.radius * 1.5, 1.0)
        return RawStructure(
            np.zeros((1, 3)), np.eye(3) * a, np.array([1], np.int32),
            target=np.zeros(1, np.float32), cif_id="raw-template",
        )

    def to_meta(self) -> dict:
        return {
            "snode_cap": self.snode_cap,
            "images": list(self.images),
            "radius": self.radius,
            "dense_m": self.dense_m,
            "gauss_len": int(len(self.gauss_filter)),
        }


def plan_raw_spec(
    calibration: Sequence,
    gdf,
    radius: float,
    dense_m: int,
    coverage: float = 0.95,
    image_margin: int = 0,
) -> RawSpec:
    """Calibrate a RawSpec from a sample of graphs/structures.

    The in-program search's candidate matrix is ``[S, S*K]`` per
    structure (S atom slots, K periodic images), so the caps ARE the
    compute: sizing them at the calibration MAX makes every request pay
    for the single worst tail structure (one 120-atom tiny-cell crystal
    inflates the whole ladder ~20x). Instead the caps cover the
    ``coverage`` quantile of the calibration distribution — structures
    beyond them are simply NOT raw-admitted (``RawSpec.admits``) and
    ride the host-featurized path, which exists anyway as the overflow
    fallback. ``coverage=1.0`` restores max-sizing.

    ``snode_cap`` = the coverage-quantile atom count (8-aligned);
    ``images`` = the per-axis coverage quantile of the f64 needed-image
    counts (+``image_margin``), floored at 1. Calibration items must
    carry a ``lattice`` (CrystalGraph with geometry, Structure, or
    RawStructure) — without one the image caps cannot be derived from
    data and raw wire is refused rather than guessed.
    """
    if not len(calibration):
        raise RawUnsupported("raw spec planning needs a calibration sample")
    if dense_m is None or dense_m < 1:
        raise RawUnsupported("raw wire requires the dense layout (dense_m)")
    lattices = [getattr(g, "lattice", None) for g in calibration]
    if any(la is None for la in lattices):
        raise RawUnsupported(
            "calibration sample carries no lattices (featurize with "
            "keep_geometry=True, or calibrate from structures)"
        )
    need = np.stack([host_image_counts(la, radius) for la in lattices])
    q = min(max(float(coverage), 0.0), 1.0)
    caps = np.maximum(
        np.quantile(need, q, axis=0, method="higher"), 1
    ).astype(np.int64) + image_margin
    sizes = np.asarray([int(g.num_nodes) for g in calibration])
    snode = int(np.quantile(sizes, q, method="higher"))
    snode = max(8, -(-snode // 8) * 8)
    return RawSpec(
        snode_cap=snode,
        images=tuple(int(c) for c in caps),
        radius=float(radius),
        dense_m=int(dense_m),
        gauss_filter=np.asarray(gdf.filter, np.float32),
        gauss_var=float(gdf.var),
    )


class RawBatch(struct.PyTreeNode):
    """Wire-form packed batch: per-structure slots (device-side pytree).

    Structure slot ``g`` owns atom slots ``[g*S, (g+1)*S)`` of the flat
    node space the in-program search emits; the rebuilt GraphBatch's
    ``node_graph`` is ``slot // S`` and its edge slots follow the dense
    layout (node n owns edge slots ``[n*M, (n+1)*M)``). Padding
    structures carry an identity lattice (host-written: the in-program
    3x3 inverse must never see a singular matrix) and all-zero masks.
    """

    frac: Any  # [Gcap, S, 3] f32, wrapped into [0, 1)
    lattices: Any  # [Gcap, 3, 3] f32 (padding: eye)
    species: Any  # [Gcap, S] i32 atomic number Z (padding: 0)
    atom_mask: Any  # [Gcap, S] u8
    graph_mask: Any  # [Gcap] f32
    targets: Any  # [Gcap, T] f32
    target_mask: Any  # [Gcap, T] f32

    @property
    def graph_capacity(self) -> int:
        return self.targets.shape[0]

    @property
    def snode_cap(self) -> int:
        return self.frac.shape[1]

    # PaddingStats/driver interface parity with GraphBatch
    @property
    def node_capacity(self) -> int:
        return self.frac.shape[0] * self.frac.shape[1]


def raw_shape_key(batch: RawBatch) -> tuple:
    """Hashable full-shape key (the batch_shape_key analog)."""
    return ("raw", np.shape(batch.frac), np.shape(batch.targets))


def pack_raw(
    items: Sequence[RawStructure],
    graph_cap: int,
    spec: RawSpec,
    num_targets: int = 1,
) -> RawBatch:
    """Stage wire-form structures into one fixed-capacity RawBatch.

    Near-zero host work by design: wrap + cast + slot copies. No
    neighbor search, no featurization, no per-edge arrays — that is the
    point of the wire format.
    """
    if not items:
        raise ValueError("cannot pack an empty structure list")
    n_items = len(items)
    if n_items > graph_cap:
        raise ValueError(f"{n_items} structures exceed graph_cap={graph_cap}")
    s_cap = spec.snode_cap
    frac = np.zeros((graph_cap, s_cap, 3), np.float32)
    lattices = np.zeros((graph_cap, 3, 3), np.float32)
    lattices[:] = np.eye(3, dtype=np.float32)  # padding-safe inverse
    species = np.zeros((graph_cap, s_cap), np.int32)
    atom_mask = np.zeros((graph_cap, s_cap), np.uint8)
    graph_mask = np.zeros(graph_cap, np.float32)
    targets = np.zeros((graph_cap, num_targets), np.float32)
    target_mask = np.zeros((graph_cap, num_targets), np.float32)
    for gi, rs in enumerate(items):
        n = rs.num_nodes
        if n > s_cap:
            raise ValueError(
                f"structure {rs.cif_id!r} has {n} atoms > snode_cap="
                f"{s_cap}; RawSpec.admits should have routed it to the "
                f"featurized fallback"
            )
        f = rs.frac_coords % 1.0
        # tiny negatives give f == 1.0 exactly under %; enforce the
        # half-open interval the image-count bound relies on
        # (data/structure.py wrapped())
        f = np.where(f >= 1.0, 0.0, f)
        frac[gi, :n] = f.astype(np.float32)
        lattices[gi] = rs.lattice.astype(np.float32)
        species[gi, :n] = rs.numbers
        atom_mask[gi, :n] = 1
        graph_mask[gi] = 1.0
        if rs.target is not None:
            t = np.atleast_1d(np.asarray(rs.target, np.float32))
            targets[gi, : len(t)] = t
            if rs.target_mask is not None:
                target_mask[gi, : len(t)] = np.atleast_1d(rs.target_mask)
            else:
                target_mask[gi, : len(t)] = 1.0
    return RawBatch(
        frac=frac, lattices=lattices, species=species,
        atom_mask=atom_mask, graph_mask=graph_mask,
        targets=targets, target_mask=target_mask,
    )


def abstract_raw_batch(graph_cap: int, spec: RawSpec,
                       num_targets: int = 1) -> RawBatch:
    """A zeros RawBatch of one rung's shape (the graftaudit lowering
    surface; content-free by construction)."""
    return pack_raw([spec.template()], graph_cap, spec,
                    num_targets=num_targets)


# ---- the numpy mirror of the in-program search (tests only) ----------


def raw_neighbor_graph_host(
    frac: np.ndarray,  # [S, 3] f32 wrapped (padding rows 0)
    lattice: np.ndarray,  # [3, 3] f32
    atom_mask: np.ndarray,  # [S] bool/u8
    spec: RawSpec,
) -> tuple:
    """Numpy mirror of ``ops.neighbor_search`` for ONE structure ->
    (neighbors [S, M] i32 local, distances [S, M] f32, edge_mask
    [S, M] u8, n_edges int, overflow bool).

    Same f32 arithmetic and the same canonical order — (center, then
    distance, then source atom, then lexicographic image) — as the
    device op; distances can differ from the compiled program by f32
    roundoff (XLA FMA contraction), while the selected edge set and
    order are exact wherever the radius/tie decisions are exact.
    """
    s_cap, m = spec.snode_cap, spec.dense_m
    frac = np.asarray(frac, np.float32)
    lat = np.asarray(lattice, np.float32)
    mask = np.asarray(atom_mask).astype(bool)
    grid = spec.offsets_grid()
    k = len(grid)
    cart = frac @ lat  # [S, 3] f32
    shifts = grid.astype(np.float32) @ lat  # [K, 3]
    pos_j = cart[:, None, :] + shifts[None, :, :]  # [S, K, 3]
    diff = pos_j[None, :, :, :] - cart[:, None, None, :]  # [S, S, K, 3]
    d2 = (diff[..., 0] * diff[..., 0] + diff[..., 1] * diff[..., 1]
          + diff[..., 2] * diff[..., 2])
    d = np.sqrt(d2).reshape(s_cap, s_cap * k)  # candidate order c = j*K + k
    valid = (mask[None, :, None] & mask[:, None, None]
             & np.ones((s_cap, s_cap, k), bool))
    eye = np.eye(s_cap, dtype=bool)[:, :, None] & (
        np.arange(k) == spec.home_image
    )[None, None, :]
    valid &= ~eye
    valid = valid.reshape(s_cap, s_cap * k)
    valid &= d <= np.float32(spec.radius)
    key = np.where(valid, d, np.float32(np.inf))
    order = np.argsort(key, axis=1, kind="stable")[:, :m]
    sorted_d = np.take_along_axis(d, order, axis=1)
    n_valid = valid.sum(axis=1)
    emask = (np.arange(m)[None, :] < n_valid[:, None]).astype(np.uint8)
    nbr = np.where(emask > 0, (order // k).astype(np.int32),
                   np.arange(s_cap, dtype=np.int32)[:, None])
    dist = np.where(emask > 0, sorted_d, np.float32(0.0))
    n_edges = int(np.minimum(n_valid, m).sum())
    need = needed_images_f32(lat, spec.radius)
    overflow = bool(np.any(need > np.asarray(spec.images, np.float32)))
    return nbr, dist.astype(np.float32), emask, n_edges, overflow


def needed_images_f32(lattice: np.ndarray, radius: float) -> np.ndarray:
    """[3] f32 needed-image counts from the f32 lattice — the EXACT
    formula the compiled program re-derives (ops/neighbor_search.py):
    plane spacing along axis k is |det| / ||a_{k+1} x a_{k+2}||, so
    needed_k = ceil(radius / spacing_k - 1e-4). The 1e-4 slack (vs the
    host f64 pre-check's 1e-12) absorbs f32 roundoff at exact-integer
    boundaries; a lattice engineered within 1e-4 of one can differ from
    the f64 judgment by one image, which the host pre-check (not this)
    gates at admission."""
    a = np.asarray(lattice, np.float32)
    cross = np.stack([
        np.cross(a[1], a[2]), np.cross(a[2], a[0]), np.cross(a[0], a[1]),
    ]).astype(np.float32)
    det = np.abs(np.float32(np.dot(a[0], cross[0])))
    norms = np.sqrt((cross * cross).sum(axis=1))
    return np.ceil(np.float32(radius) * norms / det - np.float32(1e-4))
