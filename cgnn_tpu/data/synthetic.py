"""Synthetic crystal generator + packaged toy datasets.

Stands in for Materials Project / OC20 / MD17 downloads, which are
unavailable offline (SURVEY.md §7 phase 0). Structures are random perturbed
lattices with a smooth, physically-flavored synthetic target so training
curves are meaningful (loss must beat a mean predictor — SURVEY.md §4.4).
"""

from __future__ import annotations

import numpy as np

from cgnn_tpu.data.elements import ELEMENTS
from cgnn_tpu.data.structure import Structure, lattice_from_parameters

# A spread of common elements across blocks (s/p/d) for synthetic crystals.
_SYNTH_ELEMENTS = np.array(
    [1, 3, 6, 7, 8, 9, 11, 12, 13, 14, 15, 16, 17, 19, 20, 22, 24, 26, 27,
     28, 29, 30, 31, 33, 38, 40, 42, 47, 50, 56, 74, 79, 82],
    dtype=np.int32,
)


def random_structure(
    rng: np.random.Generator,
    min_atoms: int = 2,
    max_atoms: int = 12,
    a_range: tuple[float, float] = (3.5, 7.5),
    min_separation: float = 1.2,
) -> Structure:
    """Random near-orthorhombic cell with a minimum-separation rejection pass."""
    n = int(rng.integers(min_atoms, max_atoms + 1))
    abc = rng.uniform(*a_range, size=3) * (1.0 + 0.15 * (n / max_atoms))
    angles = rng.uniform(80.0, 100.0, size=3)
    lattice = lattice_from_parameters(*abc, *angles)
    # place atoms with a crude minimum-distance rejection (not physical, just
    # avoids coincident sites which would create zero-distance edges); the
    # accept check is vectorized over placed atoms but the rng draw pattern
    # is one candidate per attempt, so seeded datasets are unchanged
    fracs: list[np.ndarray] = []
    placed = np.empty((0, 3))
    for _ in range(n):
        for _attempt in range(256):
            cand = rng.uniform(0, 1, size=3)
            d = ((cand - placed + 0.5) % 1.0 - 0.5) @ lattice
            if len(placed) == 0 or float(
                np.min(np.einsum("ij,ij->i", d, d))
            ) > min_separation**2:
                break
        fracs.append(cand)
        placed = np.concatenate([placed, cand[None]])
    numbers = rng.choice(_SYNTH_ELEMENTS, size=n)
    return Structure(lattice, np.array(fracs), numbers)


def synthetic_target(structure: Structure, noise: float = 0.0,
                     rng: np.random.Generator | None = None) -> float:
    """Smooth function of composition + geometry (a fake formation energy).

    Mixes per-element electronegativity/radius with a pairwise soft-coordination
    term so the target depends on both node features and graph structure —
    i.e. a model that ignores edges cannot fit it.
    """
    en = np.array(
        [ELEMENTS[int(z)][4] if ELEMENTS[int(z)][4] == ELEMENTS[int(z)][4] else 1.5
         for z in structure.numbers]
    )
    rad = np.array([ELEMENTS[int(z)][5] for z in structure.numbers]) / 100.0
    comp = float(np.mean(-0.8 * en + 0.3 * rad))
    # soft coordination: pairwise periodic min-image distances under 4.5 Å
    cart = structure.cart_coords
    lat = structure.lattice
    coord = 0.0
    n = structure.num_atoms
    for i in range(n):
        d_frac = (structure.frac_coords - structure.frac_coords[i] + 0.5) % 1.0 - 0.5
        d = np.linalg.norm(d_frac @ lat, axis=1)
        d = d[d > 1e-8]
        coord += float(np.sum(np.exp(-((d / 2.5) ** 2))))
    coord /= n
    target = comp - 0.35 * coord
    if noise and rng is not None:
        target += float(rng.normal(0, noise))
    return target


def synthetic_dataset(
    num_structures: int,
    seed: int = 0,
    noise: float = 0.01,
    min_atoms: int = 2,
    max_atoms: int = 12,
) -> list[tuple[str, Structure, float]]:
    """[(id, Structure, target)] — deterministic given the seed."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(num_structures):
        s = random_structure(rng, min_atoms, max_atoms)
        t = synthetic_target(s, noise, rng)
        out.append((f"synth-{i:06d}", s, t))
    return out


def synthetic_mp_dataset(
    num_structures: int,
    seed: int = 0,
    mean_atoms: float = 30.0,
    sigma: float = 0.55,
    max_atoms: int = 120,
) -> list[tuple[str, Structure, float]]:
    """MP-like size distribution: lognormal cell sizes centered near 30 atoms.

    Materials Project unit cells average ~30 atoms with a long right tail;
    benchmarking on the tiny default synthetics (~7 atoms) overstates
    structures/sec by the size ratio (VERDICT round 1 weak #3). Cell volume
    scales with atom count at ~16 Å^3/atom so density stays physical.
    """
    rng = np.random.default_rng(seed)
    mu = float(np.log(mean_atoms) - 0.5 * sigma**2)
    out = []
    for i in range(num_structures):
        n = int(np.clip(np.round(rng.lognormal(mu, sigma)), 4, max_atoms))
        a = float((n * 16.0) ** (1.0 / 3.0))
        s = random_structure(
            rng, n, n, a_range=(a * 0.9, a * 1.1), min_separation=1.6
        )
        t = synthetic_target(s, noise=0.01, rng=rng)
        out.append((f"mp-{i:06d}", s, t))
    return out


def lj_energy_forces(
    structure: Structure, epsilon: float = 0.4, sigma: float = 2.2,
    cutoff: float = 6.0,
) -> tuple[float, np.ndarray]:
    """Lennard-Jones energy + analytic forces under PBC (MD17 stand-in).

    Physical ground truth for the force head: forces are exactly -dE/dr of
    a smooth pair potential, so a correct model/autodiff pipeline can fit
    both consistently (SURVEY.md §7 phase 7).
    """
    from cgnn_tpu.data.neighbors import neighbor_list

    nl = neighbor_list(structure, cutoff)
    cart = structure.cart_coords
    rel = (
        cart[nl.neighbors]
        + nl.offsets.astype(np.float64) @ structure.lattice
        - cart[nl.centers]
    )  # vector from center i to neighbor j
    r = np.linalg.norm(rel, axis=1)
    sr6 = (sigma / r) ** 6
    # each ordered pair appears twice -> half energy per ordered pair
    energy = float(np.sum(2.0 * epsilon * (sr6**2 - sr6)))
    # dE/dr per ordered pair (full pair derivative split symmetrically)
    dEdr = 4.0 * epsilon * (-12.0 * sr6**2 + 6.0 * sr6) / r
    # F_i = -dE/dr_i; with rel = r_j - r_i, dr/dr_i = -rel/r, so the force
    # on i from the ordered pair (i,j) is +(dE/dr)(rel/r)
    f_pair = (dEdr / r)[:, None] * rel
    forces = np.zeros_like(cart)
    np.add.at(forces, nl.centers, f_pair)
    return energy, forces.astype(np.float32)


def synthetic_trajectory(
    num_frames: int,
    seed: int = 0,
    num_atoms: int = 8,
    jitter: float = 0.08,
) -> list[tuple[str, Structure, float, np.ndarray]]:
    """MD17-like trajectory: one cell, per-frame position jitter, LJ labels.

    [(id, Structure, energy, forces[N,3])]; energies/forces are consistent
    (same potential), so fitting both is well-posed. Atoms start near the LJ
    equilibrium distance (r_eq = 2^(1/6)·σ ≈ 2.47 Å for the default σ=2.2)
    and the default jitter keeps pair distances off the r^-13 repulsive wall,
    so label magnitudes stay O(1) like a real MD trajectory's.
    """
    rng = np.random.default_rng(seed)
    base = random_structure(
        rng, num_atoms, num_atoms, a_range=(6.0, 7.5), min_separation=2.5
    )
    out = []
    for k in range(num_frames):
        fracs = base.frac_coords + rng.normal(0, jitter, base.frac_coords.shape) @ np.linalg.inv(base.lattice)
        s = Structure(base.lattice, fracs, base.numbers)
        e, f = lj_energy_forces(s)
        out.append((f"frame-{k:05d}", s, e, f))
    return out


def synthetic_slab(
    rng: np.random.Generator,
    nx: int = 3,
    ny: int = 3,
    layers: int = 4,
    a0: float = 3.9,
    adsorbate_atoms: int = 2,
) -> Structure:
    """OC20-like catalyst slab: fcc(100)-ish surface + small adsorbate.

    Produces the large-graph regime (50-200+ atoms, vacuum gap, surface
    under-coordination) that BASELINE config #4 calls 'large catalyst-surface
    graphs'."""
    metal = int(rng.choice([26, 27, 28, 29, 42, 46, 47, 74, 78, 79]))
    ads = rng.choice([1, 6, 7, 8], size=adsorbate_atoms)
    vacuum = 12.0
    lattice = np.diag([nx * a0, ny * a0, layers * a0 / 2 + vacuum])
    fracs, numbers = [], []
    for iz in range(layers):
        for ix in range(nx):
            for iy in range(ny):
                off = 0.5 if iz % 2 else 0.0
                fracs.append([
                    ((ix + off) / nx) % 1.0,
                    ((iy + off) / ny) % 1.0,
                    (iz * a0 / 2) / lattice[2, 2],
                ])
                numbers.append(metal)
    surface_z = (layers - 1) * a0 / 2
    for k, z in enumerate(ads):
        fracs.append([
            rng.uniform(0, 1),
            rng.uniform(0, 1),
            (surface_z + 1.6 + 1.1 * k) / lattice[2, 2],
        ])
        numbers.append(int(z))
    s = Structure(lattice, np.array(fracs), np.array(numbers, np.int32))
    # small thermal rattle so graphs aren't perfectly degenerate
    return Structure(
        lattice,
        s.frac_coords + rng.normal(0, 0.01, s.frac_coords.shape),
        s.numbers,
    )


def synthetic_oc20_dataset(
    num_structures: int, seed: int = 0
) -> list[tuple[str, Structure, float]]:
    """[(id, slab Structure, adsorption-energy-like target)]."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(num_structures):
        # 3x3x4+1 = 37 up to 6x6x7+3 = 255 atoms — the 50-200+ regime
        # BASELINE config #4 calls "large catalyst-surface graphs"
        s = synthetic_slab(
            rng,
            nx=int(rng.integers(3, 7)),
            ny=int(rng.integers(3, 7)),
            layers=int(rng.integers(4, 8)),
            adsorbate_atoms=int(rng.integers(1, 4)),
        )
        t = synthetic_target(s, noise=0.02, rng=rng)
        out.append((f"slab-{i:06d}", s, t))
    return out
