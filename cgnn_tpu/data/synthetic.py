"""Synthetic crystal generator + packaged toy datasets.

Stands in for Materials Project / OC20 / MD17 downloads, which are
unavailable offline (SURVEY.md §7 phase 0). Structures are random perturbed
lattices with a smooth, physically-flavored synthetic target so training
curves are meaningful (loss must beat a mean predictor — SURVEY.md §4.4).
"""

from __future__ import annotations

import numpy as np

from cgnn_tpu.data.elements import ELEMENTS
from cgnn_tpu.data.structure import Structure, lattice_from_parameters

# A spread of common elements across blocks (s/p/d) for synthetic crystals.
_SYNTH_ELEMENTS = np.array(
    [1, 3, 6, 7, 8, 9, 11, 12, 13, 14, 15, 16, 17, 19, 20, 22, 24, 26, 27,
     28, 29, 30, 31, 33, 38, 40, 42, 47, 50, 56, 74, 79, 82],
    dtype=np.int32,
)


def random_structure(
    rng: np.random.Generator,
    min_atoms: int = 2,
    max_atoms: int = 12,
    a_range: tuple[float, float] = (3.5, 7.5),
) -> Structure:
    """Random near-orthorhombic cell with a minimum-separation rejection pass."""
    n = int(rng.integers(min_atoms, max_atoms + 1))
    abc = rng.uniform(*a_range, size=3) * (1.0 + 0.15 * (n / max_atoms))
    angles = rng.uniform(80.0, 100.0, size=3)
    lattice = lattice_from_parameters(*abc, *angles)
    # place atoms with a crude minimum-distance rejection (not physical, just
    # avoids coincident sites which would create zero-distance edges)
    fracs: list[np.ndarray] = []
    for _ in range(n):
        for _attempt in range(64):
            cand = rng.uniform(0, 1, size=3)
            if all(
                np.linalg.norm(((cand - f + 0.5) % 1.0 - 0.5) @ lattice) > 1.2
                for f in fracs
            ):
                break
        fracs.append(cand)
    numbers = rng.choice(_SYNTH_ELEMENTS, size=n)
    return Structure(lattice, np.array(fracs), numbers)


def synthetic_target(structure: Structure, noise: float = 0.0,
                     rng: np.random.Generator | None = None) -> float:
    """Smooth function of composition + geometry (a fake formation energy).

    Mixes per-element electronegativity/radius with a pairwise soft-coordination
    term so the target depends on both node features and graph structure —
    i.e. a model that ignores edges cannot fit it.
    """
    en = np.array(
        [ELEMENTS[int(z)][4] if ELEMENTS[int(z)][4] == ELEMENTS[int(z)][4] else 1.5
         for z in structure.numbers]
    )
    rad = np.array([ELEMENTS[int(z)][5] for z in structure.numbers]) / 100.0
    comp = float(np.mean(-0.8 * en + 0.3 * rad))
    # soft coordination: pairwise periodic min-image distances under 4.5 Å
    cart = structure.cart_coords
    lat = structure.lattice
    coord = 0.0
    n = structure.num_atoms
    for i in range(n):
        d_frac = (structure.frac_coords - structure.frac_coords[i] + 0.5) % 1.0 - 0.5
        d = np.linalg.norm(d_frac @ lat, axis=1)
        d = d[d > 1e-8]
        coord += float(np.sum(np.exp(-((d / 2.5) ** 2))))
    coord /= n
    target = comp - 0.35 * coord
    if noise and rng is not None:
        target += float(rng.normal(0, noise))
    return target


def synthetic_dataset(
    num_structures: int,
    seed: int = 0,
    noise: float = 0.01,
    min_atoms: int = 2,
    max_atoms: int = 12,
) -> list[tuple[str, Structure, float]]:
    """[(id, Structure, target)] — deterministic given the seed."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(num_structures):
        s = random_structure(rng, min_atoms, max_atoms)
        t = synthetic_target(s, noise, rng)
        out.append((f"synth-{i:06d}", s, t))
    return out
