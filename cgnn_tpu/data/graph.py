"""Graph containers and static-shape batching for TPU.

The reference batches variable-size crystal graphs by concatenation with a
``crystal_atom_idx`` range list and a dense [N, M] neighbor layout
(SURVEY.md §2 components 5-6). TPU/XLA wants static shapes, so this module
uses the idiomatic flat-COO design instead (SURVEY.md §7 phase 2):

- ``CrystalGraph``: one featurized crystal, host-side numpy, flat edge list.
- ``GraphBatch``: many crystals packed into fixed-capacity node/edge/graph
  slots with masks — a jraph-``GraphsTuple``-like pytree (jraph is not
  installed). Padding edges point at the LAST node slot and are masked;
  padding nodes belong to graph slot 0 and are masked.

  Invariant: ``centers`` is non-decreasing — ENFORCED by ``pack_graphs``
  (edges are stable-sorted by center per graph at pack time; node offsets
  grow monotonically across graphs; padding edges target the last slot).
  The jitted aggregation can therefore pass ``indices_are_sorted=True`` to
  XLA's scatter — an unchecked promise on TPU — and skip a device sort
  (ops/segment.py).
- bucketed capacity selection (geometric growth) to bound XLA recompiles
  while keeping padding waste low (SURVEY.md §5 "long-context analog").
"""

from __future__ import annotations

import dataclasses
import math
import warnings
from typing import Any, Sequence

import numpy as np
from flax import struct

from cgnn_tpu.data import invariants


class TransposeOverflowError(ValueError):
    """A batch's two-tier transpose overflow exceeded ``over_cap``.

    ``over_cap`` is sized statistically (``overflow_cap``: mean + 3 sigma
    of shuffle-composition variance), so shuffled runs that repack every
    epoch can hit this on a tail batch deep into a long job.
    ``batch_iterator`` catches THIS TYPE and splits the offending batch
    (same compiled shape); direct ``pack_graphs`` callers see the raise.
    """


@dataclasses.dataclass
class CrystalGraph:
    """One featurized crystal (host-side, numpy)."""

    atom_fea: np.ndarray  # [N, D] float32
    edge_fea: np.ndarray  # [E, G] float32 (Gaussian-expanded distances)
    centers: np.ndarray  # [E] int32 — receiving atom i
    neighbors: np.ndarray  # [E] int32 — source atom j
    target: np.ndarray  # [T] float32
    cif_id: str = ""
    # geometry (kept for the differentiable force path — SURVEY.md §7 phase 7)
    positions: np.ndarray | None = None  # [N, 3] cartesian
    lattice: np.ndarray | None = None  # [3, 3]
    offsets: np.ndarray | None = None  # [E, 3] int32 periodic images
    distances: np.ndarray | None = None  # [E] raw distances
    target_mask: np.ndarray | None = None  # [T] 1.0 where label present
    forces: np.ndarray | None = None  # [N, 3] per-atom force labels (MD17)
    # atomic numbers (kept with geometry): the raw wire format is
    # (positions, lattice, species), so a geometry-carrying graph can be
    # converted back to wire form (data/rawbatch.raw_from_graph)
    numbers: np.ndarray | None = None  # [N] int32

    @property
    def num_nodes(self) -> int:
        return len(self.atom_fea)

    @property
    def num_edges(self) -> int:
        return len(self.centers)


class GraphBatch(struct.PyTreeNode):
    """Fixed-capacity packed batch of graphs (device-side pytree)."""

    nodes: Any  # [Ncap, D] f32
    edges: Any  # [Ecap, G] f32 (COO) / [Ncap, M, G] (dense layout)
    centers: Any  # [Ecap] i32 (receiving node slot)
    neighbors: Any  # [Ecap] i32 (source node slot)
    node_graph: Any  # [Ncap] i32 (graph slot of each node)
    node_mask: Any  # [Ncap] f32 (1 = real)
    edge_mask: Any  # [Ecap] f32
    graph_mask: Any  # [Gcap] f32
    targets: Any  # [Gcap, T] f32
    target_mask: Any  # [Gcap, T] f32 (multi-task missing labels)
    # optional geometry for the force head; zeros when unused
    positions: Any  # [Ncap, 3] f32
    lattices: Any  # [Gcap, 3, 3] f32
    edge_offsets: Any  # [Ecap, 3] f32
    node_targets: Any  # [Ncap, 3] f32 per-atom force labels; zeros when unused
    # transpose of the neighbor gather (dense layout only, else None):
    # row j lists the edge slots e with neighbors[e] == j, so the gather's
    # backward becomes gather(ct, in_slots) + masked sum — a dense reduce —
    # instead of an XLA scatter-add (ops/segment.py gather_transpose)
    in_slots: Any = None  # [Ncap, In] i32 edge-slot indices
    in_mask: Any = None  # [Ncap, In] u8 (1 = real incoming edge)
    # two-tier transpose overflow (pack_graphs over_cap): when in_slots is
    # sized [Ncap, M] (tier 1 = first M incoming edges; mean in-degree == M
    # but max can be ~2M), the ~7% of edges beyond rank M land here as a
    # node-sorted COO list consumed by a small sorted segment-sum in the
    # backward — so tier 1 moves no padding bytes (measured: the [N, 2M]
    # single-tier gather was the largest op of the whole step, half padding)
    over_slots: Any = None  # [Ocap] i32 edge-slot indices
    over_nodes: Any = None  # [Ocap] i32 neighbor node (non-decreasing)
    over_mask: Any = None  # [Ocap] u8

    @property
    def node_capacity(self) -> int:
        return self.nodes.shape[0]

    @property
    def edge_capacity(self) -> int:
        # dense layout stores edges pre-shaped [Ncap, M, G] (the device
        # [E, G] -> [N, M, G] reshape is a measured 0.34 ms/step relayout
        # under the epoch scan); COO keeps the flat [Ecap, G]
        if np.ndim(self.edges) == 3:
            return self.edges.shape[0] * self.edges.shape[1]
        return self.edges.shape[0]

    @property
    def graph_capacity(self) -> int:
        return self.targets.shape[0]

    def num_real_graphs(self) -> Any:
        return self.graph_mask.sum()

    @property
    def flat_edges(self) -> Any:
        """Edge features viewed [Ecap, G] regardless of storage layout —
        the ONE place that knows the dense layout's [Ncap, M, G] shape
        (host-side numpy view; on device this reshape is a relayout)."""
        e = self.edges
        return e.reshape(-1, np.shape(e)[-1]) if np.ndim(e) == 3 else e


def dense_neighbor_views(
    g: CrystalGraph, m: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Flat COO graph -> the lineage's dense per-node neighbor arrays:
    (nbr_fea [N, M, G], nbr_idx [N, M] int64, mask [N, M] f32).

    Padding slots are masked self-loops. This is the ONE definition of the
    dense-slot assignment (k-th edge of center c -> slot (c, k), edges in
    center-sorted order) shared by the torch-oracle parity harness and
    tests — pack_graphs' dense layout uses the same rule batch-wide.
    """
    n = g.num_nodes
    counts = np.bincount(g.centers, minlength=n)
    if counts.max(initial=0) > m:
        raise ValueError(f"a node has {counts.max()} edges > M={m}")
    within = np.arange(g.num_edges) - np.repeat(
        np.cumsum(counts) - counts, counts
    )
    nbr = np.zeros((n, m, g.edge_fea.shape[1]), np.float32)
    idx = np.tile(np.arange(n, dtype=np.int64)[:, None], (1, m))
    mask = np.zeros((n, m), np.float32)
    nbr[g.centers, within] = g.edge_fea
    idx[g.centers, within] = g.neighbors
    mask[g.centers, within] = 1.0
    return nbr, idx, mask


def batch_shape_key(batch) -> tuple:
    """Hashable key identifying a batch's full compiled shape — the ONE
    definition shared by every shape-grouping consumer (ScanEpochDriver,
    parallel_batches); a new shape-bearing GraphBatch field belongs here,
    not in per-caller copies."""
    if hasattr(batch, "atom_idx"):  # CompactBatch (duck-typed: no cycle)
        from cgnn_tpu.data.compact import compact_shape_key

        return compact_shape_key(batch)
    return (
        np.shape(batch.nodes),
        # dtype too: f32 and bf16 edge batches with identical shapes must
        # not be np.stack-ed together (silent upcast + mixed-precision
        # mix). Read the attribute, NOT np.asarray(...): the batch may be
        # device-resident and asarray would fetch the whole tensor.
        np.shape(batch.edges),
        str(batch.edges.dtype),
        None if batch.in_slots is None else np.shape(batch.in_slots),
        None if batch.over_slots is None else np.shape(batch.over_slots),
    )


def max_in_degree(graphs: Sequence[CrystalGraph]) -> int:
    """Largest per-node incoming-edge count over ``graphs`` (memoized).

    In-degree (how many other atoms list atom j among their ``max_num_nbr``
    nearest) is not bounded by ``max_num_nbr``: a central atom in an open
    cell can be "nearest" to many. The transpose-slot capacity must cover
    the observed maximum; compute it once per dataset (results are cached
    on each CrystalGraph) and round up for sublane alignment.
    """
    worst = 0
    for g in graphs:
        d = getattr(g, "_max_in_degree", None)
        if d is None:
            d = (
                int(np.bincount(g.neighbors, minlength=g.num_nodes).max())
                if g.num_edges
                else 0
            )
            g._max_in_degree = d
        worst = max(worst, d)
    return worst


def in_degree_cap(graphs: Sequence[CrystalGraph]) -> int:
    """Transpose-slot capacity for a dataset: max in-degree, 8-aligned."""
    return max(8, -(-max_in_degree(graphs) // 8) * 8)


def overflow_cap(
    graphs: Sequence[CrystalGraph], graph_cap: int, dense_m: int
) -> int:
    """Static capacity for the two-tier transpose overflow list.

    Overflow per graph = sum over nodes of max(in_degree - M, 0), cached
    per graph. A batch of up to ``graph_cap`` graphs needs about
    graph_cap * mean; 3 sigma * sqrt(graph_cap) covers shuffle composition
    variance and the per-graph max guards small batches. Exceeding this at
    pack time raises loudly (pack_graphs), never truncates.
    """
    per_graph = []
    for g in graphs:
        o = getattr(g, "_overflow_" + str(dense_m), None)
        if o is None:
            o = (
                int(
                    np.maximum(
                        np.bincount(g.neighbors, minlength=g.num_nodes)
                        - dense_m,
                        0,
                    ).sum()
                )
                if g.num_edges
                else 0
            )
            setattr(g, "_overflow_" + str(dense_m), o)
        per_graph.append(o)
    per_graph = np.asarray(per_graph, np.float64)
    need = graph_cap * per_graph.mean() + 3.0 * per_graph.std() * np.sqrt(
        graph_cap
    )
    return _align8(int(max(need, per_graph.max(), 8)))


def round_to_bucket(n: int, minimum: int = 64, growth: float = 1.3) -> int:
    """Smallest capacity in the geometric bucket ladder that fits ``n``.

    Geometric buckets bound the number of distinct compiled shapes to
    O(log(max/min) / log(growth)) while wasting at most (growth-1) padding.
    """
    if n <= minimum:
        return minimum
    steps = math.ceil(math.log(n / minimum) / math.log(growth))
    return int(math.ceil(minimum * growth**steps))


def pack_graphs(
    graphs: Sequence[CrystalGraph],
    node_cap: int,
    edge_cap: int,
    graph_cap: int,
    num_targets: int | None = None,
    dense_m: int | None = None,
    in_cap: int | None = None,
    over_cap: int | None = None,
    edge_dtype=np.float32,
    transpose_shards: int = 1,
) -> GraphBatch:
    """Concatenate graphs into one fixed-capacity GraphBatch (numpy).

    ``dense_m=M`` activates the DENSE SLOT layout: node slot ``n`` owns edge
    slots ``[n*M, (n+1)*M)`` (its real edges first, masked padding after),
    requiring ``edge_cap == node_cap * M``. Every flat-COO invariant still
    holds (centers non-decreasing, masks zero on padding), so all existing
    consumers work unchanged — but a model built with ``dense_m=M`` can
    reshape the edge axis to [N, M] and aggregate messages with a plain
    sum over M instead of a segment-sum: on TPU the XLA scatter behind
    segment ops runs ~50x below HBM bandwidth, while a dense reduction is
    a fused full-speed reduce, and the per-edge v_i gather becomes a
    broadcast (measured: see models/cgcnn.py).

    ``in_cap`` (dense layout only) additionally fills ``in_slots``/
    ``in_mask`` — the transpose of the neighbor gather, sized for a maximum
    per-node in-degree of ``in_cap`` (see ``in_degree_cap``) — making the
    gather's *backward* scatter-free too (ops/segment.py gather_transpose).

    ``over_cap`` selects the TWO-TIER transpose instead (exclusive with
    ``in_cap``): tier 1 is ``in_slots`` at width ``dense_m`` (each node's
    first M incoming edges — zero padding bytes at mean in-degree M), and
    the ~7% of edges with within-neighbor rank >= M go to the node-sorted
    ``over_slots``/``over_nodes`` COO overflow (capacity ``over_cap``, see
    ``overflow_cap``; overflowing it raises, never truncates).

    ``transpose_shards > 1`` (two-tier only) builds the PER-SHARD stacked
    mappings for node-strip graph sharding directly
    (``shard_transpose_slots``) instead of the flat global mapping —
    avoiding a pack-then-rebuild on the host critical path. A per-shard
    overflow exceeding ``over_cap`` raises exactly like the global build
    (a shard's overflow is never larger than the batch's would-be global
    overflow, so this is at most as strict).
    """
    if not graphs:
        raise ValueError("cannot pack an empty graph list")
    if dense_m is not None and edge_cap != node_cap * dense_m:
        raise ValueError(
            f"dense layout requires edge_cap == node_cap * dense_m "
            f"({node_cap} * {dense_m} != {edge_cap})"
        )
    n_graphs = len(graphs)
    total_nodes = sum(g.num_nodes for g in graphs)
    total_edges = sum(g.num_edges for g in graphs)
    if n_graphs > graph_cap or total_nodes > node_cap or (
        dense_m is None and total_edges > edge_cap
    ):
        raise ValueError(
            f"batch ({n_graphs} graphs, {total_nodes} nodes, {total_edges} edges)"
            f" exceeds capacity ({graph_cap}, {node_cap}, {edge_cap})"
        )
    node_dim = graphs[0].atom_fea.shape[1]
    edge_dim = graphs[0].edge_fea.shape[1]
    tdim = num_targets or int(np.atleast_1d(graphs[0].target).shape[0])

    nodes = np.zeros((node_cap, node_dim), np.float32)
    # edge features are the largest staged tensor (G floats/edge); bf16
    # storage (train.py --bf16, bench) halves their HBM footprint and
    # per-step read bytes — the model casts to its compute dtype anyway
    edges = np.zeros((edge_cap, edge_dim), edge_dtype)
    if dense_m is None:
        # padding edges point at the last node slot: keeps `centers` sorted
        # (see module docstring) and their masked zero messages harmless
        centers = np.full(edge_cap, node_cap - 1, np.int32)
        neighbors = np.full(edge_cap, node_cap - 1, np.int32)
    else:
        # dense layout: slot k belongs to node k // M; padding slots are
        # masked self-loops on their owning node (sortedness preserved)
        centers = (np.arange(edge_cap, dtype=np.int32) // dense_m).astype(
            np.int32
        )
        neighbors = centers.copy()
    node_graph = np.zeros(node_cap, np.int32)
    node_mask = np.zeros(node_cap, np.float32)
    edge_mask = np.zeros(edge_cap, np.float32)
    graph_mask = np.zeros(graph_cap, np.float32)
    targets = np.zeros((graph_cap, tdim), np.float32)
    target_mask = np.zeros((graph_cap, tdim), np.float32)
    positions = np.zeros((node_cap, 3), np.float32)
    lattices = np.zeros((graph_cap, 3, 3), np.float32)
    edge_offsets = np.zeros((edge_cap, 3), np.float32)
    node_targets = np.zeros((node_cap, 3), np.float32)

    # ---- vectorized packing: one pass of concatenated arrays per field.
    # The per-graph Python loop this replaces was the last major
    # single-core host stage at MP-146k scale (84 s of a 656 s first
    # epoch: ~30 small numpy calls x 131k graphs); concatenation turns it
    # into ~15 C-level ops per batch regardless of graph count.
    nn_arr = np.fromiter((g.num_nodes for g in graphs), np.int64, n_graphs)
    ne_arr = np.fromiter((g.num_edges for g in graphs), np.int64, n_graphs)
    node_offs = np.zeros(n_graphs + 1, np.int64)
    np.cumsum(nn_arr, out=node_offs[1:])
    edge_offs = np.zeros(n_graphs + 1, np.int64)
    np.cumsum(ne_arr, out=edge_offs[1:])

    np.concatenate([g.atom_fea for g in graphs], axis=0,
                   out=nodes[:total_nodes])
    node_graph[:total_nodes] = np.repeat(
        np.arange(n_graphs, dtype=np.int32), nn_arr
    )
    node_mask[:total_nodes] = 1.0

    # global centers with node offsets applied: per-graph value ranges are
    # disjoint and increasing, so the batch vector is non-decreasing IFF
    # every graph is center-sorted, and ONE global stable argsort restores
    # per-graph center order without mixing graphs
    e_node_off = np.repeat(node_offs[:-1], ne_arr)
    gcent = np.concatenate([g.centers for g in graphs]).astype(np.int64)
    gcent += e_node_off
    gnbr = np.concatenate([g.neighbors for g in graphs]).astype(np.int64)
    gnbr += e_node_off
    if np.all(gcent[1:] >= gcent[:-1]):
        order = None  # knn_neighbor_list output is already center-sorted
    else:
        order = np.argsort(gcent, kind="stable")
        gcent, gnbr = gcent[order], gnbr[order]
    efea = np.concatenate([g.edge_fea for g in graphs], axis=0)
    if order is not None:
        efea = efea[order]

    if dense_m is None:
        slots = slice(0, total_edges)
        edges[slots] = efea
        edge_mask[slots] = 1.0
    else:
        counts = np.bincount(gcent, minlength=node_cap)
        worst = int(counts.max(initial=0))
        if worst > dense_m:
            bad = int(np.argmax(counts))
            gi = int(np.searchsorted(node_offs, bad, side="right")) - 1
            raise ValueError(
                f"graph {graphs[gi].cif_id!r} has a node with {worst} "
                f"edges > dense_m={dense_m}; featurize with "
                f"max_num_nbr <= dense_m"
            )
        # edge k's within-center rank: its position minus its center's
        # first position in the center-sorted edge ordering
        within = np.arange(total_edges) - (np.cumsum(counts) - counts)[gcent]
        slots = gcent * dense_m + within
        # fill the [node_cap * M] slot grid by GATHER, not scatter: slot
        # (n, k) takes sorted edge starts[n] + k when k < counts[n], else
        # a sentinel zero row — a row-scatter at these sizes ran ~4x
        # slower than take() and needed a separate edge_mask scatter
        starts = np.cumsum(counts) - counts
        src = starts[:, None] + np.arange(dense_m)
        grid_valid = np.arange(dense_m) < counts[:, None]
        np.copyto(src, total_edges, where=~grid_valid)
        efea_pad = np.empty((total_edges + 1, edge_dim), edge_dtype)
        efea_pad[:total_edges] = efea  # casts to edge_dtype in one pass
        efea_pad[total_edges] = 0.0  # sentinel zero row for padding slots
        np.take(efea_pad, src.ravel(), axis=0, out=edges, mode="clip")
        edge_mask[:] = grid_valid.ravel()
    if dense_m is None:
        centers[slots] = gcent.astype(np.int32)
    # (dense: real slot s has centers[s] == s // M by construction — the
    # arange//M initialization already equals the scatter)
    neighbors[slots] = gnbr.astype(np.int32)

    graph_mask[:n_graphs] = 1.0
    tgt = [np.atleast_1d(np.asarray(g.target, np.float32)) for g in graphs]
    if all(len(t) == len(tgt[0]) for t in tgt):
        tw = len(tgt[0])
        targets[:n_graphs, :tw] = np.stack(tgt)
        masks = [g.target_mask for g in graphs]
        if all(m is None for m in masks):
            target_mask[:n_graphs, :tw] = 1.0
        else:
            # broadcast_to: a narrower mask (e.g. a scalar ones(1) on a
            # width-3 target) broadcasts across the width, matching the
            # old per-graph `target_mask[gi, :tw] = mask` assignment
            target_mask[:n_graphs, :tw] = np.stack([
                np.ones(tw, np.float32) if m is None
                else np.broadcast_to(np.atleast_1d(m), (tw,))
                for m in masks
            ])
    else:  # ragged target widths (unusual): per-graph fallback
        for gi, (g, t) in enumerate(zip(graphs, tgt)):
            targets[gi, : len(t)] = t
            if g.target_mask is not None:
                target_mask[gi, : len(t)] = np.atleast_1d(g.target_mask)
            else:
                target_mask[gi, : len(t)] = 1.0

    def _per_graph_edge_slots(gi: int):
        # the global sort keeps graphs contiguous (disjoint gcent ranges),
        # so graph gi's edges occupy the same [edge_offs] range after it
        s = slice(edge_offs[gi], edge_offs[gi + 1])
        return slots[s] if dense_m is not None else s

    have_pos = [g.positions is not None for g in graphs]
    if all(have_pos):
        np.concatenate([g.positions for g in graphs], axis=0,
                       out=positions[:total_nodes])
    elif any(have_pos):
        for gi, g in enumerate(graphs):
            if g.positions is not None:
                positions[node_offs[gi] : node_offs[gi + 1]] = g.positions
    have_lat = [g.lattice is not None for g in graphs]
    if all(have_lat):
        lattices[:n_graphs] = np.stack([g.lattice for g in graphs])
    elif any(have_lat):
        for gi, g in enumerate(graphs):
            if g.lattice is not None:
                lattices[gi] = g.lattice
    have_off = [g.offsets is not None for g in graphs]
    if all(have_off) and total_edges:
        goff = np.concatenate([g.offsets for g in graphs], axis=0)
        edge_offsets[slots] = goff if order is None else goff[order]
    elif any(have_off):
        for gi, g in enumerate(graphs):
            if g.offsets is not None and g.num_edges:
                o = g.offsets
                if order is not None:
                    # recover this graph's local order from the global sort
                    lo = np.argsort(g.centers, kind="stable")
                    o = o[lo]
                edge_offsets[_per_graph_edge_slots(gi)] = o
    have_f = [g.forces is not None for g in graphs]
    if all(have_f):
        np.concatenate([g.forces for g in graphs], axis=0,
                       out=node_targets[:total_nodes])
    elif any(have_f):
        for gi, g in enumerate(graphs):
            if g.forces is not None:
                node_targets[node_offs[gi] : node_offs[gi + 1]] = g.forces

    in_slots = in_mask = None
    over_slots = over_nodes = over_mask = None
    if in_cap is not None and over_cap is not None:
        raise ValueError("in_cap (single-tier) and over_cap (two-tier) are "
                         "mutually exclusive")
    if in_cap is not None or over_cap is not None:
        if dense_m is None:
            raise ValueError("transpose slots require the dense layout "
                             "(dense_m)")
        if transpose_shards > 1:
            if over_cap is None:
                raise ValueError(
                    "transpose_shards requires the two-tier layout "
                    "(over_cap; in_cap single-tier mappings cannot shard)"
                )
            in_slots, in_mask, over_slots, over_nodes, over_mask = (
                shard_transpose_slots(
                    neighbors, edge_mask > 0, node_cap, dense_m,
                    transpose_shards, over_cap,
                )
            )
        else:
            in_slots, in_mask, over_slots, over_nodes, over_mask = (
                transpose_slots(
                    neighbors, edge_mask > 0, node_cap, dense_m, in_cap,
                    over_cap,
                )
            )

    return GraphBatch(
        nodes=nodes,
        edges=(edges.reshape(node_cap, dense_m, edge_dim)
               if dense_m is not None else edges),
        centers=centers,
        neighbors=neighbors,
        node_graph=node_graph,
        node_mask=node_mask,
        edge_mask=edge_mask,
        graph_mask=graph_mask,
        targets=targets,
        target_mask=target_mask,
        positions=positions,
        lattices=lattices,
        edge_offsets=edge_offsets,
        node_targets=node_targets,
        in_slots=in_slots,
        in_mask=in_mask,
        over_slots=over_slots,
        over_nodes=over_nodes,
        over_mask=over_mask,
    )


def transpose_slots(
    neighbors: np.ndarray,
    edge_real: np.ndarray,
    node_cap: int,
    dense_m: int,
    in_cap: int | None,
    over_cap: int | None,
) -> tuple:
    """Transpose of the neighbor gather: group real edge slots by their
    neighbor node (the scatter-free-backward mapping; see pack_graphs).

    ``neighbors`` [Ecap] i32, ``edge_real`` [Ecap] bool. Returns
    ``(in_slots, in_mask, over_slots, over_nodes, over_mask)`` — the last
    three ``None`` unless ``over_cap`` selects the two-tier layout.
    Stable-sorting by neighbor + a cumcount gives each real edge its
    row-local position; padding entries stay masked at slot 0.
    Shared by ``pack_graphs`` and the compact-staging packer
    (data/compact.py), which must agree exactly.
    """
    real = np.nonzero(edge_real)[0]
    nb = neighbors[real]
    counts = np.bincount(nb, minlength=node_cap)
    order = np.argsort(nb, kind="stable")
    tier = dense_m if over_cap is not None else in_cap
    if over_cap is None and len(real) and counts.max() > tier:
        raise ValueError(
            f"a node has in-degree {counts.max()} > in_cap={in_cap}; "
            f"size in_cap with in_degree_cap(graphs)"
        )
    # fill by gather (same pattern as the dense edge grid in pack_graphs):
    # row j's k-th incoming edge is the neighbor-sorted edge at
    # starts[j] + k when k < in-degree, else the sentinel zero
    real_sorted = real[order].astype(np.int32)
    starts = np.cumsum(counts) - counts
    src = starts[:, None] + np.arange(tier)
    tier_valid = np.arange(tier) < counts[:, None]
    np.copyto(src, len(real), where=~tier_valid)
    pad = np.concatenate([real_sorted, np.zeros(1, np.int32)])
    # stored FLAT [node_cap * tier]: the backward's gather wants flat
    # indices, and flattening the 2-D array on DEVICE costs a tiled->
    # linear relayout measured at 0.75 ms/step under the epoch scan
    # (s32 [1, N, In] slice -> [N*In]); in_mask keeps the 2-D shape
    # for the masked in-degree reduction. uint8 mask: it is only ever
    # cast to the compute dtype on device, and at MP-146k scale a f32
    # mask would stage ~0.5 GB of HBM
    in_slots = np.take(pad, src.ravel(), mode="clip")
    in_mask = tier_valid.astype(np.uint8)
    over_slots = over_nodes = over_mask = None
    if over_cap is not None:
        # edges with within-neighbor rank >= tier, in sorted positions
        sel2 = np.arange(len(real)) - starts.repeat(counts) >= tier
        k = int(sel2.sum())
        if k > over_cap:
            raise TransposeOverflowError(
                f"batch has {k} transpose-overflow edges > over_cap="
                f"{over_cap}; size over_cap with overflow_cap(graphs)"
            )
        # padding targets the LAST node slot so over_nodes stays
        # non-decreasing (the sorted-scatter promise; masked zero rows)
        over_slots = np.zeros(over_cap, np.int32)
        over_nodes = np.full(over_cap, node_cap - 1, np.int32)
        over_mask = np.zeros(over_cap, np.uint8)
        over_slots[:k] = real_sorted[sel2]
        over_nodes[:k] = nb[order][sel2]
        over_mask[:k] = 1
    return in_slots, in_mask, over_slots, over_nodes, over_mask


def shard_transpose_slots(
    neighbors: np.ndarray,
    edge_real: np.ndarray,
    node_cap: int,
    dense_m: int,
    n_shards: int,
    over_cap: int,
) -> tuple:
    """Per-shard two-tier transpose mappings for node-strip graph sharding.

    Under dense-layout graph parallelism (parallel/edge_parallel.py), shard
    ``s`` owns the contiguous node strip ``[s*N/D, (s+1)*N/D)`` and — by the
    dense layout's slot-ownership rule — exactly that strip's edge slots.
    The scatter-free backward then needs, PER SHARD, the edge slots in that
    shard grouped by neighbor node (over ALL nodes: a strip's edges point
    anywhere): each shard transposes its own [E/D, F] cotangent into a
    partial [N, F] node gradient, and the shard_map machinery sums the
    partials (the transpose of the replicated-nodes cast).

    Tier-1 width stays ``dense_m`` and the overflow capacity stays the
    batch-global ``over_cap``: an edge's within-neighbor rank restricted to
    one shard never exceeds its global rank, so every (tier, overflow)
    bound that held for the unsharded mapping holds per shard — sharding
    introduces NO new overflow failure mode, and the per-shard shapes are
    static functions of (node_cap, dense_m, n_shards) only.

    Returns stacked arrays with a leading shard axis, slot indices LOCAL to
    each shard's edge range: ``in_slots [D, node_cap*dense_m]``,
    ``in_mask [D, node_cap, dense_m]``, ``over_slots/over_nodes/over_mask
    [D, over_cap]``.
    """
    # the REAL precondition: shard boundaries must fall on whole node
    # rows. Checking only edge-capacity divisibility let configs with
    # dense_m % n_shards == 0 but node_cap % n_shards != 0 through (e.g.
    # node_cap=6, dense_m=8, n_shards=4), cutting strips mid node-row and
    # surfacing much later as an opaque shard_map/device_put error
    # (ADVICE r5). node_cap divisibility implies edge divisibility for
    # the dense layout (e_cap = node_cap * dense_m).
    if node_cap % n_shards:
        raise ValueError(
            f"node_cap {node_cap} not divisible by {n_shards} shards "
            f"(node-strip sharding owns whole node rows; round node_cap "
            f"up to a multiple of the shard count)"
        )
    e_cap = len(neighbors)
    if e_cap % n_shards:
        raise ValueError(
            f"edge capacity {e_cap} not divisible by {n_shards} shards "
            f"(expected node_cap * dense_m with node_cap a multiple of "
            f"the shard count)"
        )
    e_s = e_cap // n_shards
    parts = [
        transpose_slots(
            neighbors[s * e_s : (s + 1) * e_s],
            edge_real[s * e_s : (s + 1) * e_s],
            node_cap, dense_m, None, over_cap,
        )
        for s in range(n_shards)
    ]
    return tuple(np.stack([p[i] for p in parts]) for i in range(5))


def pad_batch(
    graphs: Sequence[CrystalGraph],
    graph_cap: int,
    bucket_min_nodes: int = 64,
    bucket_min_edges: int = 512,
    growth: float = 1.3,
) -> GraphBatch:
    """Pack with bucketed node/edge capacities chosen from the batch content."""
    node_cap = round_to_bucket(
        sum(g.num_nodes for g in graphs), bucket_min_nodes, growth
    )
    edge_cap = round_to_bucket(
        sum(g.num_edges for g in graphs), bucket_min_edges, growth
    )
    return pack_graphs(graphs, node_cap, edge_cap, graph_cap)


def capacities_for(
    graphs: Sequence[CrystalGraph],
    batch_size: int,
    headroom: float = 1.15,
    dense_m: int | None = None,
    snug: bool = False,
    node_multiple: int = 1,
) -> tuple[int, int]:
    """Pick one (node_cap, edge_cap) for a dataset so every shuffled batch
    fits: batch_size * max-per-graph sizes would be safe but wasteful; use
    mean + headroom over the largest observed, bucketed. Fine ladder floors
    (16/128) keep small-graph buckets tight — a 64-node floor would cap
    padding efficiency at ~60% for 8x5-atom batches.

    ``snug=True`` returns exact 8-aligned capacities at ``batch_size *
    mean`` with NO headroom and NO ladder rounding — for the
    fill-to-capacity packing mode (``batch_iterator(snug=True)``), where
    batches close on capacity rather than on graph count, so headroom
    would only manufacture padding. The number of compiled shapes is
    unchanged (one per call / per bucket); only cross-dataset shape reuse
    is given up. Measured on the MP-like distribution this lifts padding
    efficiency from ~0.69 (1 / (1.15 headroom x ~1.3 ladder step)) to
    >=0.97.

    With ``dense_m`` the edge capacity is exactly ``node_cap * dense_m``
    (the dense slot layout, pack_graphs).

    ``node_multiple`` rounds the node capacity up to a multiple (node-strip
    graph sharding needs ``node_cap`` divisible by the shard count so every
    shard owns a whole strip; parallel/edge_parallel.py)."""
    if node_multiple > 1:
        def _round_caps(nc, ec):
            nc2 = -(-nc // node_multiple) * node_multiple
            if dense_m is not None:
                return nc2, nc2 * dense_m
            return nc2, ec
        nc, ec = capacities_for(graphs, batch_size, headroom,
                                dense_m=dense_m, snug=snug)
        return _round_caps(nc, ec)
    nodes = np.array([g.num_nodes for g in graphs])
    if snug:
        # balance capacity to the BATCH COUNT: with B = ceil(n/batch_size)
        # batches, the best possible efficiency is total/(B*cap), so size
        # cap at total/B plus a packing margin (greedy fill wastes ~mean/2
        # per batch; mean+std covers it with room for shuffle variance)
        # instead of batch_size*mean — otherwise the last batch per epoch
        # is fractionally full and costs ~1/(2B) efficiency by itself.
        b_count = max(1, math.ceil(len(graphs) / batch_size))
        margin = nodes.mean() + nodes.std()
        node_cap = _align8(
            int(max(nodes.sum() / b_count + margin, nodes.max()))
        )
        if dense_m is not None:
            return node_cap, node_cap * dense_m
        edges = np.array([g.num_edges for g in graphs])
        margin_e = edges.mean() + edges.std()
        edge_cap = _align8(
            int(max(edges.sum() / b_count + margin_e, edges.max()))
        )
        return node_cap, edge_cap
    node_cap = round_to_bucket(
        int(max(batch_size * nodes.mean() * headroom, nodes.max())), minimum=16
    )
    if dense_m is not None:
        return node_cap, node_cap * dense_m
    edges = np.array([g.num_edges for g in graphs])
    edge_cap = round_to_bucket(
        int(max(batch_size * edges.mean() * headroom, edges.max())), minimum=128
    )
    return node_cap, edge_cap


def _align8(n: int) -> int:
    """Round up to a multiple of 8 (TPU sublane alignment)."""
    return max(8, -(-n // 8) * 8)


def graph_cap_for(batch_size: int) -> int:
    """Graph-slot capacity for fill-to-capacity packing: ``batch_size``
    plus ~12% slack (8-aligned) so node/edge capacity — not the graph
    count — is what closes a typical batch. Graph slots are cheap
    ([G, T] targets + [G, 3, 3] lattices); node/edge slots are not."""
    return batch_size + _align8(max(8, batch_size // 8))


@dataclasses.dataclass
class PaddingStats:
    """Accumulates padding efficiency over an epoch of packed batches.

    Efficiency = real slots / allocated slots; the figure the bucketing
    policy optimizes (SURVEY.md §5 long-context analog, §7 hard parts #1).
    """

    real_nodes: int = 0
    real_edges: int = 0
    slot_nodes: int = 0
    slot_edges: int = 0
    batches: int = 0
    shapes: set = dataclasses.field(default_factory=set)
    # per compiled (node_cap, edge_cap) shape: [real_nodes, real_edges,
    # slot_nodes, slot_edges, batches] — the per-bucket breakdown the
    # telemetry gauges report (observe.gauges.padding_gauges)
    per_shape: dict = dataclasses.field(default_factory=dict)

    def update(self, batch: GraphBatch) -> None:
        real_n = int(np.asarray(batch.node_mask).sum())
        real_e = int(np.asarray(batch.edge_mask).sum())
        self.real_nodes += real_n
        self.real_edges += real_e
        self.slot_nodes += batch.node_capacity
        self.slot_edges += batch.edge_capacity
        self.batches += 1
        shape = (batch.node_capacity, batch.edge_capacity)
        self.shapes.add(shape)
        acc = self.per_shape.setdefault(shape, [0, 0, 0, 0, 0])
        acc[0] += real_n
        acc[1] += real_e
        acc[2] += batch.node_capacity
        acc[3] += batch.edge_capacity
        acc[4] += 1

    @property
    def node_efficiency(self) -> float:
        return self.real_nodes / max(self.slot_nodes, 1)

    @property
    def edge_efficiency(self) -> float:
        return self.real_edges / max(self.slot_edges, 1)

    def wrap(self, iterator):
        """Pass batches through while accumulating stats."""
        for b in iterator:
            self.update(b)
            yield b

    def summary(self) -> str:
        return (
            f"padding efficiency: nodes {self.node_efficiency:.1%}, "
            f"edges {self.edge_efficiency:.1%} over {self.batches} batches, "
            f"{len(self.shapes)} compiled shape(s)"
        )


def assign_size_buckets(
    graphs: Sequence[CrystalGraph], n_buckets: int
) -> np.ndarray:
    """Bucket index per graph by node-count quantiles ([len(graphs)] int)."""
    sizes = np.array([g.num_nodes for g in graphs])
    if n_buckets <= 1:
        return np.zeros(len(graphs), np.int64)
    cuts = np.quantile(sizes, np.linspace(0, 1, n_buckets + 1)[1:-1])
    return np.searchsorted(cuts, sizes, side="left")


def bucketed_batch_iterator(
    graphs: Sequence[CrystalGraph],
    batch_size: int,
    n_buckets: int,
    shuffle: bool = False,
    rng: np.random.Generator | None = None,
    stats: PaddingStats | None = None,
    headroom: float = 1.15,
    dense_m: int | None = None,
    in_cap: int | None = None,
    snug: bool = False,
    per_bucket_in_cap: bool = False,
    edge_dtype=np.float32,
    pack_fn=None,
    node_multiple: int = 1,
    transpose_shards: int = 1,
):
    """Yield batches using per-size-class static capacities.

    Graphs are partitioned into ``n_buckets`` size classes (node-count
    quantiles); each class batches with its own (node_cap, edge_cap), so the
    jitted step compiles at most ``n_buckets`` distinct shapes while padding
    tracks each class's actual size distribution — the multi-bucket
    "long-context" policy for mixed MP+OC20 datasets (SURVEY.md §5).
    Batches from different classes interleave (weighted random under
    ``shuffle``) to avoid size-ordered epochs.

    ``snug`` selects fill-to-capacity packing per bucket (see
    ``batch_iterator``). ``per_bucket_in_cap`` sizes the transpose-slot
    capacity from each bucket's own worst in-degree instead of the
    dataset-wide maximum — one skewed graph (an adsorbate nearest to dozens
    of slab atoms, the OC20 geometry) then inflates only its own bucket's
    ``in_slots`` bytes, at the cost of no extra compiles (bucket shapes
    already differ).
    """
    rng = rng or np.random.default_rng()
    bucket_of = assign_size_buckets(graphs, n_buckets)
    # transpose slots default to the two-tier layout with ONE dataset-wide
    # overflow capacity: per-bucket over_caps would split otherwise-equal
    # bucket shapes into distinct compiled shapes (and strand DP device
    # groups — two buckets of small graphs often share (node_cap, edge_cap)
    # after alignment). per_bucket_in_cap forces legacy single-tier slots
    # sized by each bucket's own worst in-degree.
    over_cap = None
    if dense_m is not None and in_cap is None and not per_bucket_in_cap:
        # one uniform capacity sized by the WORST bucket: a large-graph
        # bucket's batches carry far more overflow than the dataset mean
        # (bimodal mixes), and per-bucket caps would split otherwise-equal
        # bucket shapes; the waste is a few KB of i32 per batch
        gcap = graph_cap_for(batch_size) if snug else batch_size
        over_cap = max(
            overflow_cap(
                [graphs[int(i)] for i in np.nonzero(bucket_of == b)[0]],
                gcap, dense_m,
            )
            for b in range(int(bucket_of.max()) + 1)
            if np.any(bucket_of == b)
        )
    iters, weights = [], []
    for b in range(int(bucket_of.max()) + 1):
        idxs = np.nonzero(bucket_of == b)[0]
        if len(idxs) == 0:
            continue
        sub = [graphs[int(i)] for i in idxs]
        nc, ec = capacities_for(sub, batch_size, headroom, dense_m=dense_m,
                                snug=snug, node_multiple=node_multiple)
        b_in_cap = in_cap
        if dense_m is not None and b_in_cap is None and per_bucket_in_cap:
            b_in_cap = in_degree_cap(sub)
        it = batch_iterator(sub, batch_size, nc, ec, shuffle=shuffle, rng=rng,
                            dense_m=dense_m, in_cap=b_in_cap, snug=snug,
                            over_cap=over_cap, edge_dtype=edge_dtype,
                            pack_fn=pack_fn,
                            transpose_shards=transpose_shards)
        iters.append(stats.wrap(it) if stats is not None else it)
        weights.append(float(len(idxs)))
    active = list(range(len(iters)))
    w = np.array(weights)
    while active:
        if shuffle and len(active) > 1:
            p = w[active] / w[active].sum()
            pick = int(rng.choice(active, p=p))
        else:
            pick = active[0]
        try:
            yield next(iters[pick])
        except StopIteration:
            active.remove(pick)


def plan_batches(
    graphs: Sequence[CrystalGraph],
    batch_size: int,
    node_cap: int,
    edge_cap: int,
    snug: bool = False,
):
    """Yield ``(start, end)`` index spans over ``graphs`` matching
    ``batch_iterator``'s greedy close condition EXACTLY (no shuffle),
    without packing anything.

    This is the planning half of the parallel ingest pipeline
    (data/pipeline.py): the plan is computed once on the consumer,
    cheap and deterministic, and the spans are handed to a pool of
    packer workers — input order is preserved by construction, so the
    reassembled batches map back to the input the same way the serial
    ``batch_iterator`` loop's would. Oversize graphs raise the same
    error ``batch_iterator`` raises (a plan that silently diverged from
    the packer would break span bookkeeping downstream).
    """
    graph_cap = graph_cap_for(batch_size) if snug else batch_size
    start, nn, ne = 0, 0, 0
    for i, g in enumerate(graphs):
        if g.num_nodes > node_cap or g.num_edges > edge_cap:
            raise ValueError(
                f"graph {g.cif_id!r} ({g.num_nodes} nodes, {g.num_edges} "
                f"edges) exceeds batch capacity ({node_cap}, {edge_cap}); "
                f"increase caps or filter the dataset"
            )
        if i > start and (
            i - start == graph_cap
            or nn + g.num_nodes > node_cap
            or ne + g.num_edges > edge_cap
        ):
            yield start, i
            start, nn, ne = i, 0, 0
        nn += g.num_nodes
        ne += g.num_edges
    if start < len(graphs):
        yield start, len(graphs)


def count_batches(
    graphs: Sequence[CrystalGraph],
    batch_size: int,
    node_cap: int,
    edge_cap: int,
    snug: bool = False,
) -> int:
    """Exact number of batches ``batch_iterator`` yields, without packing.

    ``len(graphs) // batch_size`` undercounts because capacity-filled
    batches split early; LR-milestone step conversion needs the real count.
    Must mirror ``batch_iterator``'s close condition exactly (incl. the
    ``snug`` graph-cap slack).
    """
    graph_cap = graph_cap_for(batch_size) if snug else batch_size
    count, in_bucket, nn, ne = 0, 0, 0, 0
    for g in graphs:
        if in_bucket and (
            in_bucket == graph_cap
            or nn + g.num_nodes > node_cap
            or ne + g.num_edges > edge_cap
        ):
            count += 1
            in_bucket, nn, ne = 0, 0, 0
        in_bucket += 1
        nn += g.num_nodes
        ne += g.num_edges
    return count + (1 if in_bucket else 0)


def _pack_overflow_safe(
    bucket: list,
    node_cap: int,
    edge_cap: int,
    graph_cap: int,
    dense_m,
    in_cap,
    over_cap,
    edge_dtype,
    pack_fn=None,
    transpose_shards: int = 1,
):
    """pack_graphs, splitting the batch on a two-tier over_cap overrun.

    ``over_cap`` covers mean + 3 sigma of shuffle-composition variance
    (``overflow_cap``), so a tail composition can exceed it after many
    successful epochs. Splitting the offending batch in half re-packs each
    half to the SAME compiled shape (capacities unchanged) — one extra
    partially-filled batch instead of a dead run. A single graph that
    exceeds ``over_cap`` on its own cannot be split and re-raises (it
    indicates over_cap was sized from different graphs than are being
    packed).
    """
    pack = pack_fn or pack_graphs
    kw = {"transpose_shards": transpose_shards} if transpose_shards > 1 \
        else {}
    try:
        yield pack(bucket, node_cap, edge_cap, graph_cap,
                   dense_m=dense_m, in_cap=in_cap, over_cap=over_cap,
                   edge_dtype=edge_dtype, **kw)
    except TransposeOverflowError:
        if len(bucket) < 2:
            raise
        warnings.warn(
            f"batch of {len(bucket)} graphs exceeded over_cap={over_cap} "
            f"(a 3-sigma shuffle tail); splitting it in half instead of "
            f"aborting the run",
            stacklevel=2,
        )
        mid = len(bucket) // 2
        for half in (bucket[:mid], bucket[mid:]):
            yield from _pack_overflow_safe(
                half, node_cap, edge_cap, graph_cap, dense_m, in_cap,
                over_cap, edge_dtype, pack_fn=pack_fn,
                transpose_shards=transpose_shards)


def batch_iterator(
    graphs: Sequence[CrystalGraph],
    batch_size: int,
    node_cap: int,
    edge_cap: int,
    shuffle: bool = False,
    rng: np.random.Generator | None = None,
    drop_last: bool = False,
    dense_m: int | None = None,
    in_cap: int | None = None,
    snug: bool = False,
    over_cap: int | None = None,
    edge_dtype=np.float32,
    pack_fn=None,
    transpose_shards: int = 1,
):
    """Yield fixed-shape GraphBatches of ``batch_size`` graphs each.

    All batches share one (node_cap, edge_cap, graph_cap) shape so the jitted
    train step compiles exactly once. Oversize batches (rare tail events) are
    split greedily rather than dropped. ``dense_m`` selects the dense slot
    layout (see pack_graphs); transpose slots are sized automatically
    (``in_degree_cap``) unless ``in_cap`` is given.

    ``snug=True`` switches to FILL-TO-CAPACITY packing: a batch closes when
    the next graph would overflow node/edge capacity (use with the snug
    capacities from ``capacities_for(snug=True)``), not when it holds
    ``batch_size`` graphs; graph slots get ~12% slack (``graph_cap_for``)
    so capacity is the binding constraint. Padding efficiency becomes
    1 - O(mean_graph / 2 / cap) per batch instead of 1/(headroom x ladder
    step) — measured 0.69 -> >=0.97 on the MP-like distribution.

    Transpose slots (dense layout): ``in_cap=None`` (default) packs the
    TWO-TIER transpose — tier-1 width ``dense_m`` + overflow COO sized by
    ``overflow_cap`` — for the scatter-free backward with no in-degree
    padding bytes; ``in_cap>0`` forces the legacy single-tier layout;
    ``in_cap=0`` disables transpose packing (eval-only batches).
    """
    graph_cap = graph_cap_for(batch_size) if snug else batch_size
    if dense_m is not None and in_cap is None and over_cap is None:
        over_cap = overflow_cap(graphs, graph_cap, dense_m)
    if in_cap is not None:
        over_cap = None  # explicit single-tier (or in_cap=0: disabled)
    in_cap = in_cap or None  # 0 disables (eval-only batches: no backward)
    order = np.arange(len(graphs))
    if shuffle:
        (rng or np.random.default_rng()).shuffle(order)
    bucket: list[CrystalGraph] = []
    nn = ne = 0
    for idx in order:
        g = graphs[int(idx)]
        if g.num_nodes > node_cap or g.num_edges > edge_cap:
            raise ValueError(
                f"graph {g.cif_id!r} ({g.num_nodes} nodes, {g.num_edges} edges) "
                f"exceeds batch capacity ({node_cap}, {edge_cap}); "
                f"increase caps or filter the dataset"
            )
        if bucket and (
            len(bucket) == graph_cap
            or nn + g.num_nodes > node_cap
            or ne + g.num_edges > edge_cap
        ):
            for packed in _pack_overflow_safe(
                    bucket, node_cap, edge_cap, graph_cap, dense_m, in_cap,
                    over_cap, edge_dtype, pack_fn=pack_fn,
                    transpose_shards=transpose_shards):
                yield invariants.maybe_check(packed, dense_m)
            bucket, nn, ne = [], 0, 0
        bucket.append(g)
        nn += g.num_nodes
        ne += g.num_edges
    # drop_last drops only an *incomplete* tail (standard loader
    # semantics): fewer than batch_size graphs. Compared against
    # batch_size, NOT graph_cap — under snug packing batches close on
    # capacity and essentially never reach graph_cap's slack, so a
    # graph_cap comparison would silently drop full tails.
    if bucket and (not drop_last or len(bucket) >= batch_size):
        for packed in _pack_overflow_safe(
                bucket, node_cap, edge_cap, graph_cap, dense_m, in_cap,
                over_cap, edge_dtype, pack_fn=pack_fn,
                transpose_shards=transpose_shards):
            yield invariants.maybe_check(packed, dense_m)
