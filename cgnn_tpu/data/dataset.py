"""Dataset assembly: structures -> featurized CrystalGraphs -> splits.

TPU-native counterpart of the reference's ``CIFData`` + loader factory
(SURVEY.md §2 components 3, 12; §3.1). Differences by design:

- Featurization is an *offline, cached* step producing flat-COO graphs
  (SURVEY.md §7 phase 4: at 10k structures/s/chip, per-step CIF parsing is
  impossible; preprocess once, stream tensors).
- Neighbor layout is flat COO, truncated to ``max_num_nbr`` nearest like the
  reference, but without fake padding edges — static shapes come from the
  batcher (graph.py), not per-atom padding.
"""

from __future__ import annotations

import csv
import dataclasses
import os
import warnings
from typing import Sequence

import numpy as np

from cgnn_tpu.data.cif import parse_cif_file
from cgnn_tpu.data.elements import atom_features
from cgnn_tpu.data.featurize import GaussianDistance
from cgnn_tpu.data.graph import CrystalGraph
from cgnn_tpu.data.neighbors import knn_neighbor_list
from cgnn_tpu.data.structure import Structure
from cgnn_tpu.data.synthetic import synthetic_dataset


@dataclasses.dataclass
class FeaturizeConfig:
    """Featurization hyperparameters (mirror the reference CLI flags)."""

    radius: float = 8.0
    max_num_nbr: int = 12
    dmin: float = 0.0
    step: float = 0.2

    def gdf(self) -> GaussianDistance:
        return GaussianDistance(self.dmin, self.radius, self.step)


def featurize_structure(
    structure: Structure,
    target,
    cfg: FeaturizeConfig,
    cif_id: str = "",
    gdf: GaussianDistance | None = None,
    target_mask=None,
    keep_geometry: bool = False,
) -> CrystalGraph:
    """Structure + label -> flat-COO CrystalGraph (host-side)."""
    gdf = gdf or cfg.gdf()
    nl = knn_neighbor_list(
        structure, cfg.radius, cfg.max_num_nbr, warn_under_coordinated=False
    )
    if len(nl) == 0:
        raise ValueError(
            f"structure {cif_id!r} has no neighbors within radius {cfg.radius}"
        )
    graph = CrystalGraph(
        atom_fea=atom_features(structure.numbers),
        edge_fea=gdf.expand(nl.distances),
        centers=nl.centers,
        neighbors=nl.neighbors,
        target=np.atleast_1d(np.asarray(target, np.float32)),
        cif_id=cif_id,
        target_mask=(
            None if target_mask is None
            else np.atleast_1d(np.asarray(target_mask, np.float32))
        ),
        distances=nl.distances,
    )
    if keep_geometry:
        # neighbor offsets are computed against WRAPPED coordinates (both
        # neighbor backends wrap fracs into [0,1)); stored geometry must
        # match or in-model edge_distances() recomputes wrong distances
        graph.positions = structure.wrapped().cart_coords.astype(np.float32)
        graph.lattice = structure.lattice.astype(np.float32)
        graph.offsets = nl.offsets.astype(np.int32)
        graph.numbers = structure.numbers.copy()
    return graph


def load_cif_directory(
    root_dir: str,
    cfg: FeaturizeConfig | None = None,
    id_prop_file: str = "id_prop.csv",
    keep_geometry: bool = False,
) -> list[CrystalGraph]:
    """Reference-compatible directory layout: ``{root}/{id}.cif`` + id_prop.csv.

    Each id_prop.csv row is ``cif_id, target[, target2, ...]`` — multi-column
    rows feed the multi-task head; empty cells become masked-out labels.
    """
    cfg = cfg or FeaturizeConfig()
    gdf = cfg.gdf()
    prop_path = os.path.join(root_dir, id_prop_file)
    if not os.path.exists(prop_path):
        raise FileNotFoundError(f"missing {prop_path}")
    graphs: list[CrystalGraph] = []
    with open(prop_path, newline="") as f:
        for row in csv.reader(f):
            if not row:
                continue
            cif_id = row[0].strip()
            raw = [c.strip() for c in row[1:]]
            target = np.array([float(c) if c else 0.0 for c in raw], np.float32)
            mask = np.array([1.0 if c else 0.0 for c in raw], np.float32)
            cif_path = os.path.join(root_dir, cif_id + ".cif")
            try:
                structure = parse_cif_file(cif_path)
                graphs.append(
                    featurize_structure(
                        structure, target, cfg, cif_id, gdf,
                        target_mask=mask, keep_geometry=keep_geometry,
                    )
                )
            except Exception as e:  # noqa: BLE001 — reference warns and skips
                warnings.warn(f"skipping {cif_id}: {e}", stacklevel=2)
    if not graphs:
        raise ValueError(f"no usable structures under {root_dir}")
    return graphs


def load_synthetic(
    num_structures: int,
    cfg: FeaturizeConfig | None = None,
    seed: int = 0,
    keep_geometry: bool = False,
    **synth_kwargs,
) -> list[CrystalGraph]:
    cfg = cfg or FeaturizeConfig()
    gdf = cfg.gdf()
    return [
        featurize_structure(s, t, cfg, sid, gdf, keep_geometry=keep_geometry)
        for sid, s, t in synthetic_dataset(num_structures, seed, **synth_kwargs)
    ]


def load_synthetic_mp(
    num_structures: int,
    cfg: FeaturizeConfig | None = None,
    seed: int = 0,
    keep_geometry: bool = False,
) -> list[CrystalGraph]:
    """MP-like size distribution (lognormal ~30 atoms) for honest benching."""
    from cgnn_tpu.data.synthetic import synthetic_mp_dataset

    cfg = cfg or FeaturizeConfig()
    gdf = cfg.gdf()
    return [
        featurize_structure(s, t, cfg, sid, gdf,
                            keep_geometry=keep_geometry)
        for sid, s, t in synthetic_mp_dataset(num_structures, seed)
    ]


def load_synthetic_oc20(
    num_structures: int,
    cfg: FeaturizeConfig | None = None,
    seed: int = 0,
) -> list[CrystalGraph]:
    """OC20 IS2RE stand-in: large catalyst-slab graphs (50-200+ atoms).

    Exercises the large-graph regime of BASELINE config #4 — surface
    under-coordination, vacuum gaps, and a wide node/edge size spread that
    stresses the bucketed batcher (SURVEY.md §2 [B:10])."""
    from cgnn_tpu.data.synthetic import synthetic_oc20_dataset

    cfg = cfg or FeaturizeConfig()
    gdf = cfg.gdf()
    return [
        featurize_structure(s, t, cfg, sid, gdf)
        for sid, s, t in synthetic_oc20_dataset(num_structures, seed)
    ]


def load_trajectory(
    num_frames: int,
    cfg: FeaturizeConfig | None = None,
    seed: int = 0,
    num_atoms: int = 8,
    jitter: float = 0.08,
) -> list[CrystalGraph]:
    """MD17 stand-in: LJ trajectory frames with energy + force labels.

    Graphs carry geometry (positions/lattice/offsets) so the differentiable
    force model can recompute distances in-model, plus per-atom ``forces``
    labels for the composite loss (BASELINE config #5).
    """
    from cgnn_tpu.data.synthetic import synthetic_trajectory

    cfg = cfg or FeaturizeConfig()
    gdf = cfg.gdf()
    graphs = []
    for sid, s, energy, forces in synthetic_trajectory(
        num_frames, seed=seed, num_atoms=num_atoms, jitter=jitter
    ):
        g = featurize_structure(
            s, energy, cfg, sid, gdf, keep_geometry=True
        )
        g.forces = forces.astype(np.float32)
        graphs.append(g)
    return graphs


def train_val_test_split(
    graphs: Sequence[CrystalGraph],
    train_ratio: float = 0.8,
    val_ratio: float = 0.1,
    seed: int = 0,
) -> tuple[list[CrystalGraph], list[CrystalGraph], list[CrystalGraph]]:
    """Deterministic shuffled split (reference: ratio-based sampler split)."""
    if train_ratio + val_ratio >= 1.0 + 1e-9:
        raise ValueError("train_ratio + val_ratio must leave room for test")
    idx = np.random.default_rng(seed).permutation(len(graphs))
    n_train = int(len(graphs) * train_ratio)
    n_val = int(len(graphs) * val_ratio)
    pick = lambda ids: [graphs[int(i)] for i in ids]  # noqa: E731
    return (
        pick(idx[:n_train]),
        pick(idx[n_train : n_train + n_val]),
        pick(idx[n_train + n_val :]),
    )
