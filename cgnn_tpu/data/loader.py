"""Prefetching device loader (SURVEY.md §2 native table: H2D double-buffer).

The reference overlaps H2D copies with compute via ``pin_memory`` +
``non_blocking`` CUDA copies. The TPU equivalent: a background thread packs
GraphBatches and ``jax.device_put``s them while the device runs the current
step, keeping a small queue of ready-on-device batches ahead of the
consumer. Packing is numpy (releases the GIL for the big copies), so one
thread suffices to hide host latency behind multi-ms device steps.

With a ``telemetry`` (observe.Telemetry), the loader reports two
counters into the run summary: ``loader_wait_s`` — time the consumer
blocked on an empty queue (the loader failing to hide host latency; the
starvation signal) — and ``loader_put_s`` — producer time spent packing
+ staging (``device_put``) per run.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator

import jax

from cgnn_tpu.data.graph import GraphBatch

_SENTINEL = object()


def prefetch_to_device(
    batches: Iterable[GraphBatch],
    size: int = 2,
    device_put: Callable = jax.device_put,
    telemetry=None,
    join_timeout: float = 5.0,
) -> Iterator[GraphBatch]:
    """Wrap a host batch iterator with an N-deep on-device prefetch queue.

    The producer shuts down when the CONSUMER abandons the iterator
    mid-epoch too (an exception in the train loop closes the generator):
    every queue put is bounded by a stop event the generator's
    ``finally`` sets, so the thread can never block forever on a full
    queue holding staged device buffers alive. Normal completion and
    producer-error propagation are unchanged.
    """
    q: queue.Queue = queue.Queue(maxsize=size)
    err: list[BaseException] = []
    stop = threading.Event()

    def bounded_put(item) -> bool:
        """put that gives up when the consumer is gone -> False."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            it = iter(batches)
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    break
                staged = device_put(b)
                if telemetry is not None:
                    telemetry.counter_add(
                        "loader_put_s", time.perf_counter() - t0
                    )
                if not bounded_put(staged):
                    return  # consumer abandoned mid-epoch
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer side
            err.append(e)
        finally:
            bounded_put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True, name="cgnn-prefetch")
    t.start()
    try:
        while True:
            t0 = time.perf_counter()
            item = q.get()
            if telemetry is not None:
                telemetry.counter_add(
                    "loader_wait_s", time.perf_counter() - t0
                )
            if item is _SENTINEL:
                break
            yield item
    finally:
        # reached on normal exhaustion AND on generator close (consumer
        # exception/abandonment): release the producer, then join — the
        # bounded puts guarantee it exits within one timeout tick
        stop.set()
        t.join(join_timeout)
    if err:
        raise err[0]
