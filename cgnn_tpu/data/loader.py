"""Prefetching device loader (SURVEY.md §2 native table: H2D double-buffer).

The reference overlaps H2D copies with compute via ``pin_memory`` +
``non_blocking`` CUDA copies. The TPU equivalent: a background thread packs
GraphBatches and ``jax.device_put``s them while the device runs the current
step, keeping a small queue of ready-on-device batches ahead of the
consumer. Packing is numpy (releases the GIL for the big copies), so one
thread suffices to hide host latency behind multi-ms device steps.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator

import jax

from cgnn_tpu.data.graph import GraphBatch

_SENTINEL = object()


def prefetch_to_device(
    batches: Iterable[GraphBatch],
    size: int = 2,
    device_put: Callable = jax.device_put,
) -> Iterator[GraphBatch]:
    """Wrap a host batch iterator with an N-deep on-device prefetch queue."""
    q: queue.Queue = queue.Queue(maxsize=size)
    err: list[BaseException] = []

    def producer():
        try:
            for b in batches:
                q.put(device_put(b))
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer side
            err.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True, name="cgnn-prefetch")
    t.start()
    while True:
        item = q.get()
        if item is _SENTINEL:
            break
        yield item
    t.join()
    if err:
        raise err[0]
