"""Element property table and the 92-dim one-hot atom featurizer.

The reference lineage initializes atom features from an ``atom_init.json``
file mapping atomic number -> 92-dim binary vector built by one-hot
discretizing elemental properties (SURVEY.md §2 component 3). That file is not
on disk and pymatgen is unavailable, so the table is regenerated here from an
in-tree element-property table (approximate literature values: Pauling
electronegativity, Cordero covalent radii, NIST ionization energies /
electron affinities, molar volumes). Properties that are undefined for an
element (e.g. noble-gas electronegativity) produce an all-zero segment,
mirroring the reference lineage's handling of missing values.

Feature layout (total 92):
    group one-hot            18   (1-18; f-block mapped to group 3)
    period one-hot            8   (1-7 used; slot 8 reserved)
    electronegativity bins   10   (Pauling, linear in [0.5, 4.0])
    covalent radius bins     10   (pm, linear in [25, 250])
    valence electrons        12   (1-12, clipped)
    first ionization bins    10   (eV, log in [ln 3, ln 25])
    electron affinity bins   10   (eV, linear in [-3.0, 3.7])
    block one-hot             4   (s, p, d, f)
    atomic volume bins       10   (ln cm^3/mol, linear in [1.5, 4.3])
"""

from __future__ import annotations

import functools
import math

import numpy as np

NAN = float("nan")

# Z: (symbol, group, period, block, electronegativity, covalent_radius_pm,
#     n_valence, first_ionization_eV, electron_affinity_eV, molar_volume_cm3)
# Approximate literature values; NaN where the property is undefined/unknown.
ELEMENTS: dict[int, tuple] = {
    1: ("H", 1, 1, "s", 2.20, 31, 1, 13.60, 0.75, 11.4),
    2: ("He", 18, 1, "s", NAN, 28, 2, 24.59, NAN, 27.2),
    3: ("Li", 1, 2, "s", 0.98, 128, 1, 5.39, 0.62, 13.1),
    4: ("Be", 2, 2, "s", 1.57, 96, 2, 9.32, NAN, 4.9),
    5: ("B", 13, 2, "p", 2.04, 84, 3, 8.30, 0.28, 4.4),
    6: ("C", 14, 2, "p", 2.55, 76, 4, 11.26, 1.26, 5.3),
    7: ("N", 15, 2, "p", 3.04, 71, 5, 14.53, NAN, 13.5),
    8: ("O", 16, 2, "p", 3.44, 66, 6, 13.62, 1.46, 14.0),
    9: ("F", 17, 2, "p", 3.98, 57, 7, 17.42, 3.40, 17.1),
    10: ("Ne", 18, 2, "p", NAN, 58, 8, 21.56, NAN, 16.8),
    11: ("Na", 1, 3, "s", 0.93, 166, 1, 5.14, 0.55, 23.7),
    12: ("Mg", 2, 3, "s", 1.31, 141, 2, 7.65, NAN, 14.0),
    13: ("Al", 13, 3, "p", 1.61, 121, 3, 5.99, 0.44, 10.0),
    14: ("Si", 14, 3, "p", 1.90, 111, 4, 8.15, 1.39, 12.1),
    15: ("P", 15, 3, "p", 2.19, 107, 5, 10.49, 0.75, 17.0),
    16: ("S", 16, 3, "p", 2.58, 105, 6, 10.36, 2.08, 15.5),
    17: ("Cl", 17, 3, "p", 3.16, 102, 7, 12.97, 3.61, 18.7),
    18: ("Ar", 18, 3, "p", NAN, 106, 8, 15.76, NAN, 24.2),
    19: ("K", 1, 4, "s", 0.82, 203, 1, 4.34, 0.50, 45.3),
    20: ("Ca", 2, 4, "s", 1.00, 176, 2, 6.11, 0.02, 26.2),
    21: ("Sc", 3, 4, "d", 1.36, 170, 3, 6.56, 0.19, 15.0),
    22: ("Ti", 4, 4, "d", 1.54, 160, 4, 6.83, 0.08, 10.6),
    23: ("V", 5, 4, "d", 1.63, 153, 5, 6.75, 0.53, 8.3),
    24: ("Cr", 6, 4, "d", 1.66, 139, 6, 6.77, 0.67, 7.2),
    25: ("Mn", 7, 4, "d", 1.55, 139, 7, 7.43, NAN, 7.4),
    26: ("Fe", 8, 4, "d", 1.83, 132, 8, 7.90, 0.15, 7.1),
    27: ("Co", 9, 4, "d", 1.88, 126, 9, 7.88, 0.66, 6.7),
    28: ("Ni", 10, 4, "d", 1.91, 124, 10, 7.64, 1.16, 6.6),
    29: ("Cu", 11, 4, "d", 1.90, 132, 11, 7.73, 1.24, 7.1),
    30: ("Zn", 12, 4, "d", 1.65, 122, 12, 9.39, NAN, 9.2),
    31: ("Ga", 13, 4, "p", 1.81, 122, 3, 6.00, 0.30, 11.8),
    32: ("Ge", 14, 4, "p", 2.01, 120, 4, 7.90, 1.23, 13.6),
    33: ("As", 15, 4, "p", 2.18, 119, 5, 9.79, 0.80, 13.1),
    34: ("Se", 16, 4, "p", 2.55, 120, 6, 9.75, 2.02, 16.4),
    35: ("Br", 17, 4, "p", 2.96, 120, 7, 11.81, 3.36, 23.5),
    36: ("Kr", 18, 4, "p", 3.00, 116, 8, 14.00, NAN, 27.9),
    37: ("Rb", 1, 5, "s", 0.82, 220, 1, 4.18, 0.49, 55.8),
    38: ("Sr", 2, 5, "s", 0.95, 195, 2, 5.69, 0.05, 33.9),
    39: ("Y", 3, 5, "d", 1.22, 190, 3, 6.22, 0.31, 19.9),
    40: ("Zr", 4, 5, "d", 1.33, 175, 4, 6.63, 0.43, 14.0),
    41: ("Nb", 5, 5, "d", 1.60, 164, 5, 6.76, 0.89, 10.8),
    42: ("Mo", 6, 5, "d", 2.16, 154, 6, 7.09, 0.75, 9.4),
    43: ("Tc", 7, 5, "d", 1.90, 147, 7, 7.28, 0.55, 8.5),
    44: ("Ru", 8, 5, "d", 2.20, 146, 8, 7.36, 1.05, 8.3),
    45: ("Rh", 9, 5, "d", 2.28, 142, 9, 7.46, 1.14, 8.3),
    46: ("Pd", 10, 5, "d", 2.20, 139, 10, 8.34, 0.56, 8.9),
    47: ("Ag", 11, 5, "d", 1.93, 145, 11, 7.58, 1.30, 10.3),
    48: ("Cd", 12, 5, "d", 1.69, 144, 12, 8.99, NAN, 13.0),
    49: ("In", 13, 5, "p", 1.78, 142, 3, 5.79, 0.30, 15.7),
    50: ("Sn", 14, 5, "p", 1.96, 139, 4, 7.34, 1.11, 16.3),
    51: ("Sb", 15, 5, "p", 2.05, 139, 5, 8.61, 1.05, 18.2),
    52: ("Te", 16, 5, "p", 2.10, 138, 6, 9.01, 1.97, 20.5),
    53: ("I", 17, 5, "p", 2.66, 139, 7, 10.45, 3.06, 25.7),
    54: ("Xe", 18, 5, "p", 2.60, 140, 8, 12.13, NAN, 35.9),
    55: ("Cs", 1, 6, "s", 0.79, 244, 1, 3.89, 0.47, 70.0),
    56: ("Ba", 2, 6, "s", 0.89, 215, 2, 5.21, 0.14, 38.2),
    57: ("La", 3, 6, "f", 1.10, 207, 3, 5.58, 0.47, 22.5),
    58: ("Ce", 3, 6, "f", 1.12, 204, 4, 5.54, 0.65, 20.7),
    59: ("Pr", 3, 6, "f", 1.13, 203, 5, 5.47, 0.96, 20.8),
    60: ("Nd", 3, 6, "f", 1.14, 201, 6, 5.53, 1.92, 20.6),
    61: ("Pm", 3, 6, "f", 1.13, 199, 7, 5.58, NAN, 20.2),
    62: ("Sm", 3, 6, "f", 1.17, 198, 8, 5.64, NAN, 19.9),
    63: ("Eu", 3, 6, "f", 1.20, 198, 9, 5.67, 0.86, 28.9),
    64: ("Gd", 3, 6, "f", 1.20, 196, 10, 6.15, NAN, 19.9),
    65: ("Tb", 3, 6, "f", 1.20, 194, 11, 5.86, NAN, 19.2),
    66: ("Dy", 3, 6, "f", 1.22, 192, 12, 5.94, NAN, 19.0),
    67: ("Ho", 3, 6, "f", 1.23, 192, 12, 6.02, NAN, 18.7),
    68: ("Er", 3, 6, "f", 1.24, 189, 12, 6.11, NAN, 18.4),
    69: ("Tm", 3, 6, "f", 1.25, 190, 12, 6.18, 1.03, 18.1),
    70: ("Yb", 3, 6, "f", 1.10, 187, 12, 6.25, NAN, 24.8),
    71: ("Lu", 3, 6, "d", 1.27, 187, 3, 5.43, 0.34, 17.8),
    72: ("Hf", 4, 6, "d", 1.30, 175, 4, 6.83, 0.02, 13.6),
    73: ("Ta", 5, 6, "d", 1.50, 170, 5, 7.55, 0.32, 10.9),
    74: ("W", 6, 6, "d", 2.36, 162, 6, 7.86, 0.82, 9.5),
    75: ("Re", 7, 6, "d", 1.90, 151, 7, 7.83, 0.15, 8.9),
    76: ("Os", 8, 6, "d", 2.20, 144, 8, 8.44, 1.10, 8.4),
    77: ("Ir", 9, 6, "d", 2.20, 141, 9, 8.97, 1.57, 8.5),
    78: ("Pt", 10, 6, "d", 2.28, 136, 10, 8.96, 2.13, 9.1),
    79: ("Au", 11, 6, "d", 2.54, 136, 11, 9.23, 2.31, 10.2),
    80: ("Hg", 12, 6, "d", 2.00, 132, 12, 10.44, NAN, 14.8),
    81: ("Tl", 13, 6, "p", 1.62, 145, 3, 6.11, 0.20, 17.2),
    82: ("Pb", 14, 6, "p", 2.33, 146, 4, 7.42, 0.36, 18.3),
    83: ("Bi", 15, 6, "p", 2.02, 148, 5, 7.29, 0.95, 21.3),
    84: ("Po", 16, 6, "p", 2.00, 140, 6, 8.41, 1.90, 22.7),
    85: ("At", 17, 6, "p", 2.20, 150, 7, 9.32, 2.80, NAN),
    86: ("Rn", 18, 6, "p", NAN, 150, 8, 10.75, NAN, 50.5),
    87: ("Fr", 1, 7, "s", 0.70, 260, 1, 4.07, 0.46, NAN),
    88: ("Ra", 2, 7, "s", 0.90, 221, 2, 5.28, 0.10, 41.1),
    89: ("Ac", 3, 7, "f", 1.10, 215, 3, 5.17, 0.35, 37.4),
    90: ("Th", 3, 7, "f", 1.30, 206, 4, 6.31, 0.60, 19.8),
    91: ("Pa", 3, 7, "f", 1.50, 200, 5, 5.89, 0.55, 15.0),
    92: ("U", 3, 7, "f", 1.38, 196, 6, 6.19, 0.53, 12.5),
    93: ("Np", 3, 7, "f", 1.36, 190, 7, 6.27, 0.48, 11.6),
    94: ("Pu", 3, 7, "f", 1.28, 187, 8, 6.03, NAN, 12.3),
    95: ("Am", 3, 7, "f", 1.30, 180, 9, 5.97, NAN, 17.6),
    96: ("Cm", 3, 7, "f", 1.30, 169, 10, 5.99, NAN, 18.1),
    97: ("Bk", 3, 7, "f", 1.30, NAN, 11, 6.20, NAN, NAN),
    98: ("Cf", 3, 7, "f", 1.30, NAN, 12, 6.28, NAN, NAN),
    99: ("Es", 3, 7, "f", 1.30, NAN, 12, 6.42, NAN, NAN),
    100: ("Fm", 3, 7, "f", 1.30, NAN, 12, 6.50, NAN, NAN),
}

SYMBOL_TO_Z: dict[str, int] = {v[0]: z for z, v in ELEMENTS.items()}
# hydrogen-isotope aliases: neutron-diffraction CIFs label deuterium/tritium
# sites 'D'/'T' (ICSD convention); chemically they featurize as hydrogen
SYMBOL_TO_Z["D"] = 1
SYMBOL_TO_Z["T"] = 1
Z_TO_SYMBOL: dict[int, str] = {z: v[0] for z, v in ELEMENTS.items()}

MAX_Z = 100
ATOM_FEA_DIM = 92

_BLOCKS = ("s", "p", "d", "f")


def _one_hot(index: int, size: int) -> np.ndarray:
    v = np.zeros(size, dtype=np.float32)
    if 0 <= index < size:
        v[index] = 1.0
    return v


def _binned(value: float, lo: float, hi: float, nbins: int, log: bool = False) -> np.ndarray:
    """One-hot bin of a continuous property; all-zeros when value is NaN."""
    v = np.zeros(nbins, dtype=np.float32)
    if value is None or (isinstance(value, float) and math.isnan(value)):
        return v
    x = math.log(value) if log else value
    lo_t = math.log(lo) if log else lo
    hi_t = math.log(hi) if log else hi
    frac = (x - lo_t) / (hi_t - lo_t)
    idx = min(nbins - 1, max(0, int(frac * nbins)))
    v[idx] = 1.0
    return v


@functools.lru_cache(maxsize=None)
def _feature_row(z: int) -> np.ndarray:
    if z not in ELEMENTS:
        raise KeyError(f"no element data for Z={z} (supported: 1..{MAX_Z})")
    _, group, period, block, en, radius, valence, ie, ea, vol = ELEMENTS[z]
    log_vol = NAN if (isinstance(vol, float) and math.isnan(vol)) else math.log(vol)
    parts = [
        _one_hot(group - 1, 18),
        _one_hot(period - 1, 8),
        _binned(en, 0.5, 4.0, 10),
        _binned(radius, 25.0, 250.0, 10),
        _one_hot(int(np.clip(valence, 1, 12)) - 1, 12),
        _binned(ie, 3.0, 25.0, 10, log=True),
        _binned(ea, -3.0, 3.7, 10),
        _one_hot(_BLOCKS.index(block), 4),
        _binned(log_vol, 1.5, 4.3, 10),
    ]
    row = np.concatenate(parts)
    assert row.shape == (ATOM_FEA_DIM,)
    return row


def atom_features(numbers) -> np.ndarray:
    """[N] atomic numbers -> [N, 92] float32 feature matrix."""
    numbers = np.asarray(numbers, dtype=np.int64).ravel()
    return np.stack([_feature_row(int(z)) for z in numbers]).astype(np.float32)


@functools.lru_cache(maxsize=1)
def full_embedding_table() -> np.ndarray:
    """[MAX_Z + 1, 92] table; row 0 is zeros (no element)."""
    table = np.zeros((MAX_Z + 1, ATOM_FEA_DIM), dtype=np.float32)
    for z in range(1, MAX_Z + 1):
        table[z] = _feature_row(z)
    return table
