"""GraphBatch invariant checks (SURVEY.md §5 race-detection/sanitizers).

The jitted step trusts several non-local data-plane invariants that are
established at pack time and never re-checked (``indices_are_sorted`` is an
UNCHECKED promise to XLA's TPU scatter; ``gather_transpose``'s custom VJP is
only correct when the transpose mapping is complete). A corrupted batch —
a bug in a new iterator, a bad cache file, a miswired shard — would train
silently wrong. This module is the loud path: ``--check-invariants``
(train.py) enables validation of every packed batch at iterator exit;
``check_batch`` can also be called directly (tests, debugging).

Checks are host-side (numpy + chex static assertions) so they add zero
device work; cost is one pass over each batch's index arrays.
"""

from __future__ import annotations

import chex
import numpy as np

_ENABLED = False


def enable(on: bool = True) -> None:
    """Globally enable per-batch validation (the --check-invariants flag)."""
    global _ENABLED
    _ENABLED = on


def enabled() -> bool:
    return _ENABLED


class BatchInvariantError(AssertionError):
    pass


def _fail(msg: str):
    raise BatchInvariantError(msg)


def check_batch(batch, dense_m: int | None = None):
    """Validate one host-side GraphBatch; raises BatchInvariantError.

    Invariants (data/graph.py module docstring + pack_graphs):
    - shape/dtype consistency across leaves (chex);
    - masks are exactly {0, 1};
    - ``centers`` is non-decreasing (the sorted-scatter promise) and every
      real edge's endpoints are real, in-range node slots;
    - padding edges carry zero mask AND zero features;
    - ``node_graph`` is non-decreasing with real nodes pointing at real
      graph slots;
    - dense layout: slot ownership centers[k] == k // M (``dense_m`` is
      inferred from pre-shaped [N, M, G] edges when not given);
    - transpose slots: ``in_slots``/``in_mask`` list every real edge slot
      exactly once under its neighbor node — the completeness property
      gather_transpose's scatter-free backward silently relies on.
    """
    if dense_m is None and np.ndim(batch.edges) == 3:
        dense_m = int(np.shape(batch.edges)[1])
    nodes = np.asarray(batch.nodes)
    edges = np.asarray(batch.flat_edges)
    centers = np.asarray(batch.centers)
    neighbors = np.asarray(batch.neighbors)
    node_graph = np.asarray(batch.node_graph)
    node_mask = np.asarray(batch.node_mask)
    edge_mask = np.asarray(batch.edge_mask)
    graph_mask = np.asarray(batch.graph_mask)

    ncap, ecap = nodes.shape[0], edges.shape[0]
    chex.assert_shape(centers, (ecap,))
    chex.assert_shape(neighbors, (ecap,))
    chex.assert_shape(edge_mask, (ecap,))
    chex.assert_shape(node_graph, (ncap,))
    chex.assert_shape(node_mask, (ncap,))
    chex.assert_type([centers, neighbors, node_graph], np.integer)

    for name, m in (("node_mask", node_mask), ("edge_mask", edge_mask),
                    ("graph_mask", graph_mask)):
        if not np.isin(m, (0.0, 1.0)).all():
            _fail(f"{name} contains values outside {{0, 1}}")

    if np.any(np.diff(centers) < 0):
        _fail("centers is not non-decreasing (sorted-scatter promise broken)")
    if centers.min(initial=0) < 0 or centers.max(initial=0) >= ncap:
        _fail("centers out of node-slot range")
    if neighbors.min(initial=0) < 0 or neighbors.max(initial=0) >= ncap:
        _fail("neighbors out of node-slot range")

    real_e = edge_mask > 0
    if real_e.any():
        if not node_mask[centers[real_e]].all():
            _fail("a real edge's center is a padding node")
        if not node_mask[neighbors[real_e]].all():
            _fail("a real edge's neighbor is a padding node")
    if np.any(np.abs(edges[~real_e]) > 0):
        _fail("padding edge slots carry nonzero features")

    real_n = node_mask > 0
    if not np.all(np.diff(node_mask) <= 0):
        _fail("real nodes are not a contiguous prefix of the node slots")
    if np.any(np.diff(node_graph[real_n]) < 0):
        _fail("node_graph is not non-decreasing over real nodes")
    if np.any(node_graph[~real_n] != 0):
        _fail("padding nodes must belong to graph slot 0")
    if real_n.any() and not graph_mask[node_graph[real_n]].all():
        _fail("a real node belongs to a padding graph slot")

    if dense_m is not None:
        owner = np.arange(ecap) // dense_m
        if not np.array_equal(centers, owner.astype(centers.dtype)):
            _fail(f"dense slot ownership broken: centers != slot//{dense_m}")

    if batch.in_slots is not None:
        _check_transpose_mapping(batch, neighbors, real_e, ncap)
    return batch


def _check_transpose_mapping(batch, neighbors, real_e, ncap):
    """The gather_transpose completeness property (flat ``neighbors`` [E]
    and ``real_e`` [E] bool) — shared by GraphBatch and CompactBatch.

    Per-shard stacked mappings (``in_mask`` [D, N, tier] from
    shard_transpose_slots, node-strip graph sharding) are validated by
    converting each shard's LOCAL slot indices back to global ids — each
    shard must list exactly its own slot range's real edges, and the union
    must satisfy the same completeness property as the flat mapping."""
    def collect(in_slots, in_mask, over, ncap, slot_range, offset, tag):
        """One mapping's (listed global slot ids, neighbor rows) — the
        SHARED collector for the flat mapping (offset 0, full slot range)
        and each shard of a per-shard stack (local range + shard offset),
        so the completeness contract cannot diverge between the two."""
        if in_mask.shape[0] != ncap:
            _fail(f"{tag}in_slots/in_mask row count != node capacity")
        lst = in_slots.reshape(in_mask.shape)[in_mask > 0]
        if lst.size and (lst.min() < 0 or lst.max() >= slot_range):
            _fail(f"{tag}transpose mapping lists a slot outside its "
                  f"range [0, {slot_range})")
        parts = [lst + offset]
        rows = [np.repeat(np.arange(ncap), (in_mask > 0).sum(axis=1))]
        if over is not None:
            osl, ond, omk = over
            chex.assert_shape(ond, osl.shape)
            chex.assert_shape(omk, osl.shape)
            if np.any(np.diff(ond) < 0):
                _fail(f"{tag}over_nodes is not non-decreasing "
                      f"(sorted-scatter promise broken)")
            sel = omk > 0
            if sel.any() and (osl[sel].min() < 0
                              or osl[sel].max() >= slot_range):
                _fail(f"{tag}overflow lists a slot outside its range")
            parts.append(osl[sel] + offset)
            rows.append(ond[sel])
        return parts, rows

    in_mask = np.asarray(batch.in_mask)
    over_all = (
        None if batch.over_slots is None
        else (np.asarray(batch.over_slots), np.asarray(batch.over_nodes),
              np.asarray(batch.over_mask))
    )
    if in_mask.ndim == 3:
        n_sh = in_mask.shape[0]
        if len(real_e) % n_sh:
            _fail("sharded transpose mapping: edge capacity not divisible "
                  "by the shard count")
        e_s = len(real_e) // n_sh
        in_slots = np.asarray(batch.in_slots).reshape(n_sh, -1)
        listed_parts, row_parts = [], []
        for s in range(n_sh):
            parts, rows_s = collect(
                in_slots[s], in_mask[s],
                None if over_all is None else tuple(x[s] for x in over_all),
                ncap, e_s, s * e_s, f"shard {s} ",
            )
            listed_parts += parts
            row_parts += rows_s
        listed = np.concatenate(listed_parts)
        rows = np.concatenate(row_parts)
    else:
        parts, rows_p = collect(
            np.asarray(batch.in_slots), in_mask, over_all, ncap,
            len(real_e), 0, "",
        )
        listed = np.concatenate(parts)
        rows = np.concatenate(rows_p)
    if listed.size != int(real_e.sum()):
        _fail(
            f"transpose mapping lists {listed.size} edges but the batch "
            f"has {int(real_e.sum())} real edges (gather_transpose "
            f"backward would drop/duplicate gradient)"
        )
    if listed.size:
        if np.unique(listed).size != listed.size:
            _fail("transpose mapping lists an edge slot twice")
        if not real_e[listed].all():
            _fail("transpose mapping lists a padding edge slot")
        if not np.array_equal(
            np.sort(listed), np.sort(np.nonzero(real_e)[0])
        ):
            _fail("transpose mapping misses a real edge slot")
        if not np.array_equal(neighbors[listed], rows):
            _fail("a transpose row lists an edge of a different neighbor")


def check_compact_batch(batch, dense_m: int | None = None):
    """Validate a CompactBatch (data/compact.py) — the raw-form analog of
    ``check_batch``. The expensive expanded-form checks (feature zeros on
    padding) become mask/range checks on the raw payload; the transpose-
    mapping completeness check is shared verbatim."""
    atom_idx = np.asarray(batch.atom_idx)
    distances = np.asarray(batch.distances)
    neighbors = np.asarray(batch.neighbors)
    node_graph = np.asarray(batch.node_graph)
    node_mask = np.asarray(batch.node_mask)
    edge_mask = np.asarray(batch.edge_mask)
    graph_mask = np.asarray(batch.graph_mask)
    ncap, m = distances.shape
    if dense_m is not None and dense_m != m:
        _fail(f"compact batch packed with M={m} but dense_m={dense_m} "
              f"expected")
    chex.assert_shape(atom_idx, (ncap,))
    chex.assert_shape(neighbors, (ncap * m,))
    chex.assert_shape(edge_mask, (ncap, m))
    chex.assert_shape(node_mask, (ncap,))
    for name, msk in (("node_mask", node_mask), ("edge_mask", edge_mask),
                      ("graph_mask", graph_mask)):
        if not np.isin(msk, (0, 1)).all():
            _fail(f"{name} contains values outside {{0, 1}}")
    if atom_idx.min(initial=0) < 0:
        _fail("negative atom vocabulary index")
    if neighbors.min(initial=0) < 0 or neighbors.max(initial=0) >= ncap:
        _fail("neighbors out of node-slot range")
    real_e = edge_mask > 0
    if not node_mask[neighbors.reshape(ncap, m)[real_e]].all():
        _fail("a real edge's neighbor is a padding node")
    if np.any(real_e & ~(node_mask > 0)[:, None]):
        _fail("a padding node owns a real edge slot")
    if np.any(distances[~real_e] != 0):
        _fail("padding edge slots carry nonzero distances")
    if not np.isfinite(distances).all():
        _fail("non-finite distances")
    real_n = node_mask > 0
    if not np.all(np.diff(node_mask.astype(np.int8)) <= 0):
        _fail("real nodes are not a contiguous prefix of the node slots")
    if np.any(np.diff(node_graph[real_n]) < 0):
        _fail("node_graph is not non-decreasing over real nodes")
    if real_n.any() and not graph_mask[node_graph[real_n]].all():
        _fail("a real node belongs to a padding graph slot")
    if batch.in_slots is not None:
        _check_transpose_mapping(batch, neighbors, real_e.reshape(-1), ncap)
    return batch


def maybe_check(batch, dense_m: int | None = None):
    """check_batch when globally enabled, else pass-through."""
    if _ENABLED:
        if hasattr(batch, "atom_idx"):
            check_compact_batch(batch, dense_m)
        else:
            check_batch(batch, dense_m)
    return batch


def check_stacked_batch(stacked, dense_m: int | None = None,
                        train: bool = False):
    """Validate a device-stacked batch ([D, ...] leaves) row by row.

    ``train=True`` additionally requires every device row to carry at
    least one real graph: ``empty_batch_like`` rows are an EVAL-ONLY
    padding device (psum-neutral metrics) — in a training step their
    zero gradients would silently dilute the pmean and their degenerate
    statistics would reach the BatchNorm EMA (the docstring contract
    this check enforces; see parallel/data_parallel.py).
    """
    import jax

    n_dev = int(np.shape(stacked.node_mask)[0])
    checker = (check_compact_batch if hasattr(stacked, "atom_idx")
               else check_batch)
    for d in range(n_dev):
        row = jax.tree_util.tree_map(lambda x, _d=d: x[_d], stacked)
        checker(row, dense_m)
        if train and float(np.asarray(row.graph_mask).sum()) == 0:
            _fail(
                f"device row {d} of a TRAINING batch has zero real graphs "
                f"(empty_batch_like is eval-only padding; training on it "
                f"dilutes pmean gradients)"
            )
    return stacked


def check_any(batch, dense_m: int | None = None, train: bool = False):
    """Dispatch on stacking: 1-D node_mask -> single batch, 2-D -> stacked.

    Single training batches cannot be empty by construction
    (batch_iterator never yields an empty pack), so ``train`` only adds
    the non-empty-row requirement for stacked batches.
    """
    if np.ndim(batch.node_mask) == 1:
        if hasattr(batch, "atom_idx"):
            return check_compact_batch(batch, dense_m)
        return check_batch(batch, dense_m)
    return check_stacked_batch(batch, dense_m, train=train)


def maybe_check_any(batch, dense_m: int | None = None, train: bool = False):
    if _ENABLED:
        check_any(batch, dense_m, train=train)
    return batch


def spot_check_graphs(graphs, k: int = 16):
    """Sample-validate CrystalGraphs (cache reload path: a bad/truncated
    cache file would otherwise surface as silent training corruption).

    Checks an evenly spaced sample of ``k`` graphs: index ranges, sorted
    centers (the pack-time no-op-sort assumption), finite features and
    labels, and per-array row-count consistency.
    """
    if not graphs:
        _fail("empty graph list")
    idx = np.unique(np.linspace(0, len(graphs) - 1, num=min(k, len(graphs)),
                                dtype=np.int64))
    for i in idx:
        g = graphs[int(i)]
        n, e = g.num_nodes, g.num_edges
        if len(g.edge_fea) != e or len(g.neighbors) != e:
            _fail(f"graph {g.cif_id!r}: edge array row counts disagree")
        if e:
            c, nb = np.asarray(g.centers), np.asarray(g.neighbors)
            if c.min() < 0 or c.max() >= n or nb.min() < 0 or nb.max() >= n:
                _fail(f"graph {g.cif_id!r}: edge endpoints out of range")
        if not np.isfinite(np.asarray(g.atom_fea)).all():
            _fail(f"graph {g.cif_id!r}: non-finite atom features")
        if not np.isfinite(np.asarray(g.edge_fea)).all():
            _fail(f"graph {g.cif_id!r}: non-finite edge features")
        if not np.isfinite(np.asarray(g.target, np.float64)).all():
            _fail(f"graph {g.cif_id!r}: non-finite target")
    return graphs


def maybe_spot_check_graphs(graphs, k: int = 16):
    if _ENABLED:
        spot_check_graphs(graphs, k)
    return graphs
