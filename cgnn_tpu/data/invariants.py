"""GraphBatch invariant checks (SURVEY.md §5 race-detection/sanitizers).

The jitted step trusts several non-local data-plane invariants that are
established at pack time and never re-checked (``indices_are_sorted`` is an
UNCHECKED promise to XLA's TPU scatter; ``gather_transpose``'s custom VJP is
only correct when the transpose mapping is complete). A corrupted batch —
a bug in a new iterator, a bad cache file, a miswired shard — would train
silently wrong. This module is the loud path: ``--check-invariants``
(train.py) enables validation of every packed batch at iterator exit;
``check_batch`` can also be called directly (tests, debugging).

Checks are host-side (numpy + chex static assertions) so they add zero
device work; cost is one pass over each batch's index arrays.
"""

from __future__ import annotations

import chex
import numpy as np

_ENABLED = False


def enable(on: bool = True) -> None:
    """Globally enable per-batch validation (the --check-invariants flag)."""
    global _ENABLED
    _ENABLED = on


def enabled() -> bool:
    return _ENABLED


class BatchInvariantError(AssertionError):
    pass


def _fail(msg: str):
    raise BatchInvariantError(msg)


def check_batch(batch, dense_m: int | None = None):
    """Validate one host-side GraphBatch; raises BatchInvariantError.

    Invariants (data/graph.py module docstring + pack_graphs):
    - shape/dtype consistency across leaves (chex);
    - masks are exactly {0, 1};
    - ``centers`` is non-decreasing (the sorted-scatter promise) and every
      real edge's endpoints are real, in-range node slots;
    - padding edges carry zero mask AND zero features;
    - ``node_graph`` is non-decreasing with real nodes pointing at real
      graph slots;
    - dense layout (``dense_m``): slot ownership centers[k] == k // M;
    - transpose slots: ``in_slots``/``in_mask`` list every real edge slot
      exactly once under its neighbor node — the completeness property
      gather_transpose's scatter-free backward silently relies on.
    """
    nodes = np.asarray(batch.nodes)
    edges = np.asarray(batch.flat_edges)
    centers = np.asarray(batch.centers)
    neighbors = np.asarray(batch.neighbors)
    node_graph = np.asarray(batch.node_graph)
    node_mask = np.asarray(batch.node_mask)
    edge_mask = np.asarray(batch.edge_mask)
    graph_mask = np.asarray(batch.graph_mask)

    ncap, ecap = nodes.shape[0], edges.shape[0]
    chex.assert_shape(centers, (ecap,))
    chex.assert_shape(neighbors, (ecap,))
    chex.assert_shape(edge_mask, (ecap,))
    chex.assert_shape(node_graph, (ncap,))
    chex.assert_shape(node_mask, (ncap,))
    chex.assert_type([centers, neighbors, node_graph], np.integer)

    for name, m in (("node_mask", node_mask), ("edge_mask", edge_mask),
                    ("graph_mask", graph_mask)):
        if not np.isin(m, (0.0, 1.0)).all():
            _fail(f"{name} contains values outside {{0, 1}}")

    if np.any(np.diff(centers) < 0):
        _fail("centers is not non-decreasing (sorted-scatter promise broken)")
    if centers.min(initial=0) < 0 or centers.max(initial=0) >= ncap:
        _fail("centers out of node-slot range")
    if neighbors.min(initial=0) < 0 or neighbors.max(initial=0) >= ncap:
        _fail("neighbors out of node-slot range")

    real_e = edge_mask > 0
    if real_e.any():
        if not node_mask[centers[real_e]].all():
            _fail("a real edge's center is a padding node")
        if not node_mask[neighbors[real_e]].all():
            _fail("a real edge's neighbor is a padding node")
    if np.any(np.abs(edges[~real_e]) > 0):
        _fail("padding edge slots carry nonzero features")

    real_n = node_mask > 0
    if not np.all(np.diff(node_mask) <= 0):
        _fail("real nodes are not a contiguous prefix of the node slots")
    if np.any(np.diff(node_graph[real_n]) < 0):
        _fail("node_graph is not non-decreasing over real nodes")
    if np.any(node_graph[~real_n] != 0):
        _fail("padding nodes must belong to graph slot 0")
    if real_n.any() and not graph_mask[node_graph[real_n]].all():
        _fail("a real node belongs to a padding graph slot")

    if dense_m is not None:
        owner = np.arange(ecap) // dense_m
        if not np.array_equal(centers, owner.astype(centers.dtype)):
            _fail(f"dense slot ownership broken: centers != slot//{dense_m}")

    if batch.in_slots is not None:
        in_mask = np.asarray(batch.in_mask)
        in_slots = np.asarray(batch.in_slots).reshape(in_mask.shape)
        if in_mask.shape[0] != ncap:
            _fail("in_slots/in_mask row count != node capacity")
        listed = in_slots[in_mask > 0]
        rows = np.repeat(np.arange(ncap), (in_mask > 0).sum(axis=1))
        if batch.over_slots is not None:
            over_slots = np.asarray(batch.over_slots)
            over_nodes = np.asarray(batch.over_nodes)
            over_mask = np.asarray(batch.over_mask)
            chex.assert_shape(over_nodes, over_slots.shape)
            chex.assert_shape(over_mask, over_slots.shape)
            if np.any(np.diff(over_nodes) < 0):
                _fail("over_nodes is not non-decreasing (sorted-scatter "
                      "promise broken)")
            listed = np.concatenate([listed, over_slots[over_mask > 0]])
            rows = np.concatenate([rows, over_nodes[over_mask > 0]])
        if listed.size != int(real_e.sum()):
            _fail(
                f"transpose mapping lists {listed.size} edges but the batch "
                f"has {int(real_e.sum())} real edges (gather_transpose "
                f"backward would drop/duplicate gradient)"
            )
        if listed.size:
            if np.unique(listed).size != listed.size:
                _fail("transpose mapping lists an edge slot twice")
            if not real_e[listed].all():
                _fail("transpose mapping lists a padding edge slot")
            if not np.array_equal(
                np.sort(listed), np.sort(np.nonzero(real_e)[0])
            ):
                _fail("transpose mapping misses a real edge slot")
            if not np.array_equal(neighbors[listed], rows):
                _fail("a transpose row lists an edge of a different neighbor")
    return batch


def maybe_check(batch, dense_m: int | None = None):
    """check_batch when globally enabled, else pass-through."""
    if _ENABLED:
        check_batch(batch, dense_m)
    return batch
