"""Periodic neighbor lists (in-tree; pymatgen/ase unavailable).

Replaces the reference's pymatgen ``get_all_neighbors`` radius search
(SURVEY.md §2 component 3, §3.1 hot path). Two implementations:

- ``neighbor_list_brute``: explicit-loop O(N^2 * images) reference used as the
  ground truth in tests (SURVEY.md §4.1).
- ``neighbor_list``: vectorized over all periodic images with chunking over
  center atoms to bound memory; the production host-side path. A C++
  cell-list backend can be swapped in behind the same signature for the
  offline preprocessor (SURVEY.md §7 phase 4).

Edges are returned in flat COO form: for each pair within ``radius``,
``centers[k]`` is the receiving atom i, ``neighbors[k]`` the source atom j,
``offsets[k]`` the integer image of j, and ``distances[k]`` = |r_j + offset@L
- r_i|. Self-pairs are excluded only in the home image (an atom can neighbor
its own periodic copies).
"""

from __future__ import annotations

import dataclasses
import math
import warnings

import numpy as np

from cgnn_tpu.data.structure import Structure


@dataclasses.dataclass
class NeighborList:
    centers: np.ndarray  # [E] int32, receiving atom i
    neighbors: np.ndarray  # [E] int32, source atom j
    distances: np.ndarray  # [E] float32
    offsets: np.ndarray  # [E, 3] int32, periodic image of j

    def __len__(self) -> int:
        return len(self.centers)


def _image_counts(lattice: np.ndarray, radius: float) -> tuple[int, int, int]:
    """Images needed per axis: ceil(radius / plane-spacing)."""
    inv = np.linalg.inv(lattice)
    # row-vector convention: spacing along axis k is 1 / ||inv[:, k]||
    return tuple(int(math.ceil(radius * np.linalg.norm(inv[:, k]) - 1e-12)) for k in range(3))


def neighbor_list_brute(structure: Structure, radius: float) -> NeighborList:
    """Explicit-loop reference implementation (tests only; O(N^2 * images))."""
    s = structure.wrapped()
    cart = s.cart_coords
    n = s.num_atoms
    na, nb, nc = _image_counts(s.lattice, radius)
    centers, neighbors, dists, offs = [], [], [], []
    for i in range(n):
        for j in range(n):
            for ia in range(-na, na + 1):
                for ib in range(-nb, nb + 1):
                    for ic in range(-nc, nc + 1):
                        if i == j and ia == 0 and ib == 0 and ic == 0:
                            continue
                        shift = np.array([ia, ib, ic], dtype=np.float64) @ s.lattice
                        d = float(np.linalg.norm(cart[j] + shift - cart[i]))
                        if d <= radius:
                            centers.append(i)
                            neighbors.append(j)
                            dists.append(d)
                            offs.append((ia, ib, ic))
    return NeighborList(
        np.asarray(centers, dtype=np.int32),
        np.asarray(neighbors, dtype=np.int32),
        np.asarray(dists, dtype=np.float32),
        np.asarray(offs, dtype=np.int32).reshape(-1, 3),
    )


def neighbor_list(
    structure: Structure,
    radius: float,
    chunk_elems: int = 8_000_000,
    backend: str = "auto",
) -> NeighborList:
    """Periodic radius search (production host path).

    backend='auto' uses the C++ kernel (cgnn_tpu.native) when a compiler is
    available and falls back to the vectorized numpy path; 'numpy'/'native'
    force one side ('native' raises if the library can't be built).
    """
    if radius <= 0:
        raise ValueError(f"radius must be positive, got {radius}")
    if backend not in ("auto", "numpy", "native"):
        raise ValueError(f"unknown backend {backend!r}")
    if backend in ("auto", "native"):
        from cgnn_tpu.native import neighbor_search_native

        result = neighbor_search_native(
            structure.lattice, structure.frac_coords, radius
        )
        if result is not None:
            c, nb, d, off = result
            return NeighborList(c, nb, d, off)
        if backend == "native":
            raise RuntimeError("native neighbor backend unavailable (no g++?)")
    s = structure.wrapped()
    cart = s.cart_coords  # [N, 3]
    n = s.num_atoms
    na, nb, nc = _image_counts(s.lattice, radius)
    grid = np.mgrid[-na : na + 1, -nb : nb + 1, -nc : nc + 1].reshape(3, -1).T
    shifts = grid.astype(np.float64) @ s.lattice  # [K, 3]
    k = len(grid)

    # positions of every image of every atom: [N*K, 3]
    img_pos = (cart[:, None, :] + shifts[None, :, :]).reshape(-1, 3)
    home = np.nonzero((grid == 0).all(axis=1))[0][0]

    centers_out, neighbors_out, dists_out, offs_out = [], [], [], []
    # chunk over center atoms so the [chunk, N*K] matrix stays bounded
    chunk = max(1, int(chunk_elems // max(1, n * k)))
    for start in range(0, n, chunk):
        stop = min(n, start + chunk)
        delta = img_pos[None, :, :] - cart[start:stop, None, :]  # [C, N*K, 3]
        dist = np.sqrt(np.einsum("cpk,cpk->cp", delta, delta))  # [C, N*K]
        ci, p = np.nonzero(dist <= radius)
        j = p // k
        img = p % k
        keep = ~((j == ci + start) & (img == home))  # drop home-image self pairs
        ci, j, img = ci[keep], j[keep], img[keep]
        centers_out.append((ci + start).astype(np.int32))
        neighbors_out.append(j.astype(np.int32))
        dists_out.append(dist[ci, p[keep]].astype(np.float32))
        offs_out.append(grid[img].astype(np.int32))

    return NeighborList(
        np.concatenate(centers_out) if centers_out else np.zeros(0, np.int32),
        np.concatenate(neighbors_out) if neighbors_out else np.zeros(0, np.int32),
        np.concatenate(dists_out) if dists_out else np.zeros(0, np.float32),
        np.concatenate(offs_out) if offs_out else np.zeros((0, 3), np.int32),
    )


def knn_neighbor_list(
    structure: Structure,
    radius: float,
    max_num_nbr: int,
    warn_under_coordinated: bool = True,
) -> NeighborList:
    """Radius search truncated to the ``max_num_nbr`` nearest per center.

    Mirrors the reference's sort/truncate behavior (SURVEY.md §2 component 3,
    default max_num_nbr=12): keeps the nearest M neighbors of each atom and
    warns when an atom has fewer than M within the radius (no fake padding
    edges are created — downstream batching handles ragged counts natively).
    """
    nl = neighbor_list(structure, radius)
    n = structure.num_atoms
    order = np.lexsort((nl.distances, nl.centers))
    centers = nl.centers[order]
    counts = np.bincount(centers, minlength=n)
    if warn_under_coordinated and np.any(counts < max_num_nbr):
        short = int((counts < max_num_nbr).sum())
        warnings.warn(
            f"{short}/{n} atoms have fewer than {max_num_nbr} neighbors within "
            f"radius {radius}; consider increasing the radius",
            stacklevel=2,
        )
    # rank of each edge within its center group (centers are sorted)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    rank = np.arange(len(centers)) - np.repeat(starts, counts)
    keep = rank < max_num_nbr
    sel = order[keep]
    return NeighborList(
        nl.centers[sel], nl.neighbors[sel], nl.distances[sel], nl.offsets[sel]
    )
