"""Compact device staging: stage raw atoms + distances, featurize on device.

A packed ``GraphBatch`` stages ~2.2 KB/node: one-hot-style atom rows
([N, 92] f32) and Gaussian-expanded edge features ([N, M, G] f32) dominate.
Both are pure functions of tiny raw data — atom rows are rows of a small
per-dataset vocabulary table, and edge features are a fixed radial basis of
the scalar distance (SURVEY.md §2 components 3-4). ``CompactBatch`` stages
the raw form instead (~180 B/node, ~12x less) and ``make_expander`` rebuilds
the exact ``GraphBatch`` INSIDE the jitted step, where the table gather and
``exp()`` fuse into the surrounding program at negligible cost next to the
conv matmuls.

Why this is the TPU-first shape of the problem (measured, round 5):
- host->device on this environment's tunneled chip runs ~36 MB/s, so the
  MP-146k device-resident epoch (~8.9 GB staged) pays ~250 s of first-epoch
  H2D; compact staging cuts that ~12x.
- HBM holds the compact form (~0.7 GB for MP-146k vs ~8.9 GB), so
  device-resident training scales to ~10x larger datasets per chip.
- host packing writes ~12x fewer bytes (the full-fidelity pack is
  page-fault-bound, not compute-bound).

Supported: the dense slot layout (``dense_m``) for energy / band-gap /
multi-task / classification models. The force task recomputes geometry
in-model from positions and does not read staged edge features at all
(models/forcefield.py); it keeps its own staging path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import numpy as np
from flax import struct

from cgnn_tpu.data.featurize import gaussian_expand
from cgnn_tpu.data.graph import GraphBatch, transpose_slots


class CompactUnsupported(ValueError):
    """The dataset cannot be staged compactly (caller should fall back to
    full-fidelity packing — this is a capability probe, not a failure)."""


class AtomVocab:
    """Per-dataset vocabulary of distinct atom-feature rows.

    The reference lineage draws atom features from a fixed per-element
    table (``atom_init.json``; data/elements.py here), so a dataset has at
    most ~100 distinct rows. The vocabulary is recovered from the data
    (hash rows, dedupe) rather than assumed, so any upstream featurizer
    works; datasets with effectively-continuous atom features overflow
    ``max_size`` and raise ``CompactUnsupported``.
    """

    def __init__(self, table: np.ndarray, hash_vec: np.ndarray,
                 hash_order: np.ndarray):
        self.table = table  # [V, D] f32
        self._hash_vec = hash_vec
        self._sorted_hashes = hash_order  # sorted row hashes, index-aligned
        self._sorted_to_idx: np.ndarray | None = None

    @classmethod
    def build(cls, graphs: Sequence, max_size: int = 4096) -> "AtomVocab":
        rng = np.random.default_rng(0x5EED)
        dim = graphs[0].atom_fea.shape[1]
        hv = rng.standard_normal(dim)
        seen: dict[float, np.ndarray] = {}
        for g in graphs:
            h = np.asarray(g.atom_fea, np.float64) @ hv
            # cache per graph: index lookup reuses these (pack time)
            g._vocab_hashes = h
            for hh in np.unique(h):
                if hh not in seen:
                    row = np.asarray(
                        g.atom_fea[np.argmax(h == hh)], np.float32
                    )
                    seen[float(hh)] = row
                    if len(seen) > max_size:
                        raise CompactUnsupported(
                            f"more than {max_size} distinct atom-feature "
                            f"rows; atom features look continuous — use "
                            f"full-fidelity staging"
                        )
        hashes = np.array(sorted(seen))
        table = np.stack([seen[float(h)] for h in hashes])
        return cls(table, hv, hashes)

    @property
    def size(self) -> int:
        return len(self.table)

    def indices(self, g) -> np.ndarray:
        """[N] i32 vocabulary index per atom (cached on the graph);
        verifies exact reconstruction (hash collisions raise loudly)."""
        idx = getattr(g, "_vocab_idx", None)
        if idx is None:
            h = getattr(g, "_vocab_hashes", None)
            if h is None:
                h = np.asarray(g.atom_fea, np.float64) @ self._hash_vec
            idx = np.searchsorted(self._sorted_hashes, h).astype(np.int32)
            if (
                idx.max(initial=0) >= self.size
                or not np.array_equal(
                    self.table[idx], np.asarray(g.atom_fea, np.float32)
                )
            ):
                raise CompactUnsupported(
                    f"graph {g.cif_id!r} has atom rows outside the "
                    f"vocabulary (hash collision or mixed featurizers)"
                )
            g._vocab_idx = idx
            if hasattr(g, "_vocab_hashes"):
                del g._vocab_hashes
        return idx


@dataclasses.dataclass(frozen=True)
class CompactSpec:
    """Everything the expander needs to rebuild GraphBatches on device."""

    vocab: AtomVocab
    gauss_filter: np.ndarray  # [G] f32 mu grid
    gauss_var: float
    dense_m: int
    edge_dtype: Any = np.float32

    def __post_init__(self):
        # identity token for per-graph probe verdicts: a verdict cached
        # under spec A must never be read by spec B (different
        # checkpoint/vocabulary in the same process). The token object
        # is retained by every cache entry that references it, so its
        # identity can never be recycled into a false match.
        object.__setattr__(self, "_probe_token", object())

    def graph_compactable(self, g, atol: float = 1e-5,
                          sample_edges: int = 32) -> bool:
        """Can THIS graph be staged compactly under this spec?

        The dataset-level ``build`` probe validates a sample; serving
        admits arbitrary per-request graphs, so each one is checked
        individually (and the verdict cached on the graph, keyed to this
        spec): raw distances present and consistent, atom rows inside
        the vocabulary, and the stored edge features equal to the
        Gaussian expansion of the distances — so a client-supplied graph
        whose ``edge_fea`` disagrees with its ``distances`` is staged
        full-fidelity instead of silently answered from different edges.
        The feature check verifies an evenly spaced sample of
        ``sample_edges`` edges: featurization mismatches (wrong
        radius/step, different featurizer) are global and any sample
        catches them, while a full O(E x G) expansion per request would
        tax the submit path with a meaningful fraction of the very cost
        compact staging removes. Never raises.
        """
        cached = getattr(g, "_compact_ok", None)
        if cached is not None and cached[0] is self._probe_token:
            return cached[1]
        ok = False
        try:
            if (
                g.distances is not None
                and len(g.distances) == g.num_edges
                and np.ndim(g.edge_fea) == 2
                and g.edge_fea.shape[1] == len(self.gauss_filter)
            ):
                self.vocab.indices(g)  # raises CompactUnsupported if not
                d = np.asarray(g.distances, np.float32)
                step = max(1, len(d) // sample_edges)
                idx = np.arange(0, len(d), step)[:sample_edges]
                want = gaussian_expand(d[idx], self.gauss_filter,
                                       self.gauss_var)
                ok = np.allclose(
                    np.asarray(g.edge_fea, np.float32)[idx], want,
                    atol=atol,
                )
        except (CompactUnsupported, ValueError, TypeError):
            ok = False
        try:
            g._compact_ok = (self._probe_token, ok)
        except AttributeError:  # frozen/slotted graph: just skip the cache
            pass
        return ok

    @classmethod
    def build(cls, graphs: Sequence, gdf, dense_m: int,
              edge_dtype=np.float32, validate_k: int = 8) -> "CompactSpec":
        """Probe a dataset for compact stageability.

        ``gdf`` is the GaussianDistance the caller believes featurized the
        dataset; a sample of graphs is re-expanded and compared against the
        stored edge features, so a stale cache featurized with different
        parameters raises instead of training on silently different edges.
        """
        if not graphs:
            raise CompactUnsupported("empty graph list")
        if any(g.distances is None for g in graphs):
            raise CompactUnsupported(
                "graphs carry no raw distances (old cache format?)"
            )
        step = max(1, len(graphs) // validate_k)
        for g in graphs[:: step][:validate_k]:
            want = np.asarray(g.edge_fea, np.float32)
            got = gdf.expand(g.distances)
            if want.shape != got.shape or not np.allclose(
                want, got, atol=1e-5
            ):
                raise CompactUnsupported(
                    f"graph {g.cif_id!r}: edge features do not match the "
                    f"Gaussian expansion of stored distances (dataset "
                    f"featurized with different radius/step?)"
                )
        vocab = AtomVocab.build(graphs)
        return cls(vocab, np.asarray(gdf.filter, np.float32),
                   float(gdf.var), int(dense_m), edge_dtype)


class CompactBatch(struct.PyTreeNode):
    """Raw-form packed batch (dense slot layout; device-side pytree).

    Same slot geometry and invariants as the GraphBatch that
    ``make_expander`` rebuilds from it: node slot ``n`` owns edge slots
    ``[n*M, (n+1)*M)``, masks zero on padding, ``in_slots``/``over_*``
    identical to ``pack_graphs`` (shared ``transpose_slots``).
    """

    atom_idx: Any  # [Ncap] i32 vocabulary row per node
    distances: Any  # [Ncap, M] f32 (0 on padding slots)
    neighbors: Any  # [Ncap*M] i32 (padding: own node)
    edge_mask: Any  # [Ncap, M] u8
    node_graph: Any  # [Ncap] i32
    node_mask: Any  # [Ncap] u8
    graph_mask: Any  # [Gcap] f32
    targets: Any  # [Gcap, T] f32
    target_mask: Any  # [Gcap, T] f32
    in_slots: Any = None  # [Ncap*M] i32 (two-tier tier 1)
    in_mask: Any = None  # [Ncap, M] u8
    over_slots: Any = None  # [O] i32
    over_nodes: Any = None  # [O] i32
    over_mask: Any = None  # [O] u8

    # PaddingStats/driver interface parity with GraphBatch
    @property
    def node_capacity(self) -> int:
        return self.atom_idx.shape[0]

    @property
    def edge_capacity(self) -> int:
        return self.distances.shape[0] * self.distances.shape[1]

    @property
    def graph_capacity(self) -> int:
        return self.targets.shape[0]


def compact_shape_key(batch: CompactBatch) -> tuple:
    """Hashable full-shape key (the batch_shape_key analog)."""
    return (
        "compact",
        np.shape(batch.distances),
        np.shape(batch.targets),
        None if batch.in_slots is None else np.shape(batch.in_slots),
        None if batch.over_slots is None else np.shape(batch.over_slots),
    )


def compact_buffer_key(node_cap: int, dense_m: int, graph_cap: int,
                       tdim: int) -> tuple:
    """Pool key for reusable compact staging buffers (data/pipeline.py
    ``BufferPool``): one free-list per distinct buffer geometry."""
    return ("compact", node_cap, dense_m, graph_cap, tdim)


def alloc_compact_buffers(node_cap: int, dense_m: int, graph_cap: int,
                          tdim: int) -> CompactBatch:
    """Freshly allocate one forward-only (no transpose slots) compact
    staging buffer set — the ``BufferPool`` factory for
    ``pack_compact(out=...)``."""
    return CompactBatch(
        atom_idx=np.zeros(node_cap, np.int32),
        distances=np.zeros((node_cap, dense_m), np.float32),
        neighbors=np.zeros(node_cap * dense_m, np.int32),
        edge_mask=np.zeros((node_cap, dense_m), np.uint8),
        node_graph=np.zeros(node_cap, np.int32),
        node_mask=np.zeros(node_cap, np.uint8),
        graph_mask=np.zeros(graph_cap, np.float32),
        targets=np.zeros((graph_cap, tdim), np.float32),
        target_mask=np.zeros((graph_cap, tdim), np.float32),
    )


# base dense neighbor pattern (slot k -> its owning node k // M), cached
# per shape: recomputing it per batch is an avoidable fresh allocation on
# the packer's critical path
_BASE_NEIGHBORS: dict[tuple[int, int], np.ndarray] = {}


def _base_neighbors(node_cap: int, dense_m: int) -> np.ndarray:
    base = _BASE_NEIGHBORS.get((node_cap, dense_m))
    if base is None:
        base = (np.arange(node_cap * dense_m, dtype=np.int32)
                // dense_m).astype(np.int32)
        base.setflags(write=False)
        _BASE_NEIGHBORS[(node_cap, dense_m)] = base
    return base


def pack_compact(
    graphs: Sequence,
    node_cap: int,
    edge_cap: int,
    graph_cap: int,
    spec: CompactSpec,
    num_targets: int | None = None,
    dense_m: int | None = None,
    in_cap: int | None = None,
    over_cap: int | None = None,
    edge_dtype=None,  # accepted for pack_fn signature parity; spec wins
    out: CompactBatch | None = None,
) -> CompactBatch:
    """pack_graphs' compact twin: same slot geometry, raw-form payload.

    Raises the same ``TransposeOverflowError`` on two-tier overflow so
    ``_pack_overflow_safe``'s split-don't-abort recovery applies unchanged.

    ``out`` (forward-only batches) recycles a previously allocated buffer
    set (``alloc_compact_buffers``) instead of allocating fresh arrays:
    PERF.md §7 measured fresh zeros page-faulting at ~0.2 GB/s effective,
    so reuse turns the pack's output writes into stores to already-mapped
    pages. The returned batch ALIASES ``out``'s arrays — hand the buffer
    back to its pool only after the device has consumed the dispatch that
    read it. Bit-identical to a fresh pack (pinned by test).
    """
    dense_m = dense_m if dense_m is not None else spec.dense_m
    if dense_m is None:
        raise ValueError("compact staging requires the dense layout")
    if edge_cap != node_cap * dense_m:
        raise ValueError(
            f"dense layout requires edge_cap == node_cap * dense_m "
            f"({node_cap} * {dense_m} != {edge_cap})"
        )
    if not graphs:
        raise ValueError("cannot pack an empty graph list")
    if out is not None and (in_cap or over_cap is not None):
        raise ValueError("buffer reuse (out=) is forward-only: transpose "
                         "slots are not pooled")
    n_graphs = len(graphs)
    if n_graphs > graph_cap:
        raise ValueError(f"{n_graphs} graphs exceed graph_cap={graph_cap}")
    nn_arr = np.fromiter((g.num_nodes for g in graphs), np.int64, n_graphs)
    ne_arr = np.fromiter((g.num_edges for g in graphs), np.int64, n_graphs)
    node_offs = np.zeros(n_graphs + 1, np.int64)
    np.cumsum(nn_arr, out=node_offs[1:])
    total_nodes = int(node_offs[-1])
    total_edges = int(ne_arr.sum())
    if total_nodes > node_cap:
        raise ValueError(
            f"batch ({total_nodes} nodes) exceeds node_cap={node_cap}"
        )
    tdim = num_targets or int(np.atleast_1d(graphs[0].target).shape[0])

    if out is not None:
        want = (node_cap, dense_m, graph_cap, tdim)
        got = (out.atom_idx.shape[0], out.distances.shape[1],
               out.targets.shape[0], out.targets.shape[1])
        if want != got:
            raise ValueError(
                f"out buffer geometry {got} does not match the requested "
                f"pack {want} (pool keyed by compact_buffer_key?)"
            )
        atom_idx, node_graph, node_mask = (
            out.atom_idx, out.node_graph, out.node_mask
        )
        # only the padding tail needs zeroing: [:total_nodes] is fully
        # overwritten below (bit-parity with the fresh-zeros path)
        atom_idx[total_nodes:] = 0
        node_graph[total_nodes:] = 0
        node_mask[total_nodes:] = 0
    else:
        atom_idx = np.zeros(node_cap, np.int32)
        node_graph = np.zeros(node_cap, np.int32)
        node_mask = np.zeros(node_cap, np.uint8)
    np.concatenate([spec.vocab.indices(g) for g in graphs],
                   out=atom_idx[:total_nodes])
    node_graph[:total_nodes] = np.repeat(
        np.arange(n_graphs, dtype=np.int32), nn_arr
    )
    node_mask[:total_nodes] = 1

    e_node_off = np.repeat(node_offs[:-1], ne_arr)
    gcent = np.concatenate([g.centers for g in graphs]).astype(np.int64)
    gcent += e_node_off
    gnbr = np.concatenate([g.neighbors for g in graphs]).astype(np.int64)
    gnbr += e_node_off
    dist = np.concatenate([g.distances for g in graphs]).astype(np.float32)
    if not np.all(gcent[1:] >= gcent[:-1]):
        order = np.argsort(gcent, kind="stable")
        gcent, gnbr, dist = gcent[order], gnbr[order], dist[order]

    counts = np.bincount(gcent, minlength=node_cap)
    worst = int(counts.max(initial=0))
    if worst > dense_m:
        bad = int(np.argmax(counts))
        gi = int(np.searchsorted(node_offs, bad, side="right")) - 1
        raise ValueError(
            f"graph {graphs[gi].cif_id!r} has a node with {worst} edges "
            f"> dense_m={dense_m}; featurize with max_num_nbr <= dense_m"
        )
    within = np.arange(total_edges) - (np.cumsum(counts) - counts)[gcent]
    slots = gcent * dense_m + within
    starts = np.cumsum(counts) - counts
    src = starts[:, None] + np.arange(dense_m)
    grid_valid = np.arange(dense_m) < counts[:, None]
    np.copyto(src, total_edges, where=~grid_valid)
    dist_pad = np.concatenate([dist, np.zeros(1, np.float32)])
    if out is not None:
        distances, edge_mask, neighbors = (
            out.distances, out.edge_mask, out.neighbors
        )
        # every slot of all three is overwritten: take covers the full
        # [node_cap, M] grid (padding slots read the appended 0), the
        # mask copies the full grid, neighbors resets to the base
        # pattern before the real-edge scatter
        np.take(dist_pad, src, mode="clip", out=distances)
        np.copyto(edge_mask, grid_valid, casting="unsafe")
        np.copyto(neighbors, _base_neighbors(node_cap, dense_m))
    else:
        distances = np.take(dist_pad, src, mode="clip")  # [node_cap, M]
        edge_mask = grid_valid.astype(np.uint8)
        neighbors = _base_neighbors(node_cap, dense_m).copy()
    neighbors[slots] = gnbr.astype(np.int32)

    if out is not None:
        graph_mask, targets, target_mask = (
            out.graph_mask, out.targets, out.target_mask
        )
        graph_mask[n_graphs:] = 0.0
        targets.fill(0.0)  # ragged target widths: no full overwrite below
        target_mask.fill(0.0)
    else:
        graph_mask = np.zeros(graph_cap, np.float32)
        targets = np.zeros((graph_cap, tdim), np.float32)
        target_mask = np.zeros((graph_cap, tdim), np.float32)
    graph_mask[:n_graphs] = 1.0
    tgt = [np.atleast_1d(np.asarray(g.target, np.float32)) for g in graphs]
    if all(len(t) == len(tgt[0]) for t in tgt):
        tw = len(tgt[0])
        targets[:n_graphs, :tw] = np.stack(tgt)
        masks = [g.target_mask for g in graphs]
        if all(m is None for m in masks):
            target_mask[:n_graphs, :tw] = 1.0
        else:
            target_mask[:n_graphs, :tw] = np.stack([
                np.ones(tw, np.float32) if m is None
                else np.broadcast_to(np.atleast_1d(m), (tw,))
                for m in masks
            ])
    else:
        for gi, (g, t) in enumerate(zip(graphs, tgt)):
            targets[gi, : len(t)] = t
            if g.target_mask is not None:
                target_mask[gi, : len(t)] = np.atleast_1d(g.target_mask)
            else:
                target_mask[gi, : len(t)] = 1.0

    in_slots = in_mask = over_slots = over_nodes = over_mask = None
    if in_cap is not None and over_cap is not None:
        raise ValueError("in_cap and over_cap are mutually exclusive")
    if in_cap == 0:  # explicit disable (eval-only batches: no backward)
        in_cap = None
    if in_cap is not None or over_cap is not None:
        in_slots, in_mask, over_slots, over_nodes, over_mask = (
            transpose_slots(
                neighbors, edge_mask.reshape(-1) > 0, node_cap, dense_m,
                in_cap, over_cap,
            )
        )

    return CompactBatch(
        atom_idx=atom_idx,
        distances=distances,
        neighbors=neighbors,
        edge_mask=edge_mask,
        node_graph=node_graph,
        node_mask=node_mask,
        graph_mask=graph_mask,
        targets=targets,
        target_mask=target_mask,
        in_slots=in_slots,
        in_mask=in_mask,
        over_slots=over_slots,
        over_nodes=over_nodes,
        over_mask=over_mask,
    )


def make_expander(spec: CompactSpec) -> Callable[[CompactBatch], GraphBatch]:
    """Jit-composable CompactBatch -> GraphBatch reconstruction.

    Numerics: identical to pack_graphs except edge features go through
    ``jnp.exp`` instead of ``np.exp`` (<= 1 ulp f32 difference, washed out
    by the bf16 compute cast). Geometry fields come back ``None`` — the
    energy-family models never read them (models/cgcnn.py), and staging
    zeros for them would defeat the point.
    """
    import jax.numpy as jnp

    table = np.asarray(spec.vocab.table, np.float32)
    mu = np.asarray(spec.gauss_filter, np.float32)
    inv_var2 = np.float32(1.0 / spec.gauss_var**2)
    edge_dtype = spec.edge_dtype

    def expand(cb: CompactBatch) -> GraphBatch:
        n, m = cb.distances.shape
        node_mask = cb.node_mask.astype(jnp.float32)
        nodes = jnp.asarray(table)[cb.atom_idx] * node_mask[:, None]
        emask = cb.edge_mask.astype(jnp.float32)
        d = cb.distances[..., None]
        efea = jnp.exp(-((d - jnp.asarray(mu)) ** 2) * inv_var2)
        efea = (efea * emask[..., None]).astype(edge_dtype)
        centers = jnp.arange(n * m, dtype=jnp.int32) // m
        return GraphBatch(
            nodes=nodes,
            edges=efea,
            centers=centers,
            neighbors=cb.neighbors,
            node_graph=cb.node_graph,
            node_mask=node_mask,
            edge_mask=emask.reshape(-1),
            graph_mask=cb.graph_mask,
            targets=cb.targets,
            target_mask=cb.target_mask,
            positions=None,
            lattices=None,
            edge_offsets=None,
            node_targets=None,
            in_slots=cb.in_slots,
            in_mask=cb.in_mask,
            over_slots=cb.over_slots,
            over_nodes=cb.over_nodes,
            over_mask=cb.over_mask,
        )

    return expand


def compact_pack_fn(spec: CompactSpec) -> Callable:
    """Adapter matching the ``pack_fn`` signature batch_iterator threads to
    ``_pack_overflow_safe`` (pack_graphs-compatible keyword set)."""

    def pack(graphs, node_cap, edge_cap, graph_cap, **kw):
        return pack_compact(graphs, node_cap, edge_cap, graph_cap, spec,
                            **kw)

    return pack
