"""On-disk trajectory datasets for the force task (BASELINE config #5).

The reference's MD17 config is a file-based trajectory dataset (BASELINE.json
config #5: "per-atom force head on MD17 trajectories"); this module defines
the rebuild's on-disk contract: ONE ``.npz`` FILE PER TRAJECTORY, accepted in
either of two key conventions:

native (written by :func:`save_trajectory_npz`)::

    positions [T, N, 3] float   cartesian coordinates, Å
    numbers   [N]       int     atomic numbers
    energy    [T]       float   total energy per frame
    forces    [T, N, 3] float   per-atom forces
    lattice   [3, 3] or [T, 3, 3] float   OPTIONAL periodic cell; when
              absent a per-frame vacuum box is synthesized (gas-phase
              molecules — the MD17 regime)

MD17/sGDML public convention (so published MD17 ``.npz`` downloads load
unchanged)::

    R [T, N, 3], z [N], E [T] or [T, 1], F [T, N, 3]      (no lattice)

Splitting policy (:func:`split_trajectory_groups`): frames of one MD
trajectory are heavily time-autocorrelated, so shuffling frames across
train/val/test leaks. With >= 3 trajectories the split is BY TRAJECTORY
(whole files per split); below that each trajectory is cut into CONTIGUOUS
time blocks so adjacent frames stay within one split.
"""

from __future__ import annotations

import os
import warnings
from typing import Sequence

import numpy as np

from cgnn_tpu.data.graph import CrystalGraph
from cgnn_tpu.data.structure import Structure


def save_trajectory_npz(
    path: str,
    positions: np.ndarray,
    numbers: np.ndarray,
    energies: np.ndarray,
    forces: np.ndarray,
    lattice: np.ndarray | None = None,
) -> None:
    """Write one trajectory in the native key convention (see module doc)."""
    arrays = {
        "positions": np.asarray(positions, np.float32),
        "numbers": np.asarray(numbers, np.int32),
        "energy": np.asarray(energies, np.float32),
        "forces": np.asarray(forces, np.float32),
    }
    if lattice is not None:
        arrays["lattice"] = np.asarray(lattice, np.float32)
    np.savez_compressed(path, **arrays)


def load_trajectory_npz(path: str) -> dict:
    """Read a trajectory ``.npz`` into canonical keys, validating shapes.

    Returns ``{"positions", "numbers", "energy", "forces", "lattice"}``
    (``lattice`` may be None). Both key conventions are accepted; anything
    else raises ``ValueError`` naming the file and what was found.
    """
    with np.load(path) as z:
        keys = set(z.files)
        if {"positions", "numbers", "energy", "forces"} <= keys:
            pos = np.asarray(z["positions"], np.float64)
            numbers = np.asarray(z["numbers"], np.int32).ravel()
            energy = np.asarray(z["energy"], np.float64).reshape(-1)
            forces = np.asarray(z["forces"], np.float64)
            lattice = (
                np.asarray(z["lattice"], np.float64) if "lattice" in keys
                else None
            )
        elif {"R", "z", "E", "F"} <= keys:  # MD17/sGDML convention
            pos = np.asarray(z["R"], np.float64)
            numbers = np.asarray(z["z"], np.int32).ravel()
            energy = np.asarray(z["E"], np.float64).reshape(-1)
            forces = np.asarray(z["F"], np.float64)
            lattice = None
        else:
            raise ValueError(
                f"{path}: unrecognized trajectory keys {sorted(keys)}; "
                f"expected positions/numbers/energy/forces (native) or "
                f"R/z/E/F (MD17)"
            )
    if pos.ndim != 3 or pos.shape[-1] != 3:
        raise ValueError(f"{path}: positions must be [T, N, 3], got {pos.shape}")
    t, n = pos.shape[:2]
    if len(numbers) != n:
        raise ValueError(
            f"{path}: {len(numbers)} atomic numbers for {n} position columns"
        )
    if len(energy) != t:
        raise ValueError(f"{path}: {len(energy)} energies for {t} frames")
    if forces.shape != pos.shape:
        raise ValueError(
            f"{path}: forces shape {forces.shape} != positions {pos.shape}"
        )
    if lattice is not None:
        if lattice.shape == (3, 3):
            lattice = np.broadcast_to(lattice, (t, 3, 3))
        elif lattice.shape != (t, 3, 3):
            raise ValueError(
                f"{path}: lattice must be [3,3] or [T,3,3], got {lattice.shape}"
            )
    return {
        "positions": pos,
        "numbers": numbers,
        "energy": energy,
        "forces": forces,
        "lattice": lattice,
    }


def _vacuum_box(cart: np.ndarray, margin: float) -> tuple[np.ndarray, np.ndarray]:
    """(lattice [3,3], frac [N,3]) placing a molecule in a diagonal box.

    Each box side is the position extent plus ``2 * margin``, with the
    molecule centered; any periodic image of any atom is therefore at
    least ``2 * margin`` away, so with ``margin >= radius`` the periodic
    neighbor machinery reduces to open boundaries exactly.
    """
    lo = cart.min(axis=0)
    span = cart.max(axis=0) - lo
    side = span + 2.0 * margin
    lattice = np.diag(side)
    frac = (cart - lo + margin) / side
    return lattice, frac


def trajectory_graphs(
    path: str,
    cfg,
    stride: int = 1,
    limit: int | None = None,
) -> list[CrystalGraph]:
    """One trajectory file -> featurized CrystalGraphs with force labels.

    Graphs keep geometry (positions/lattice/offsets) so the differentiable
    force model recomputes distances in-model (models/forcefield.py), and
    carry per-atom ``forces`` for the composite loss. ``cif_id`` is
    ``"{filename-stem}/{frame:05d}"``.
    """
    from cgnn_tpu.data.dataset import featurize_structure

    data = load_trajectory_npz(path)
    gdf = cfg.gdf()
    stem = os.path.splitext(os.path.basename(path))[0]
    graphs: list[CrystalGraph] = []
    frames = range(0, data["positions"].shape[0], max(1, stride))
    for k in frames:
        if limit is not None and len(graphs) >= limit:
            break
        cart = data["positions"][k]
        if data["lattice"] is not None:
            lat = data["lattice"][k]
            frac = cart @ np.linalg.inv(lat)
        else:
            # vacuum box with a >= radius margin: periodic images stay out
            # of neighbor range, so the crystal pipeline handles gas-phase
            # molecules without an open-boundary special case
            lat, frac = _vacuum_box(cart, margin=max(cfg.radius, 4.0))
        s = Structure(lat, frac, data["numbers"])
        g = featurize_structure(
            s, float(data["energy"][k]), cfg, f"{stem}/{k:05d}", gdf,
            keep_geometry=True,
        )
        g.forces = data["forces"][k].astype(np.float32)
        graphs.append(g)
    return graphs


def is_trajectory_path(path: str) -> bool:
    """True when ``path`` is a trajectory ``.npz`` or a directory holding some."""
    if path.endswith(".npz"):
        return os.path.isfile(path)
    if os.path.isdir(path):
        return any(f.endswith(".npz") for f in os.listdir(path))
    return False


def load_trajectory_root(
    root: str, cfg, stride: int = 1
) -> list[list[CrystalGraph]]:
    """Directory of ``*.npz`` (or one file) -> graphs GROUPED BY TRAJECTORY.

    The grouping is the unit of :func:`split_trajectory_groups`; flatten with
    ``[g for grp in groups for g in grp]`` when splits are not needed.
    """
    if os.path.isfile(root):
        paths = [root]
    else:
        paths = sorted(
            os.path.join(root, f)
            for f in os.listdir(root)
            if f.endswith(".npz")
        )
    if not paths:
        raise FileNotFoundError(f"no trajectory .npz files under {root}")
    groups = [trajectory_graphs(p, cfg, stride=stride) for p in paths]
    if not any(groups):
        raise ValueError(f"trajectory files under {root} contain no frames")
    return [g for g in groups if g]


def regroup_by_trajectory(graphs: Sequence) -> list[list] | None:
    """Rebuild trajectory grouping from ``"stem/frame"`` cif_ids.

    Graph caches (data/cache.py) flatten the grouping; the ids keep it.
    Returns None when any id lacks the separator (non-trajectory data) —
    callers then fall back to the generic split.
    """
    if not graphs or not all("/" in g.cif_id for g in graphs):
        return None
    groups: dict[str, list] = {}
    for g in graphs:
        groups.setdefault(g.cif_id.rsplit("/", 1)[0], []).append(g)
    return list(groups.values())


def split_trajectory_groups(
    groups: Sequence[list],
    train_ratio: float = 0.8,
    val_ratio: float = 0.1,
    seed: int = 0,
) -> tuple[list, list, list]:
    """Leak-aware train/val/test split (see module docstring for policy).

    With >= 3 trajectories: whole trajectories per split — each split with
    a nonzero quota is seeded with one trajectory (in seeded shuffle
    order) so none it owes frames to is empty, the rest go greedily to
    the split furthest below its frame-count quota. A zero-ratio split
    (e.g. ``val_ratio=0``) is never seeded and receives nothing.

    Whole-trajectory granularity means the realized frame fractions can
    deviate from the requested ratios by up to one trajectory's worth of
    frames per split — substantial when trajectory lengths are very
    unequal. A UserWarning reports the realized fractions whenever any
    split lands more than 5 points (0.05 absolute) off its quota.

    With 1-2 trajectories: contiguous time blocks within each.
    """
    if train_ratio + val_ratio >= 1.0 + 1e-9:
        raise ValueError("train_ratio + val_ratio must leave room for test")
    if len(groups) < 3:
        train: list = []
        val: list = []
        test: list = []
        for grp in groups:
            n = len(grp)
            n_tr = int(n * train_ratio)
            n_va = int(n * val_ratio)
            train += grp[:n_tr]
            val += grp[n_tr : n_tr + n_va]
            test += grp[n_tr + n_va :]
        return train, val, test
    order = np.random.default_rng(seed).permutation(len(groups))
    total = float(sum(len(g) for g in groups))
    quota = (train_ratio, val_ratio, 1.0 - train_ratio - val_ratio)
    seeds = [j for j in range(3) if quota[j] > 1e-9]
    splits: tuple[list, list, list] = ([], [], [])
    for k, i in enumerate(order):
        grp = groups[int(i)]
        if k < len(seeds):
            j = seeds[k]  # seed each owed split with one trajectory
        else:
            deficits = [
                quota[j] - len(splits[j]) / total
                if quota[j] > 1e-9 else -np.inf
                for j in range(3)
            ]
            j = int(np.argmax(deficits))
        splits[j].extend(grp)
    realized = tuple(len(s) / total for s in splits)
    if any(abs(realized[j] - quota[j]) > 0.05 for j in range(3)):
        warnings.warn(
            "whole-trajectory split deviates from requested ratios: "
            f"realized train/val/test = {realized[0]:.3f}/{realized[1]:.3f}/"
            f"{realized[2]:.3f} vs requested {quota[0]:.3f}/{quota[1]:.3f}/"
            f"{quota[2]:.3f} (granularity is one trajectory)",
            stacklevel=2,
        )
    return splits
