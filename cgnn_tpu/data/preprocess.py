"""Offline preprocessor CLI: CIF directory -> graph cache.

    python -m cgnn_tpu.data.preprocess DATA_DIR -o graphs.npz [-j N] [flags]

The once-per-dataset step that replaces the reference's per-epoch
DataLoader-worker featurization (SURVEY.md §7 phase 4). train.py consumes
the cache via ``--cache``.
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("root_dir")
    p.add_argument("-o", "--out", required=True, help="output .npz cache path")
    p.add_argument("-j", "--workers", type=int, default=0, help="0 = all cores")
    p.add_argument("--radius", type=float, default=8.0)
    p.add_argument("--max-num-nbr", type=int, default=12)
    p.add_argument("--dmin", type=float, default=0.0)
    p.add_argument("--step", type=float, default=0.2)
    p.add_argument("--keep-geometry", action="store_true",
                   help="store positions/lattices/offsets (force training)")
    args = p.parse_args(argv)

    from cgnn_tpu.data.cache import featurize_directory_parallel, save_graph_cache
    from cgnn_tpu.data.dataset import FeaturizeConfig

    cfg = FeaturizeConfig(
        radius=args.radius, max_num_nbr=args.max_num_nbr,
        dmin=args.dmin, step=args.step,
    )
    t0 = time.perf_counter()
    graphs, failures = featurize_directory_parallel(
        args.root_dir, cfg, workers=args.workers or None,
        keep_geometry=args.keep_geometry,
    )
    dt = time.perf_counter() - t0
    for cif_id, err in failures[:20]:
        print(f"skipped {cif_id}: {err}", file=sys.stderr)
    if len(failures) > 20:
        print(f"... and {len(failures) - 20} more failures", file=sys.stderr)
    if not graphs:
        print("no usable structures", file=sys.stderr)
        return 1
    save_graph_cache(graphs, args.out)
    print(
        f"featurized {len(graphs)} structures in {dt:.1f}s "
        f"({len(graphs) / max(dt, 1e-9):.0f} structs/s) -> {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
