"""Data layer: structures, featurization, neighbor lists, graph batching.

TPU-native replacement for the reference's ``data.py`` + ``atom_init.json``
pipeline (SURVEY.md §1 "Data layer", §2 components 3-5, 12). pymatgen / ase /
spglib are not available in this environment, so CIF parsing, the periodic
neighbor list, and the batched-graph container are implemented in-tree.
"""

from cgnn_tpu.data.structure import Structure, lattice_from_parameters
from cgnn_tpu.data.elements import atom_features, ATOM_FEA_DIM
from cgnn_tpu.data.featurize import GaussianDistance
from cgnn_tpu.data.cif import parse_cif, parse_cif_file
from cgnn_tpu.data.neighbors import (
    neighbor_list_brute,
    neighbor_list,
    knn_neighbor_list,
)
from cgnn_tpu.data.graph import CrystalGraph, GraphBatch, pack_graphs, pad_batch
from cgnn_tpu.data.synthetic import random_structure, synthetic_dataset
from cgnn_tpu.data.cache import (
    save_graph_cache,
    load_graph_cache,
    featurize_directory_parallel,
)
from cgnn_tpu.data.loader import prefetch_to_device
from cgnn_tpu.data.pipeline import BufferPool, PackError, parallel_pack
from cgnn_tpu.data.rawbatch import (
    RawBatch,
    RawSpec,
    RawStructure,
    pack_raw,
    plan_raw_spec,
    raw_from_graph,
)

__all__ = [
    "Structure",
    "lattice_from_parameters",
    "atom_features",
    "ATOM_FEA_DIM",
    "GaussianDistance",
    "parse_cif",
    "parse_cif_file",
    "neighbor_list_brute",
    "neighbor_list",
    "knn_neighbor_list",
    "CrystalGraph",
    "GraphBatch",
    "pack_graphs",
    "pad_batch",
    "random_structure",
    "synthetic_dataset",
    "save_graph_cache",
    "load_graph_cache",
    "featurize_directory_parallel",
    "prefetch_to_device",
    "BufferPool",
    "PackError",
    "parallel_pack",
    "RawBatch",
    "RawSpec",
    "RawStructure",
    "pack_raw",
    "plan_raw_spec",
    "raw_from_graph",
]
