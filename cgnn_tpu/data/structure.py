"""Minimal periodic crystal structure container (pymatgen is unavailable).

Holds a 3x3 row-vector lattice, fractional coordinates, and atomic numbers.
This replaces the reference lineage's dependency on pymatgen ``Structure``
(SURVEY.md §1 "Data layer"); only the operations the pipeline needs are
implemented: lattice construction from cell parameters, frac<->cart
conversion, and validation.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from cgnn_tpu.data.elements import SYMBOL_TO_Z


def lattice_from_parameters(
    a: float, b: float, c: float, alpha: float, beta: float, gamma: float
) -> np.ndarray:
    """Cell parameters (Å, degrees) -> 3x3 row-vector lattice matrix.

    Standard crystallographic convention: a along x; b in the xy plane.
    """
    alpha_r, beta_r, gamma_r = (math.radians(x) for x in (alpha, beta, gamma))
    cos_a, cos_b, cos_g = math.cos(alpha_r), math.cos(beta_r), math.cos(gamma_r)
    sin_g = math.sin(gamma_r)
    if abs(sin_g) < 1e-12:
        raise ValueError(f"degenerate cell: gamma={gamma}")
    cx = c * cos_b
    cy = c * (cos_a - cos_b * cos_g) / sin_g
    cz_sq = c * c - cx * cx - cy * cy
    if cz_sq <= 0:
        raise ValueError(
            f"invalid cell parameters ({a}, {b}, {c}, {alpha}, {beta}, {gamma})"
        )
    return np.array(
        [
            [a, 0.0, 0.0],
            [b * cos_g, b * sin_g, 0.0],
            [cx, cy, math.sqrt(cz_sq)],
        ],
        dtype=np.float64,
    )


@dataclasses.dataclass
class Structure:
    """A periodic crystal: row-vector lattice [3,3], frac coords [N,3], Z [N]."""

    lattice: np.ndarray
    frac_coords: np.ndarray
    numbers: np.ndarray

    def __post_init__(self):
        self.lattice = np.asarray(self.lattice, dtype=np.float64).reshape(3, 3)
        self.frac_coords = np.asarray(self.frac_coords, dtype=np.float64).reshape(-1, 3)
        self.numbers = np.asarray(self.numbers, dtype=np.int32).ravel()
        if len(self.numbers) != len(self.frac_coords):
            raise ValueError(
                f"{len(self.numbers)} atomic numbers but {len(self.frac_coords)} sites"
            )
        if len(self.numbers) == 0:
            raise ValueError("empty structure")
        vol = abs(np.linalg.det(self.lattice))
        if vol < 1e-6:
            raise ValueError(f"degenerate lattice (volume {vol})")

    @classmethod
    def from_symbols(cls, lattice, frac_coords, symbols) -> "Structure":
        numbers = [SYMBOL_TO_Z[s] for s in symbols]
        return cls(lattice, frac_coords, numbers)

    @property
    def num_atoms(self) -> int:
        return len(self.numbers)

    @property
    def cart_coords(self) -> np.ndarray:
        """[N,3] Cartesian coordinates (frac @ lattice, row-vector convention)."""
        return self.frac_coords @ self.lattice

    @property
    def volume(self) -> float:
        return float(abs(np.linalg.det(self.lattice)))

    def lattice_parameters(self) -> tuple[float, float, float, float, float, float]:
        """(a, b, c, alpha, beta, gamma) in Å / degrees."""
        lengths = np.linalg.norm(self.lattice, axis=1)
        a1, a2, a3 = self.lattice

        def angle(u, v):
            cosv = float(np.dot(u, v) / (np.linalg.norm(u) * np.linalg.norm(v)))
            return math.degrees(math.acos(max(-1.0, min(1.0, cosv))))

        return (
            float(lengths[0]), float(lengths[1]), float(lengths[2]),
            angle(a2, a3), angle(a1, a3), angle(a1, a2),
        )

    def wrapped(self) -> "Structure":
        """Copy with fractional coordinates wrapped into [0, 1)."""
        f = self.frac_coords % 1.0
        # tiny negatives give f == 1.0 exactly under %; enforce the half-open
        # interval, which the neighbor-list image-count bound relies on
        f = np.where(f >= 1.0, 0.0, f)
        return Structure(self.lattice, f, self.numbers)
