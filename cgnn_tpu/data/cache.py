"""On-disk graph cache: featurize once, stream tensors (SURVEY.md §7 phase 4).

At the 10k structures/sec/chip target, per-step CIF parsing + neighbor
search is orders of magnitude too slow (§3.4) — the reference's
DataLoader-worker model cannot feed a TPU. The pipeline is therefore:

    CIFs --(featurize, parallel, once)--> cache file --(mmap)--> batcher

Format: a single ``.npz`` holding the concatenation of all per-graph arrays
plus offset tables — O(1) metadata, zero-copy row slicing on load.
"""

from __future__ import annotations

import os
from concurrent.futures import ProcessPoolExecutor
from typing import Sequence

import numpy as np

from cgnn_tpu.data.graph import CrystalGraph

_VERSION = 1


def save_graph_cache(graphs: Sequence[CrystalGraph], path: str) -> None:
    """Serialize featurized graphs into one compact npz."""
    node_counts = np.array([g.num_nodes for g in graphs], np.int64)
    edge_counts = np.array([g.num_edges for g in graphs], np.int64)
    tgt = [np.atleast_1d(np.asarray(g.target, np.float32)) for g in graphs]
    tdim = max(len(t) for t in tgt)
    targets = np.zeros((len(graphs), tdim), np.float32)
    target_mask = np.zeros((len(graphs), tdim), np.float32)
    for i, (g, t) in enumerate(zip(graphs, tgt)):
        targets[i, : len(t)] = t
        if g.target_mask is not None:
            target_mask[i, : len(t)] = np.atleast_1d(g.target_mask)
        else:
            target_mask[i, : len(t)] = 1.0

    have_geom = all(
        g.positions is not None and g.lattice is not None and g.offsets is not None
        for g in graphs
    )
    payload = {
        "version": np.int64(_VERSION),
        "node_counts": node_counts,
        "edge_counts": edge_counts,
        "atom_fea": np.concatenate([g.atom_fea for g in graphs]),
        "edge_fea": np.concatenate([g.edge_fea for g in graphs]),
        "centers": np.concatenate([g.centers for g in graphs]),
        "neighbors": np.concatenate([g.neighbors for g in graphs]),
        "targets": targets,
        "target_mask": target_mask,
        "cif_ids": np.array([g.cif_id for g in graphs]),
        "has_geometry": np.int64(1 if have_geom else 0),
    }
    if all(g.distances is not None for g in graphs):
        payload["distances"] = np.concatenate([g.distances for g in graphs])
    if have_geom:
        payload["positions"] = np.concatenate([g.positions for g in graphs])
        payload["lattices"] = np.stack([g.lattice for g in graphs])
        payload["offsets"] = np.concatenate([g.offsets for g in graphs])
    if all(g.forces is not None for g in graphs):
        payload["forces"] = np.concatenate([g.forces for g in graphs])
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)


def load_graph_cache(path: str) -> list[CrystalGraph]:
    """Load a cache back into CrystalGraphs (views into the mmap'd arrays)."""
    z = np.load(path, mmap_mode="r", allow_pickle=False)
    if int(z["version"]) != _VERSION:
        raise ValueError(
            f"cache {path} has version {int(z['version'])}, expected {_VERSION}"
        )
    node_counts = np.asarray(z["node_counts"])
    edge_counts = np.asarray(z["edge_counts"])
    node_off = np.concatenate([[0], np.cumsum(node_counts)])
    edge_off = np.concatenate([[0], np.cumsum(edge_counts)])
    atom_fea = z["atom_fea"]
    edge_fea = z["edge_fea"]
    centers = z["centers"]
    neighbors = z["neighbors"]
    targets = np.asarray(z["targets"])
    target_mask = np.asarray(z["target_mask"])
    cif_ids = np.asarray(z["cif_ids"])
    has_geom = bool(int(z["has_geometry"]))
    distances = z["distances"] if "distances" in z else None
    from cgnn_tpu.data import invariants

    graphs = []
    for i in range(len(node_counts)):
        ns, ne = slice(node_off[i], node_off[i + 1]), slice(edge_off[i], edge_off[i + 1])
        graphs.append(
            CrystalGraph(
                atom_fea=atom_fea[ns],
                edge_fea=edge_fea[ne],
                centers=np.asarray(centers[ne]),
                neighbors=np.asarray(neighbors[ne]),
                target=targets[i],
                cif_id=str(cif_ids[i]),
                target_mask=target_mask[i],
                distances=None if distances is None else distances[ne],
                positions=z["positions"][ns] if has_geom else None,
                lattice=np.asarray(z["lattices"][i]) if has_geom else None,
                offsets=z["offsets"][ne] if has_geom else None,
                forces=z["forces"][ns] if "forces" in z else None,
            )
        )
    # sample-validate under --check-invariants: a truncated or bit-rotted
    # cache would otherwise surface as silent training corruption
    return invariants.maybe_spot_check_graphs(graphs)


def _featurize_one(args):
    import warnings

    from cgnn_tpu.data.cif import parse_cif_file
    from cgnn_tpu.data.dataset import FeaturizeConfig, featurize_structure

    cif_path, cif_id, target, mask, cfg_dict, keep_geometry = args
    cfg = FeaturizeConfig(**cfg_dict)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            structure = parse_cif_file(cif_path)
            return featurize_structure(
                structure, target, cfg, cif_id,
                target_mask=mask, keep_geometry=keep_geometry,
            )
    except Exception as e:  # noqa: BLE001 — mirror the reference: warn+skip
        return (cif_id, str(e))


def featurize_directory_parallel(
    root_dir: str,
    cfg,
    workers: int | None = None,
    id_prop_file: str = "id_prop.csv",
    keep_geometry: bool = False,
) -> tuple[list[CrystalGraph], list[tuple[str, str]]]:
    """Parallel CIF -> graph featurization (the offline preprocessor core).

    Returns (graphs, failures). Worker processes sidestep the GIL for the
    numpy-heavy neighbor search; the reference used DataLoader workers for
    the same reason, but per-epoch instead of once.
    """
    import csv
    import dataclasses

    workers = workers or os.cpu_count() or 1
    prop_path = os.path.join(root_dir, id_prop_file)
    if not os.path.exists(prop_path):
        raise FileNotFoundError(f"missing {prop_path}")
    jobs = []
    cfg_dict = dataclasses.asdict(cfg)
    with open(prop_path, newline="") as f:
        for row in csv.reader(f):
            if not row:
                continue
            cif_id = row[0].strip()
            raw = [c.strip() for c in row[1:]]
            target = np.array([float(c) if c else 0.0 for c in raw], np.float32)
            mask = np.array([1.0 if c else 0.0 for c in raw], np.float32)
            jobs.append(
                (os.path.join(root_dir, cif_id + ".cif"), cif_id, target, mask,
                 cfg_dict, keep_geometry)
            )
    graphs: list[CrystalGraph] = []
    failures: list[tuple[str, str]] = []

    def consume(results) -> None:
        # stream results as workers finish instead of materializing the
        # full list first: failures surface incrementally (a broken CIF
        # at position 3 of a 146k-file directory is visible in seconds,
        # not after the whole sweep) and peak host memory holds one
        # in-flight chunk per worker, not a second copy of every graph
        for r in results:
            if isinstance(r, CrystalGraph):
                graphs.append(r)
            else:
                failures.append(r)

    if workers <= 1:
        consume(map(_featurize_one, jobs))
    else:
        with ProcessPoolExecutor(max_workers=workers) as pool:
            consume(pool.map(_featurize_one, jobs, chunksize=32))
    return graphs, failures
