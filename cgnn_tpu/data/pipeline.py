"""Parallel host-ingest pipeline: a pool of packers feeding one consumer.

``prefetch_to_device`` (data/loader.py) hides ONE producer behind the
device; that is enough for training, where a multi-ms fused train step
amortizes a single packer. The forward path has no such luck: a predict
step is sub-ms, so at inference the device drains batches faster than
one thread can pack them and the chip sits idle on the host's critical
path (BENCH_r05: 112,305 structs/s device rate vs 1,461 end-to-end —
98.7% host). ``parallel_pack`` generalizes the producer pattern to a
POOL of packer threads with order-restoring reassembly:

    jobs ──feeder──> in-queue ──N workers (pack_fn)──> reassembly
                                                          │ (in order)
                                                       consumer

- **Bounded**: at most ``depth`` jobs are in flight (queued + packing +
  reassembled-but-unconsumed), so host memory for staged batches stays
  flat no matter how far the packers outrun the consumer.
- **Order-restoring**: results are yielded in job order regardless of
  which worker finishes first — the caller's span bookkeeping (output
  row -> input graph) survives parallelism untouched.
- **Deterministic shutdown**: every blocking queue operation is bounded
  by a stop event the consumer generator's ``finally`` sets, exactly
  like the loader's ``bounded_put`` — a consumer that abandons the
  iterator mid-stream (exception, early return) releases feeder and
  workers within one timeout tick; nothing ever blocks forever holding
  packed batches alive.
- **Per-job errors**: a ``pack_fn`` exception is delivered IN ORDER as a
  :class:`PackError` result (``raise_on_error=True`` re-raises it at the
  consumer) so one poisoned batch fails its own slot, not the stream —
  the serving path resolves just that flush's futures with the error.

Packing is numpy (the big copies release the GIL), so threads scale
until memory bandwidth, not the interpreter, is the wall — the same
reasoning as the loader, multiplied.

Telemetry (mirrors ``loader_wait_s``/``loader_put_s``):

- ``pipeline_wait_s``   — consumer blocked waiting for the next in-order
  result (packers failing to keep ahead; the starvation signal);
- ``pipeline_pack_s``   — cumulative worker seconds spent in ``pack_fn``;
- ``pipeline_jobs``     — jobs completed;
- ``pipeline_workers``  / ``pipeline_occupancy`` gauges — pool size and
  pack-busy share of the pool's wall-clock capacity.

``BufferPool`` is the allocation half of the fix: PERF.md §7 measured
the full-fidelity pack PAGE-FAULT bound (fresh zeros at ~0.2 GB/s
effective), so packers that re-use preallocated per-shape buffers
(``pack_compact(out=...)``) write into already-mapped pages instead of
faulting fresh ones in per batch. Release discipline is the caller's:
a buffer goes back to the pool only once the device has consumed the
dispatch that read it (see train/infer.py's window-fence release and
serve/server.py's post-fetch release).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Hashable, Iterable, Iterator

from cgnn_tpu.analysis import racecheck

_STOP = object()
_TICK = 0.05  # seconds; the shutdown-latency bound for every blocking op


@dataclasses.dataclass
class PackError:
    """An in-order stand-in for a job whose ``pack_fn`` raised."""

    error: BaseException


class BufferPool:
    """Reusable host staging buffers, keyed by (hashable) shape.

    ``acquire`` pops a free buffer for ``key`` or builds one via
    ``factory``; ``release`` returns it. The pool never blocks and never
    shrinks below what the pipeline's bounded depth can have in flight;
    ``limit_per_key`` only caps pathological release floods (extras are
    dropped to the GC). Thread-safe: packers acquire from worker
    threads, the consumer releases after the device consumed the batch.
    """

    def __init__(self, limit_per_key: int = 16):
        self._free: dict[Hashable, list] = {}
        self._lock = threading.Lock()
        self.limit_per_key = limit_per_key
        self.allocated = 0  # fresh factory builds (the page-fault count)
        self.reused = 0

    def acquire(self, key: Hashable, factory: Callable[[], Any]):
        with self._lock:
            free = self._free.get(key)
            if free:
                self.reused += 1
                return free.pop()
            self.allocated += 1
        return factory()

    def release(self, key: Hashable, buf: Any) -> None:
        with self._lock:
            free = self._free.setdefault(key, [])
            if len(free) < self.limit_per_key:
                free.append(buf)


def parallel_pack(
    jobs: Iterable,
    pack_fn: Callable[[Any], Any],
    *,
    workers: int = 2,
    depth: int | None = None,
    telemetry=None,
    raise_on_error: bool = True,
    name: str = "cgnn-pack",
    join_timeout: float = 5.0,
) -> Iterator[Any]:
    """Yield ``pack_fn(job)`` for each job, in job order, packed by a
    pool of ``workers`` threads (module docstring has the contract).

    ``jobs`` is consumed by a dedicated feeder thread, so a blocking
    jobs generator (e.g. a batcher's ``next_flush`` stream) overlaps
    with packing too. ``depth`` bounds in-flight jobs (default
    ``2 * workers``). An exception raised by the JOBS iterable itself is
    re-raised at the consumer after in-flight results drain (the
    loader's producer-error contract).
    """
    workers = max(1, int(workers))
    depth = depth or 2 * workers
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    in_q: queue.Queue = queue.Queue()
    stop = threading.Event()
    slots = threading.BoundedSemaphore(depth)
    cond = threading.Condition()
    results: dict[int, Any] = {}
    feed_err: list[BaseException] = []
    n_jobs = [-1]  # total job count, known once the feeder exhausts jobs
    pack_busy = [0.0]

    def feeder() -> None:
        seq = 0
        try:
            for payload in jobs:
                while not stop.is_set():
                    if slots.acquire(timeout=_TICK):
                        break
                else:
                    return  # consumer gone; drop the stream
                if stop.is_set():
                    slots.release()
                    return
                in_q.put((seq, payload))
                seq += 1
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            feed_err.append(e)
        finally:
            with cond:
                n_jobs[0] = seq
                cond.notify_all()
            in_q.put(_STOP)

    # the pack-pool hop in the Chrome-trace stream: one span per job on
    # its worker's track, keyed by sequence number so a request trace
    # (serve.pack carries the same wall window) lines up with the pool
    spans = getattr(telemetry, "spans", None)

    def worker() -> None:
        while not stop.is_set():
            racecheck.heartbeat()  # ticks every _TICK even when starved
            try:
                item = in_q.get(timeout=_TICK)
            except queue.Empty:
                continue
            if item is _STOP:
                in_q.put(_STOP)  # wake the sibling workers too
                return
            seq, payload = item
            t0 = time.perf_counter()
            try:
                res = pack_fn(payload)
            except BaseException as e:  # noqa: BLE001 — delivered in-order
                res = PackError(e)
            t1 = time.perf_counter()
            dt = t1 - t0
            with cond:
                pack_busy[0] += dt
                results[seq] = res
                cond.notify_all()
            if spans is not None:
                spans.complete(f"{name}.job", t0, t1, seq=seq,
                               error=isinstance(res, PackError))
            if telemetry is not None:
                telemetry.counter_add("pipeline_pack_s", dt)
                telemetry.counter_add("pipeline_jobs", 1)

    # stable names (graftcheck GC-THREADNAME): racecheck heartbeats and
    # faulthandler deadlock dumps key on them. The pool prefix stays in
    # the worker name — the beats registry is keyed BY name, so two
    # pools in one process (serve's 'cgnn-serve-pack' + an inference
    # 'cgnn-pack') must not share a key, or one pool's fresh beat masks
    # the other pool's wedged worker
    feed_t = threading.Thread(target=feeder, daemon=True,
                              name=f"{name}-feeder")
    work_ts = [
        threading.Thread(target=worker, daemon=True,
                         name=f"{name}-worker-{i}")
        for i in range(workers)
    ]
    t_start = time.perf_counter()
    feed_t.start()
    for t in work_ts:
        t.start()
    if telemetry is not None:
        telemetry.set_gauge("pipeline_workers", float(workers))
    try:
        seq = 0
        while True:
            t0 = time.perf_counter()
            with cond:
                while seq not in results:
                    if n_jobs[0] >= 0 and seq >= n_jobs[0]:
                        break
                    cond.wait(timeout=_TICK)
                if n_jobs[0] >= 0 and seq >= n_jobs[0]:
                    break
                res = results.pop(seq)
            if telemetry is not None:
                telemetry.counter_add(
                    "pipeline_wait_s", time.perf_counter() - t0
                )
            seq += 1
            slots.release()
            if isinstance(res, PackError) and raise_on_error:
                raise res.error
            yield res
    finally:
        # reached on normal exhaustion AND on generator close (consumer
        # abandonment): release feeder + workers, then join — every
        # blocking op above is bounded by _TICK, so they exit promptly
        stop.set()
        feed_t.join(join_timeout)
        for t in work_ts:
            t.join(join_timeout)
        if telemetry is not None:
            wall = max(time.perf_counter() - t_start, 1e-9)
            telemetry.set_gauge(
                "pipeline_occupancy",
                min(1.0, pack_busy[0] / (workers * wall)),
            )
    if feed_err:
        raise feed_err[0]
