"""MeshExecutor: one mesh-based execution layer for the forward path
(ISSUE 10, ROADMAP item 1).

Until this module the repo ran TWO parallelism stacks: training's
``shard_map`` over ``parallel/mesh.py`` meshes, and serving's
thread-per-device ``DeviceSet`` (ISSUE 5) — N Python dispatch threads,
N param replica tuples, N executables per program. ``MeshExecutor``
collapses the serving/inference side onto the SAME ``Mesh`` +
``NamedSharding`` + jit mechanism training uses (SNIPPETS.md [2]-[3]):

- **One program, one dispatch.** A forward program is the single-device
  predict body wrapped in ``shard_map`` over a 1-D ``('data',)`` mesh:
  per-shard sub-batches stack on a leading device axis, the stacked
  batch is ``device_put`` with ``NamedSharding(mesh, P('data'))`` (each
  device receives exactly its slice — nothing is replicated), params
  are placed ONCE replicated (``P()``), and one jitted call runs every
  device. The jit cache holds ONE entry per (rung, staging form, tier)
  — not ``programs x N`` executables like ``DeviceSet`` — and the
  dispatch path has no router, no per-device queues, no per-device
  threads.

- **Bit-exact by construction.** Inside ``shard_map`` each device runs
  the UNPARTITIONED body on its own sub-batch — the same HLO a
  single-device dispatch of that sub-batch runs (the leading-axis
  squeeze/expand are layout no-ops). Mesh-vs-DeviceSet parity over
  identical packed batches is therefore exact, pinned by
  tests/test_executor.py across the ladder, compact staging, and the
  ragged tail.

- **One sharded param tree.** ``place_params`` returns a single
  replicated-over-the-mesh state; ``serve.reload.ParamStore`` holds it
  as its one entry per tier (``placer=``), so a hot swap publishes one
  tree under one version — the per-device replica tuple disappears.

- **Multi-host ready.** The same mesh layer extends across processes:
  ``parallel/dist.py`` stages host-local stacks as global arrays and
  coordinates checkpoint commits/hot reloads; a ``MeshExecutor`` over
  ``jax.devices()`` in a ``jax.distributed`` run is the pod-serving
  shape (this container proves the single-host 8-device slice, the
  2-process CPU dryrun the cross-host mechanics).

The classic failure mode this layer must never regress into: a batch
``device_put`` WITHOUT the sharding (or with ``P()``) silently
replicates every byte to every device — N x the H2D traffic and HBM of
the sharded layout with identical outputs. graftaudit's GA-SHARD check
budgets the compiled program's per-device argument bytes against the
``params + batch/N`` model so that mistake blocks CI
(analysis/program_audit.py).
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from cgnn_tpu.parallel import compat


class MeshExecutor:
    """Mesh + shardings + the sharded-program factory for one device set.

    ``devices`` defaults to the backend-aware ``resolve_devices('auto')``
    (serve/devices.py: all local devices on accelerators, one on CPU —
    an explicit list forces, which is how the 8-host-device dryrun runs
    in-container).
    """

    def __init__(self, devices: Sequence | None = None, *,
                 axis: str = "data"):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        if devices is None:
            from cgnn_tpu.serve.devices import resolve_devices

            devices = resolve_devices("auto")
        devices = list(devices)
        if not devices:
            raise ValueError("a MeshExecutor needs at least one device")
        self.devices = tuple(devices)
        self.axis = axis
        self.mesh = Mesh(np.array(devices), (axis,))
        self.param_sharding = NamedSharding(self.mesh, P())
        self.batch_sharding = NamedSharding(self.mesh, P(axis))
        self._jax = jax

    def __len__(self) -> int:
        return len(self.devices)

    # ---- placement ----

    def place_params(self, state):
        """ONE replicated-over-the-mesh param tree (the ParamStore
        entry). Committed placement: dispatches follow it to the mesh
        with no per-call device routing."""
        return self._jax.device_put(state, self.param_sharding)

    def stage(self, stacked):
        """Stage a host-stacked ``[N, ...]`` batch pytree batch-axis
        SHARDED: each device receives exactly its ``[1, ...]`` slice.
        This line is the whole point — ``device_put`` without the
        sharding would replicate the full stack to every device (the
        GA-SHARD failure mode)."""
        return self._jax.device_put(stacked, self.batch_sharding)

    def stack(self, batches: Sequence):
        """Stack N same-shape per-shard batches on the leading device
        axis (host-side; pytree structure preserved, so a CompactBatch
        stays a CompactBatch and the predict body's trace-time staging
        dispatch still sees its type)."""
        if len(batches) != len(self):
            raise ValueError(
                f"need exactly {len(self)} per-shard batches "
                f"(one per mesh device), got {len(batches)}"
            )
        return self._jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *batches)

    # ---- the sharded program ----

    def shard_predict(self, predict_body: Callable):
        """The ONE jitted sharded forward program factory.

        ``predict_body`` is the unjitted (state, batch) -> [G, T] body
        (train.step.make_predict_step). Returns a jitted callable over
        (replicated state, ``[N, ...]`` stacked batch) -> ``[N, G, T]``
        whose single dispatch covers every mesh device. Each traced
        (rung, staging form, tier) is ONE cache entry and ONE compiled
        multi-device executable — the compile count is ``programs``,
        never ``programs x N``.
        """
        from jax.sharding import PartitionSpec as P

        jax = self._jax

        def stacked_body(state, batch):
            # inside shard_map the batch slice is [1, ...]: squeeze to
            # the single-device batch, run the UNCHANGED body, restack —
            # per-shard HLO identical to a single-device dispatch.
            # tree_map, not [None]: the raw-wire program returns a
            # (preds, overflow, n_edges) TUPLE (ISSUE 11) and every
            # output leaf restacks on the device axis the same way
            sub = jax.tree_util.tree_map(lambda x: x[0], batch)
            return jax.tree_util.tree_map(
                lambda x: x[None], predict_body(state, sub))

        return jax.jit(compat.shard_map(
            stacked_body, mesh=self.mesh,
            in_specs=(P(), P(self.axis)), out_specs=P(self.axis),
            check_vma=False,  # no collectives in the forward body
        ))

    # ---- serving-side shard planning ----

    def split_round_robin(self, items: Sequence) -> list[list]:
        """items[j] -> shard j % N (row j // N): the flush split. Keeps
        shard loads within one item of each other, and the (shard, row)
        coordinate of every item is a pure function of its index."""
        n = len(self)
        return [list(items[i::n]) for i in range(n)]

    def plan_flush(self, graphs: Sequence, shape_set):
        """Split a flush's graphs across the mesh and pick ONE common
        rung for every shard -> (groups, shape, counts).

        Every shard's sub-batch must pack the same compiled shape (the
        stack axis is uniform), so the rung is the smallest one that
        fits the LARGEST shard group. Shards the round-robin leaves
        empty are packed with a filler copy of the first graph — their
        output rows are never read (``counts`` records real graphs per
        shard; accounting and response mapping key on it)."""
        groups = self.split_round_robin(list(graphs))
        counts = [len(g) for g in groups]
        need_g = need_n = need_e = 1
        for g in groups:
            if not g:
                continue
            n = sum(x.num_nodes for x in g)
            e = sum(shape_set.graph_counts(x)[1] for x in g)
            need_g = max(need_g, len(g))
            need_n = max(need_n, n)
            need_e = max(need_e, e)
        shape = shape_set.shape_for(need_g, need_n, need_e)
        if shape is None:
            raise ValueError(
                f"no rung fits the per-shard split "
                f"({need_g} graphs / {need_n} nodes / {need_e} edge "
                f"slots) — the flush should have been admitted smaller"
            )
        filler = [graphs[0]]
        groups = [g if g else filler for g in groups]
        return groups, shape, counts

    def abstract_stacked(self, batch_aval):
        """Stacked ``[N, ...]`` avals from one per-shard batch aval —
        the graftaudit lowering surface for the mesh program."""
        jax = self._jax

        def stackaval(x):
            return jax.ShapeDtypeStruct((len(self), *x.shape), x.dtype)

        return jax.tree_util.tree_map(stackaval, batch_aval)
