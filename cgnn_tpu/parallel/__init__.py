"""Distributed layer: data and graph (edge-sharded) parallelism over a mesh.

TPU-native replacement for the reference's NCCL DDP (SURVEY.md §1
"Distributed layer", §2 parallelism inventory, §5 "Distributed communication
backend"): no process groups, no rendezvous, no gradient buckets — one SPMD
program over ``Mesh(devices, ('data',))`` where XLA emits the ICI/DCN
collectives from ``psum``/``pmean`` inside ``shard_map``. Scaling past one
pod slice adds a DCN axis to the same mesh; the step body is unchanged.

``edge_parallel`` adds the sequence-parallel analog for graphs (SURVEY.md §5
"long-context analog"): the edge axis sharded over a ``'graph'`` mesh axis,
composable with data parallelism as a 2-D ``('data', 'graph')`` mesh.
"""

from cgnn_tpu.parallel.compat import shard_map, pcast, HAS_NATIVE_SHARD_MAP
from cgnn_tpu.parallel.mesh import make_mesh, device_count
from cgnn_tpu.parallel.data_parallel import (
    stack_batches,
    empty_batch_like,
    make_parallel_train_step,
    make_parallel_eval_step,
    parallel_batches,
    shard_leading_axis,
    replicate_state,
    fit_data_parallel,
)
from cgnn_tpu.parallel.executor import MeshExecutor
from cgnn_tpu.parallel.edge_parallel import (
    pad_edges_divisible,
    shard_batch,
    make_edge_parallel_train_step,
    make_edge_parallel_eval_step,
    make_dp_edge_parallel_train_step,
)

__all__ = [
    "shard_map",
    "pcast",
    "HAS_NATIVE_SHARD_MAP",
    "make_mesh",
    "device_count",
    "stack_batches",
    "empty_batch_like",
    "make_parallel_train_step",
    "make_parallel_eval_step",
    "parallel_batches",
    "shard_leading_axis",
    "replicate_state",
    "fit_data_parallel",
    "MeshExecutor",
    "pad_edges_divisible",
    "shard_batch",
    "make_edge_parallel_train_step",
    "make_edge_parallel_eval_step",
    "make_dp_edge_parallel_train_step",
]
