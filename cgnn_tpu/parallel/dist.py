"""Multi-host readiness: ``jax.distributed`` lifecycle + cross-host
coordination helpers (ISSUE 10, ROADMAP item 1).

One process per host, every process running the SAME program over a
GLOBAL device mesh — that is the jax multi-controller model this module
wraps. The pieces, each of which the 2-process CPU dryrun
(``scripts/multihost_smoke.sh``, CI ``multihost-dryrun``) exercises
in-container:

- **Lifecycle**: :func:`initialize` / :func:`initialize_from_env` wire
  ``jax.distributed.initialize`` (coordinator address + process id/count
  from ``CGNN_TPU_COORDINATOR`` / ``CGNN_TPU_NUM_PROCESSES`` /
  ``CGNN_TPU_PROCESS_ID``). On a CPU backend the gloo cross-process
  collectives implementation is selected FIRST — the default CPU backend
  cannot run multiprocess computations at all, and the option only takes
  effect before the backend initializes.
- **Data**: :func:`host_shard` gives each host its strided slice of the
  dataset — disjoint and complete by construction (pinned by test), the
  per-host slicing the loader-side of multi-host DP rides on;
  :func:`shard_global` / :func:`replicate_global` stage host-local
  arrays as global jax Arrays over a multi-process mesh (the
  ``device_put`` twins in data_parallel.py only address local devices).
- **Coordination**: :func:`barrier` (named sync over all processes),
  :func:`broadcast_str` (process 0 -> everyone), and
  :class:`ReloadCoordinator` — the cross-host hot-reload agreement:
  process 0 names the save to swap to, non-zero hosts WAIT until they
  see that save's commit marker on their own filesystem view, and every
  process swaps after one shared barrier, so a mid-run reload lands
  version-consistent everywhere.
- **Checkpointing**: :func:`is_coordinator` gates saves — exactly one
  committer per run (train.py skips saves on non-zero processes), so
  two hosts can never race the versioned-save sequence.

Collectives are blocking and must be called by EVERY process in the
same order: drive :class:`ReloadCoordinator` from lockstep
``poll_once`` loops (the smoke does), not from free-running watcher
threads with different lifetimes.

Everything degrades to a no-op in a single-process run: ``active()`` is
False, ``barrier`` returns immediately, ``host_shard`` returns the
whole sequence — so the same entrypoints run unchanged on one host.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Sequence

_initialized = False

_ENV_COORD = "CGNN_TPU_COORDINATOR"
_ENV_NPROC = "CGNN_TPU_NUM_PROCESSES"
_ENV_PID = "CGNN_TPU_PROCESS_ID"

# fixed wire width for broadcast_str (save names are ckpt-%08d, 13
# chars; 256 leaves room for tags/paths without a variable-size
# collective)
_STR_BYTES = 256


def configured_env() -> dict | None:
    """The multi-host env config, or None when unset/incomplete."""
    coord = os.environ.get(_ENV_COORD, "")
    if not coord:
        return None
    try:
        nproc = int(os.environ[_ENV_NPROC])
        pid = int(os.environ[_ENV_PID])
    except (KeyError, ValueError):
        raise ValueError(
            f"{_ENV_COORD} is set but {_ENV_NPROC}/{_ENV_PID} are not "
            f"both integers — all three configure a multi-host run"
        ) from None
    return {"coordinator": coord, "num_processes": nproc, "process_id": pid}


def initialize(coordinator: str, num_processes: int, process_id: int,
               log_fn: Callable = print) -> None:
    """``jax.distributed.initialize`` with the CPU-collectives fix.

    Must run before any jax computation touches a backend. Idempotent
    per process (a second call is a no-op)."""
    global _initialized
    if _initialized:
        return
    import jax

    if num_processes < 2:
        raise ValueError(f"num_processes must be >= 2, got {num_processes}")
    # the default CPU backend refuses multiprocess computations; gloo is
    # the jaxlib-bundled cross-process implementation. Set BEFORE
    # initialize — after backend init the option is a silent no-op.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") or (
        not os.environ.get("JAX_PLATFORMS")
    ):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — option absent on some jaxlibs
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log_fn(
        f"dist: process {jax.process_index()}/{jax.process_count()} up "
        f"(coordinator {coordinator}; {len(jax.local_devices())} local / "
        f"{len(jax.devices())} global devices)"
    )


def initialize_from_env(log_fn: Callable = print) -> bool:
    """Initialize iff the CGNN_TPU_* env triple is set -> did it."""
    cfg = configured_env()
    if cfg is None:
        return False
    initialize(cfg["coordinator"], cfg["num_processes"],
               cfg["process_id"], log_fn=log_fn)
    return True


def active() -> bool:
    """True in a live multi-process run (>= 2 jax processes)."""
    if not _initialized:
        return False
    import jax

    return jax.process_count() > 1


def process_index() -> int:
    import jax

    return jax.process_index() if _initialized else 0


def process_count() -> int:
    import jax

    return jax.process_count() if _initialized else 1


def is_coordinator() -> bool:
    """Process 0 — the ONE checkpoint committer of a multi-host run."""
    return process_index() == 0


def host_shard(seq: Sequence, index: int | None = None,
               count: int | None = None) -> list:
    """This host's strided slice of ``seq`` — the per-host data split.

    Strided (``seq[i::n]``) rather than contiguous: shard sizes differ
    by at most one, and the union over all hosts is exactly ``seq``
    (disjoint and complete; pinned by test_executor). A no-op (full
    copy) in single-process runs."""
    i = process_index() if index is None else index
    n = process_count() if count is None else count
    if i < 0 or i >= n:
        raise ValueError(f"host_shard index {i} outside [0, {n})")
    return list(seq[i::n])


# ---- collectives ------------------------------------------------------


def barrier(name: str) -> None:
    """Block until every process reaches this named point (no-op when
    single-process). Names must match across processes."""
    if not active():
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_str(value: str) -> str:
    """Process 0's ``value`` on every process (fixed 256-slot wire).

    One int32 slot per byte: the broadcast collective promotes sub-word
    dtypes to int32 on this backend (measured: a uint8 buffer comes
    back byte-spread), so encode at word width from the start."""
    if not active():
        return value
    import numpy as np
    from jax.experimental import multihost_utils

    raw = value.encode()[:_STR_BYTES]
    buf = np.zeros(_STR_BYTES, np.int32)
    buf[: len(raw)] = np.frombuffer(raw, np.uint8).astype(np.int32)
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    return bytes(out[out != 0].astype(np.uint8)).decode()


def min_over_hosts(value: int) -> int:
    """min(value) across processes — the step-count equalizer: every
    host must run the SAME number of collective steps per epoch, so the
    per-epoch batch list truncates to the shortest host's count."""
    if not active():
        return int(value)
    import numpy as np
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray([value], np.int64))
    return int(np.min(gathered))


# ---- global-array staging --------------------------------------------


def _is_key(x) -> bool:
    import jax

    return isinstance(x, jax.Array) and hasattr(x, "dtype") and (
        getattr(x.dtype, "name", "").startswith("key")
    )


def _tree_global(tree, mesh, spec):
    """host-local leaves -> global Arrays over ``mesh`` under ``spec``.

    PRNG key leaves ride as raw key data (the multihost staging
    primitive rejects typed key arrays) and are re-wrapped after."""
    import jax
    import numpy as np
    from jax.experimental import multihost_utils

    keys = {}

    def strip(path, x):
        if _is_key(x):
            keys[path] = True
            return jax.random.key_data(x)
        return x

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    stripped = [strip(p, x) for p, x in flat]
    # true host copies, not CPU-aliasing views (GC-ALIAS): the staged
    # global arrays must not share memory with buffers a donated step
    # may later reuse
    host = jax.tree_util.tree_map(np.array, jax.device_get(stripped))
    out = multihost_utils.host_local_array_to_global_array(
        host, mesh, spec)
    rewrapped = [
        jax.random.wrap_key_data(x) if flat[i][0] in keys else x
        for i, x in enumerate(out)
    ]
    return jax.tree_util.tree_unflatten(treedef, rewrapped)


def replicate_global(tree, mesh):
    """Replicated placement over a multi-process mesh (the
    ``device_put(x, NamedSharding(mesh, P()))`` twin — device_put cannot
    address another process's devices). Inputs must be identical on
    every host (the multihost staging layer asserts it)."""
    from jax.sharding import PartitionSpec as P

    return _tree_global(tree, mesh, P())


def shard_global(local_stack, mesh, spec):
    """This host's ``[n_local, ...]`` stack -> the global batch-sharded
    array (leading axis = concatenation of every host's stack in
    process order)."""
    return _tree_global(local_stack, mesh, spec)


def localize(tree):
    """Global (replicated) arrays -> host-local numpy-backed leaves, so
    a post-fit state can feed single-device programs (final test eval,
    checkpoint template restores). PRNG keys survive round-trip."""
    import jax
    import numpy as np

    def pull(x):
        if _is_key(x):
            # np.array, not asarray: a true copy (CPU device_get
            # ALIASES device buffers — GC-ALIAS)
            return jax.random.wrap_key_data(
                np.array(jax.device_get(jax.random.key_data(x))))
        if isinstance(x, jax.Array):
            return np.array(jax.device_get(x))
        return x

    return jax.tree_util.tree_map(pull, tree)


# ---- cross-host hot reload -------------------------------------------


class ReloadCoordinator:
    """Cross-host agreement on which committed save to hot-swap to.

    Plugs into ``serve.reload.CheckpointWatcher(coordinator=...)``:
    every ``poll_once`` on every process calls this with the newest
    committed save it sees locally (or None). Process 0's view wins —
    it broadcasts the candidate name; non-zero hosts then WAIT (bounded)
    until their own filesystem view shows that save's commit marker
    (shared-filesystem lag is real), and everyone swaps only after one
    shared barrier. Returns the agreed name, or None for "no swap this
    round" — which is itself an agreement: no host swaps early.

    Each call is a COLLECTIVE: every process must poll in lockstep
    (drive poll_once from a shared-cadence loop, as the multihost smoke
    does; a free-running watcher thread that dies mid-collective hangs
    its peers).
    """

    def __init__(self, manager, *, visibility_timeout_s: float = 30.0,
                 log_fn: Callable = print):
        self._mgr = manager
        self._timeout = visibility_timeout_s
        self._log = log_fn
        self._round = 0

    def __call__(self, newest: str | None) -> str | None:
        self._round += 1
        if not active():
            return newest
        agreed = broadcast_str((newest or "") if is_coordinator() else "")
        if not agreed:
            # collective no-op round: everyone agreed there is nothing
            # to swap to (keeps the per-poll collective count aligned)
            barrier(f"cgnn-reload-idle-{self._round}")
            return None
        deadline = time.monotonic() + self._timeout
        while not self._mgr.is_committed(agreed):
            # the non-zero-host wait on the commit marker: process 0
            # saw the manifest; this host's fs view may lag behind it
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"process {process_index()} never saw the commit "
                    f"marker of {agreed} within {self._timeout}s — "
                    f"shared checkpoint directory out of sync"
                )
            time.sleep(0.05)
        barrier(f"cgnn-reload-{agreed}-{self._round}")
        return agreed
