"""Edge-sharded (graph-parallel) message passing — the sequence-parallel
analog for crystal graphs (SURVEY.md §5 "long-context analog").

A crystal-graph model has no sequence axis; its scaling axis is the EDGE
list. When a batch's edge work exceeds one chip (giant OC20 cells, or a
single structure too large for HBM), shard the edge axis across a mesh
axis ``'graph'``:

- node features are replicated; each device gathers endpoints for ITS edge
  shard only (contiguous chunks of the globally center-sorted edge list, so
  the per-shard sortedness invariant holds);
- the dominant FLOPs — the per-edge ``fc_full`` dense layer — split D ways;
- per-node partial aggregates are ``psum``-ed back to full sums (one ICI
  all-reduce per conv layer, the ring-attention-style collective);
- edge-BatchNorm moments span all shards (two-psum masked moments in
  MaskedBatchNorm.axis_name).

Gradients: the step runs under ``shard_map`` with replication checking ON
(``check_vma=True``), so JAX's transpose machinery inserts the psum that
converts each shard's partial parameter cotangents into the full gradient
— no manual pmean over 'graph' (which would be wrong: node-side parameter
contributions are replicated-complete while edge-side ones are partial).
This composes with data parallelism as a 2-D mesh ``('data', 'graph')``;
grads/stats still pmean over 'data' explicitly as in plain DP.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cgnn_tpu.data.graph import GraphBatch
from cgnn_tpu.train.state import TrainState
from cgnn_tpu.train.step import make_eval_step, make_train_step

# GraphBatch leaves whose leading axis is the edge axis
EDGE_FIELDS = ("edges", "centers", "neighbors", "edge_mask", "edge_offsets")
# transpose-slot fields exist only in the dense layout, which edge sharding
# rejects; specs carry None so the pytrees match COO batches (where they
# are None)
_DENSE_ONLY_FIELDS = (
    "in_slots", "in_mask", "over_slots", "over_nodes", "over_mask",
)
_ALL_FIELDS = tuple(f.name for f in dataclasses.fields(GraphBatch))


def pad_edges_divisible(batch: GraphBatch, n_shards: int) -> GraphBatch:
    """Pad the edge axis so it splits evenly into ``n_shards`` (host-side).

    Padding edges follow the pack_graphs convention: masked out, pointing
    at the last node slot (preserves the sorted-centers invariant).
    """
    e = batch.edge_capacity
    pad = -e % n_shards
    if pad == 0:
        return batch
    ncap = batch.node_capacity

    def pad_field(name, x):
        if name not in EDGE_FIELDS:
            return x
        widths = [(0, pad)] + [(0, 0)] * (np.ndim(x) - 1)
        fill = ncap - 1 if name in ("centers", "neighbors") else 0
        return np.pad(np.asarray(x), widths, constant_values=fill)

    return GraphBatch(
        **{
            name: pad_field(name, getattr(batch, name))
            for name in _ALL_FIELDS
        }
    )


def batch_specs(
    graph_axis: str | None = "graph", data_axis: str | None = None
) -> GraphBatch:
    """GraphBatch of PartitionSpecs: edge leaves sharded over ``graph_axis``,
    optional leading stacked-device axis over ``data_axis``."""
    lead = (data_axis,) if data_axis else ()

    def spec(name):
        if name in _DENSE_ONLY_FIELDS:
            return None
        if name in EDGE_FIELDS and graph_axis:
            return P(*lead, graph_axis)
        return P(*lead)

    return GraphBatch(**{name: spec(name) for name in _ALL_FIELDS})


def shard_batch(
    batch: GraphBatch,
    mesh: Mesh,
    graph_axis: str = "graph",
    data_axis: str | None = None,
):
    """device_put a batch with edge leaves split over the graph axis (and,
    when ``data_axis`` is given, every leaf's leading stacked-device axis
    split over it)."""
    specs = batch_specs(graph_axis=graph_axis, data_axis=data_axis)

    def put(x, s):
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree_util.tree_map(
        put, batch, specs, is_leaf=lambda x: isinstance(x, P)
    )


def make_edge_parallel_train_step(
    mesh: Mesh,
    classification: bool = False,
    graph_axis: str = "graph",
) -> Callable:
    """(replicated state, edge-sharded batch) -> (state, metrics).

    The model inside ``state.apply_fn`` must be built with
    ``edge_axis_name=graph_axis``. Replication checking stays ON so the
    parameter-gradient psum over the graph axis is inserted by transpose.
    """
    inner = make_train_step(classification)

    smapped = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(), batch_specs(graph_axis=graph_axis)),
        out_specs=(P(), P()),
    )
    return jax.jit(smapped, donate_argnums=0)


def make_edge_parallel_eval_step(
    mesh: Mesh,
    classification: bool = False,
    graph_axis: str = "graph",
) -> Callable:
    inner = make_eval_step(classification)
    smapped = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(), batch_specs(graph_axis=graph_axis)),
        out_specs=P(),
    )
    return jax.jit(smapped)


def make_dp_edge_parallel_train_step(
    mesh: Mesh,
    classification: bool = False,
    data_axis: str = "data",
    graph_axis: str = "graph",
) -> Callable:
    """2-D mesh step: batches stacked over 'data', edges sharded over
    'graph' within each data shard. Input leaves: [D, ...] with edge leaves
    [D, E]; stats pmean over 'data', metrics psum over 'data'.

    Gradients: replication checking is ON, so the shard_map transpose
    psums parameter cotangents over BOTH mesh axes (over 'graph' that
    completes the edge-partial grads; over 'data' it sums per-shard grads).
    Scaling the loss by 1/n_data turns that data-axis sum into the DDP
    mean — an explicit pmean here would be an identity on the already
    reduced value (it arrives axis-invariant), silently leaving grads
    n_data times too large.
    """
    from cgnn_tpu.parallel.data_parallel import _squeeze0

    inner = make_train_step(
        classification,
        axis_name=data_axis,
        loss_scale=1.0 / mesh.shape[data_axis],
        pmean_grads=False,
    )

    def body(state: TrainState, stacked: GraphBatch):
        return inner(state, _squeeze0(stacked))

    smapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), batch_specs(graph_axis=graph_axis, data_axis=data_axis)),
        out_specs=(P(), P()),
    )
    return jax.jit(smapped, donate_argnums=0)


def make_dp_edge_parallel_eval_step(
    mesh: Mesh,
    classification: bool = False,
    loss_fn: Callable | None = None,
    data_axis: str = "data",
    graph_axis: str = "graph",
) -> Callable:
    """2-D mesh eval step: metrics psum over 'data' (each graph shard
    computes identical metrics after the model's psum over 'graph')."""
    from cgnn_tpu.parallel.data_parallel import _squeeze0

    inner = make_eval_step(classification, axis_name=data_axis, loss_fn=loss_fn)

    def body(state: TrainState, stacked: GraphBatch):
        return inner(state, _squeeze0(stacked))

    smapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), batch_specs(graph_axis=graph_axis, data_axis=data_axis)),
        out_specs=P(),
    )
    return jax.jit(smapped)


def shard_stacked_batch(
    stacked: GraphBatch,
    mesh: Mesh,
    data_axis: str = "data",
    graph_axis: str = "graph",
):
    """device_put a [D, ...]-stacked batch onto a 2-D mesh: leading axis over
    'data', edge leaves additionally split over 'graph'."""
    return shard_batch(
        stacked, mesh, graph_axis=graph_axis, data_axis=data_axis
    )
