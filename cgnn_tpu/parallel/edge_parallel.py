"""Edge-sharded (graph-parallel) message passing — the sequence-parallel
analog for crystal graphs (SURVEY.md §5 "long-context analog").

A crystal-graph model has no sequence axis; its scaling axis is the EDGE
list. When a batch's edge work exceeds one chip (giant OC20 cells, or a
single structure too large for HBM), shard the edge axis across a mesh
axis ``'graph'``:

- node features are replicated; each device gathers endpoints for ITS edge
  shard only (contiguous chunks of the globally center-sorted edge list, so
  the per-shard sortedness invariant holds);
- the dominant FLOPs — the per-edge ``fc_full`` dense layer — split D ways;
- per-node partial aggregates are ``psum``-ed back to full sums (one ICI
  all-reduce per conv layer, the ring-attention-style collective);
- edge-BatchNorm moments span all shards (two-psum masked moments in
  MaskedBatchNorm.axis_name).

Gradients: the step runs under ``shard_map`` with replication checking ON
(``check_vma=True``), so JAX's transpose machinery inserts the psum that
converts each shard's partial parameter cotangents into the full gradient
— no manual pmean over 'graph' (which would be wrong: node-side parameter
contributions are replicated-complete while edge-side ones are partial).
This composes with data parallelism as a 2-D mesh ``('data', 'graph')``;
grads/stats still pmean over 'data' explicitly as in plain DP.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cgnn_tpu.parallel import compat
from cgnn_tpu.data.graph import GraphBatch
from cgnn_tpu.train.state import TrainState
from cgnn_tpu.train.step import (
    TRAIN_STEP_DONATE,
    make_eval_step,
    make_train_step,
)

# GraphBatch leaves whose leading axis is the edge axis
EDGE_FIELDS = ("edges", "centers", "neighbors", "edge_mask", "edge_offsets")
# transpose-slot fields exist only in the dense layout, which edge sharding
# rejects; specs carry None so the pytrees match COO batches (where they
# are None)
_DENSE_ONLY_FIELDS = (
    "in_slots", "in_mask", "over_slots", "over_nodes", "over_mask",
)
_ALL_FIELDS = tuple(f.name for f in dataclasses.fields(GraphBatch))


def pad_edges_divisible(batch: GraphBatch, n_shards: int) -> GraphBatch:
    """Pad the edge axis so it splits evenly into ``n_shards`` (host-side).

    Padding edges follow the pack_graphs convention: masked out, pointing
    at the last node slot (preserves the sorted-centers invariant).
    """
    e = batch.edge_capacity
    pad = -e % n_shards
    if pad == 0:
        return batch
    ncap = batch.node_capacity

    def pad_field(name, x):
        if name not in EDGE_FIELDS:
            return x
        widths = [(0, pad)] + [(0, 0)] * (np.ndim(x) - 1)
        fill = ncap - 1 if name in ("centers", "neighbors") else 0
        return np.pad(np.asarray(x), widths, constant_values=fill)

    return GraphBatch(
        **{
            name: pad_field(name, getattr(batch, name))
            for name in _ALL_FIELDS
        }
    )


def batch_specs(
    graph_axis: str | None = "graph", data_axis: str | None = None
) -> GraphBatch:
    """GraphBatch of PartitionSpecs: edge leaves sharded over ``graph_axis``,
    optional leading stacked-device axis over ``data_axis``."""
    lead = (data_axis,) if data_axis else ()

    def spec(name):
        if name in _DENSE_ONLY_FIELDS:
            return None
        if name in EDGE_FIELDS and graph_axis:
            return P(*lead, graph_axis)
        return P(*lead)

    return GraphBatch(**{name: spec(name) for name in _ALL_FIELDS})


def dense_batch_specs(
    graph_axis: str = "graph",
    data_axis: str | None = None,
    with_transpose: bool = True,
) -> GraphBatch:
    """PartitionSpecs for a DENSE-layout batch under node-strip graph
    sharding (prepare_dense_sharded): ``edges`` [N, M, G] split over its
    node-owner axis, the flat per-slot leaves ([E] = [N*M]) split likewise,
    and the per-shard transpose stacks ([D, ...]) split one-mapping-per-
    shard. Node leaves stay replicated over ``graph_axis`` — the conv
    slices its own strip and psums the padded aggregate back to full.

    ``with_transpose=False`` matches eval batches, whose transpose fields
    are dropped by ``prepare_dense_sharded`` (no backward, no mapping)."""
    lead = (data_axis,) if data_axis else ()

    def spec(name):
        if name in _DENSE_ONLY_FIELDS:
            return P(*lead, graph_axis) if with_transpose else None
        if name in EDGE_FIELDS:
            return P(*lead, graph_axis)
        return P(*lead)

    return GraphBatch(**{name: spec(name) for name in _ALL_FIELDS})


def prepare_dense_sharded(
    batch: GraphBatch, n_shards: int, train: bool = True
) -> GraphBatch:
    """Host-side prep of a dense-layout batch for node-strip sharding.

    Training batches get per-shard two-tier transpose mappings
    (data/graph.py shard_transpose_slots — shard-local slot indices,
    stacked [D, ...]); eval batches drop their mapping fields entirely
    (no backward runs, and an empty [N, 0] mapping would force a distinct
    sharded pytree/spec structure for nothing).
    """
    if np.ndim(batch.edges) != 3:
        raise ValueError(
            "prepare_dense_sharded expects a dense-layout batch "
            "(edges pre-shaped [N, M, G]; pack with dense_m)"
        )
    ncap = batch.node_capacity
    if ncap % n_shards:
        raise ValueError(
            f"node capacity {ncap} not divisible by {n_shards} graph "
            f"shards; round node_cap up to a multiple of the shard count"
        )
    if not train or batch.in_slots is None:
        return dataclasses.replace(
            batch, in_slots=None, in_mask=None, over_slots=None,
            over_nodes=None, over_mask=None,
        )
    if np.ndim(batch.in_mask) == 3:
        # already per-shard (pack_graphs transpose_shards) — but ONLY for
        # the same shard count: a 4-shard mapping split over a 2-way mesh
        # would drop half the cotangents with no shape error
        if batch.in_mask.shape[0] != n_shards:
            raise ValueError(
                f"batch carries a {batch.in_mask.shape[0]}-shard transpose "
                f"mapping but {n_shards} graph shards were requested"
            )
        return batch
    if batch.over_slots is None:
        # A single-tier mapping carries no overflow capacity, and the
        # per-shard rebuild is only guaranteed overflow-safe when the cap
        # came from the batch's own two-tier mapping (per-shard overflow
        # is a subset of global overflow). A guessed cap could raise
        # TransposeOverflowError mid-training — refuse instead.
        raise ValueError(
            "graph sharding needs the two-tier transpose layout; pack "
            "with in_cap=None (the default) instead of a single-tier "
            "in_cap"
        )
    from cgnn_tpu.data.graph import shard_transpose_slots

    m = batch.edges.shape[1]
    in_slots, in_mask, over_slots, over_nodes, over_mask = (
        shard_transpose_slots(
            np.asarray(batch.neighbors), np.asarray(batch.edge_mask) > 0,
            ncap, m, n_shards, len(batch.over_slots),
        )
    )
    return dataclasses.replace(
        batch, in_slots=in_slots, in_mask=in_mask, over_slots=over_slots,
        over_nodes=over_nodes, over_mask=over_mask,
    )


def _auto_specs(
    batch: GraphBatch,
    graph_axis: str,
    data_axis: str | None,
    dense_rank: int,
) -> GraphBatch:
    """The ONE dense/COO spec dispatch: dense layouts are detected by the
    edges leaf's rank (``dense_rank`` = 3 + one per leading stack axis),
    and dense batches' transpose fields follow their presence (train
    batches carry per-shard mappings, eval batches dropped theirs)."""
    if np.ndim(batch.edges) == dense_rank:
        return dense_batch_specs(
            graph_axis=graph_axis, data_axis=data_axis,
            with_transpose=batch.in_slots is not None,
        )
    return batch_specs(graph_axis=graph_axis, data_axis=data_axis)


def _put_specs(tree, mesh: Mesh, specs, prefix: tuple = ()):
    """device_put every leaf per its spec, with ``prefix`` axes prepended
    (the scan staging's replicated step axis)."""

    def put(x, s):
        return jax.device_put(x, NamedSharding(mesh, P(*prefix, *s)))

    return jax.tree_util.tree_map(
        put, tree, specs, is_leaf=lambda x: isinstance(x, P)
    )


def shard_batch(
    batch: GraphBatch,
    mesh: Mesh,
    graph_axis: str = "graph",
    data_axis: str | None = None,
):
    """device_put a batch with edge leaves split over the graph axis (and,
    when ``data_axis`` is given, every leaf's leading stacked-device axis
    split over it). Dense-layout batches ([N, M, G] edges, optionally
    prepared by ``prepare_dense_sharded``) get the dense spec set."""
    specs = _auto_specs(batch, graph_axis, data_axis,
                        dense_rank=4 if data_axis else 3)
    return _put_specs(batch, mesh, specs)


def _specs(graph_axis, data_axis=None, dense=False, with_transpose=True):
    """Spec pytree for COO (batch_specs) or dense (dense_batch_specs)."""
    if dense:
        return dense_batch_specs(
            graph_axis=graph_axis, data_axis=data_axis,
            with_transpose=with_transpose,
        )
    return batch_specs(graph_axis=graph_axis, data_axis=data_axis)


def _harden(inner: Callable, guard: bool) -> Callable:
    """Optionally wrap an edge-sharded train body with the divergence
    guard. Safe under replication checking: the guard's keep-or-skip
    condition reads post-transpose-psum grads/params, which are already
    replicated over 'graph', so its selects and skip metrics are too."""
    if not guard:
        return inner
    from cgnn_tpu.resilience.guard import guard_step

    return guard_step(inner)


def make_edge_parallel_train_step(
    mesh: Mesh,
    classification: bool = False,
    graph_axis: str = "graph",
    dense: bool = False,
    grad_health: bool = False,
    guard: bool = False,
) -> Callable:
    """(replicated state, edge-sharded batch) -> (state, metrics).

    The model inside ``state.apply_fn`` must be built with
    ``edge_axis_name=graph_axis`` (and, for ``dense=True``, the matching
    ``dense_m``; batches via ``prepare_dense_sharded``). Replication
    checking stays ON so the parameter-gradient psum over the graph axis
    is inserted by transpose.

    ``grad_health`` adds the in-graph grad/update-norm and NaN/Inf
    metrics (observe.health) — the PR-1 known gap, closed: the values
    derive from the post-transpose-psum grads and the model's own
    psum-complete loss, both replicated over 'graph', so they pass
    replication checking without extra collectives. ``guard`` wraps the
    body with the divergence guard (see ``_harden``).
    """
    inner = _harden(
        make_train_step(classification, grad_health=grad_health), guard
    )

    smapped = compat.shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(), _specs(graph_axis, dense=dense)),
        out_specs=(P(), P()),
    )
    return jax.jit(smapped, donate_argnums=TRAIN_STEP_DONATE)


def make_edge_parallel_eval_step(
    mesh: Mesh,
    classification: bool = False,
    graph_axis: str = "graph",
    dense: bool = False,
) -> Callable:
    inner = make_eval_step(classification)
    smapped = compat.shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(), _specs(graph_axis, dense=dense, with_transpose=False)),
        out_specs=P(),
    )
    return jax.jit(smapped)


def make_dp_edge_parallel_train_step(
    mesh: Mesh,
    classification: bool = False,
    data_axis: str = "data",
    graph_axis: str = "graph",
    dense: bool = False,
    grad_health: bool = False,
    guard: bool = False,
) -> Callable:
    """2-D mesh step: batches stacked over 'data', edges sharded over
    'graph' within each data shard. Input leaves: [D, ...] with edge leaves
    [D, E]; stats pmean over 'data', metrics psum over 'data'.

    Gradients: replication checking is ON, so the shard_map transpose
    psums parameter cotangents over BOTH mesh axes (over 'graph' that
    completes the edge-partial grads; over 'data' it sums per-shard grads).
    Scaling the loss by 1/n_data turns that data-axis sum into the DDP
    mean — an explicit pmean here would be an identity on the already
    reduced value (it arrives axis-invariant), silently leaving grads
    n_data times too large.

    ``grad_health``/``guard`` as in ``make_edge_parallel_train_step``;
    the health loss is additionally pmean-ed over 'data' by the inner
    step (any shard's NaN must be visible everywhere, not just shard 0's
    escaping value).
    """
    from cgnn_tpu.parallel.data_parallel import _squeeze0

    inner = _harden(
        make_train_step(
            classification,
            axis_name=data_axis,
            loss_scale=1.0 / mesh.shape[data_axis],
            pmean_grads=False,
            grad_health=grad_health,
        ),
        guard,
    )

    def body(state: TrainState, stacked: GraphBatch):
        return inner(state, _squeeze0(stacked))

    smapped = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), _specs(graph_axis, data_axis, dense=dense)),
        out_specs=(P(), P()),
    )
    return jax.jit(smapped, donate_argnums=TRAIN_STEP_DONATE)


def make_dp_edge_parallel_eval_step(
    mesh: Mesh,
    classification: bool = False,
    loss_fn: Callable | None = None,
    data_axis: str = "data",
    graph_axis: str = "graph",
    dense: bool = False,
) -> Callable:
    """2-D mesh eval step: metrics psum over 'data' (each graph shard
    computes identical metrics after the model's psum over 'graph')."""
    from cgnn_tpu.parallel.data_parallel import _squeeze0

    inner = make_eval_step(classification, axis_name=data_axis, loss_fn=loss_fn)

    def body(state: TrainState, stacked: GraphBatch):
        return inner(state, _squeeze0(stacked))

    smapped = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), _specs(graph_axis, data_axis, dense=dense,
                              with_transpose=False)),
        out_specs=P(),
    )
    return jax.jit(smapped)


def shard_stacked_batch(
    stacked: GraphBatch,
    mesh: Mesh,
    data_axis: str = "data",
    graph_axis: str = "graph",
):
    """device_put a [D, ...]-stacked batch onto a 2-D mesh: leading axis over
    'data', edge leaves additionally split over 'graph' (dense-layout
    batches — edges stacked [D, N, M, G] — get the dense spec set)."""
    return shard_batch(
        stacked, mesh, graph_axis=graph_axis, data_axis=data_axis
    )


def shard_scan_stack_2d(
    tree: GraphBatch,
    mesh: Mesh,
    data_axis: str = "data",
    graph_axis: str = "graph",
):
    """device_put a STACK of device-stacked batches ([B, D, ...] leaves)
    onto a ('data','graph') mesh — the ScanEpochDriver staging for
    graph-sharded runs (the 2-D twin of data_parallel.shard_scan_stack).

    Axis 0 is the scan/step axis (replicated); axis 1 the data-device
    axis; edge leaves and per-shard transpose stacks additionally split
    over 'graph' on their own axes. The scan body's dynamic index along
    axis 0 preserves the inner shardings, so the shard_map step inside
    the scan sees exactly the per-step path's layout. COO stacks
    ([B, D, E, G] edges) and dense stacks ([B, D, N, M, G]) are
    distinguished by rank, like shard_batch."""
    specs = _auto_specs(tree, graph_axis, data_axis, dense_rank=5)
    return _put_specs(tree, mesh, specs, prefix=(None,))
