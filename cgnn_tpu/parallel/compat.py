"""jax version compatibility for the parallelism layer.

The repo targets the current jax API (``jax.shard_map`` with
``check_vma``, ``jax.lax.pcast``); the in-container runtime is jax
0.4.37, where shard_map lives at ``jax.experimental.shard_map.shard_map``
with the older ``check_rep`` keyword and ``pcast`` does not exist. Until
this module, every shard_map call site hit ``AttributeError: jax has no
attribute 'shard_map'`` in-container — the bulk of the 43 pre-existing
seed test failures (ROADMAP "Tier-1 trajectory"), which passed only in
CI's newer jax. This is the ONE resolution point:

- :func:`shard_map` — the new-API surface (``check_vma`` keyword). On a
  jax with native ``jax.shard_map`` it delegates verbatim. On the old
  API it maps to ``check_rep``, with one semantic concession: the old
  replication checker predates ``jax.lax.pcast`` and has no rule for
  ``linear_call``-style custom-transpose ops, so ``check_vma=True``
  downgrades to ``check_rep=False`` there. Gradient correctness does NOT
  ride on the checker — the transpose of a ``P()`` (replicated) input is
  a psum of its per-shard cotangents in either mode, which is exactly
  the parameter-gradient reduction edge_parallel.py documents — the
  checker only verifies declared output replication, so the downgrade
  trades a consistency assertion, not math.
- :func:`pcast` — ``jax.lax.pcast`` when it exists; identity otherwise
  (without replication tracking there is nothing to cast between).
"""

from __future__ import annotations

import jax

HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on new jax; the experimental equivalent on old.

    Keyword-only like the new API. ``check_vma=False`` maps to
    ``check_rep=False``; ``check_vma=True`` also maps to
    ``check_rep=False`` on old jax (see module docstring — the old
    checker cannot type the custom-transpose ops these step bodies use).
    """
    if HAS_NATIVE_SHARD_MAP:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def pcast(x, axis_name, to: str = "varying"):
    """``jax.lax.pcast`` where it exists; identity on old jax.

    Under the new vma type system the cast marks a replicated value
    varying so the transpose machinery inserts the psums that complete
    per-shard partial node cotangents at exactly the right points. The
    old system cannot express that bookkeeping: an identity leaves the
    cross-shard gather cotangent terms of STACKED convs incomplete
    (measured ~1e-4 relative on the dense node-strip parity pins — a
    hand-inserted transpose-psum was tried and double-counts the
    replicated residual paths, ~50x worse), so on old jax the dense
    graph-sharded backward is approximate at the 1e-4 level and its
    exact-parity tests skip (tests/test_edge_parallel.py); CI's jax
    runs them exactly.
    """
    pc = getattr(jax.lax, "pcast", None)
    if pc is not None:
        return pc(x, axis_name, to=to)
    return x
