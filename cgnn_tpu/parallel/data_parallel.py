"""Data-parallel training over a mesh (SURVEY.md §2 parallelism inventory).

The reference wraps its model in DDP: per-rank processes, NCCL allreduce on
gradient buckets overlapped with backward (SURVEY.md §3.5). The TPU-native
equivalent is one SPMD program: per-device packed GraphBatches are stacked
on a leading device axis, sharded over ``Mesh(('data',))``, and the step
body (cgnn_tpu.train.step with ``axis_name='data'``) runs under shard_map —
``pmean`` on grads/BatchNorm stats becomes an ICI allreduce placed by XLA
wherever it overlaps best. Batch semantics match DDP: identical params on
every device, global batch = sum of per-device batches, metric sums are
exact psum totals.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cgnn_tpu.parallel import compat
from cgnn_tpu.data.graph import (
    CrystalGraph,
    GraphBatch,
    PaddingStats,
    batch_iterator,
    batch_shape_key,
    bucketed_batch_iterator,
)
from cgnn_tpu.resilience import faultinject
from cgnn_tpu.train.state import TrainState
from cgnn_tpu.train.step import (
    TRAIN_STEP_DONATE,
    make_eval_step,
    make_train_step,
)


def stack_batches(batches: Sequence[GraphBatch]) -> GraphBatch:
    """Stack D same-shape batches on a new leading device axis."""
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)


def empty_batch_like(batch: GraphBatch) -> GraphBatch:
    """All-padding batch with the same capacities (masks are zero).

    Used to pad the last eval step up to a full device group; contributes
    exactly zero to psum-ed metric sums. Never use for training steps —
    running-stat updates would average in its degenerate statistics.
    Under --check-invariants this is ENFORCED: parallel_batches checks
    train-time device groups and make_parallel_train_step rejects
    host-side stacked batches with an all-padding row.
    """
    ncap = batch.node_capacity
    # dense layout: centers/neighbors are STRUCTURAL (slot k belongs to
    # node k//M; padding = masked self-loops), so the empty batch keeps the
    # ownership pattern; flat COO padding points at the last node slot
    dense = np.ndim(batch.edges) == 3
    empty_centers = (np.array(batch.centers) if dense
                     else np.full_like(batch.centers, ncap - 1))
    return GraphBatch(
        nodes=np.zeros_like(batch.nodes),
        edges=np.zeros_like(batch.edges),
        centers=empty_centers,
        neighbors=(empty_centers.copy() if dense
                   else np.full_like(batch.neighbors, ncap - 1)),
        node_graph=np.zeros_like(batch.node_graph),
        node_mask=np.zeros_like(batch.node_mask),
        edge_mask=np.zeros_like(batch.edge_mask),
        graph_mask=np.zeros_like(batch.graph_mask),
        targets=np.zeros_like(batch.targets),
        target_mask=np.zeros_like(batch.target_mask),
        positions=np.zeros_like(batch.positions),
        lattices=np.zeros_like(batch.lattices),
        edge_offsets=np.zeros_like(batch.edge_offsets),
        node_targets=np.zeros_like(batch.node_targets),
        in_slots=None if batch.in_slots is None else np.zeros_like(batch.in_slots),
        in_mask=None if batch.in_mask is None else np.zeros_like(batch.in_mask),
        over_slots=(None if batch.over_slots is None
                    else np.zeros_like(batch.over_slots)),
        over_nodes=(None if batch.over_nodes is None
                    else np.full_like(batch.over_nodes, ncap - 1)),
        over_mask=(None if batch.over_mask is None
                   else np.zeros_like(batch.over_mask)),
    )


def parallel_batches(
    graphs: Sequence[CrystalGraph],
    n_devices: int,
    batch_size: int,
    node_cap: int,
    edge_cap: int,
    shuffle: bool = False,
    rng: np.random.Generator | None = None,
    pad_incomplete: bool = False,
    dense_m: int | None = None,
    in_cap: int | None = None,
    buckets: int = 1,
    snug: bool = False,
    stats: PaddingStats | None = None,
    edge_dtype=np.float32,
    prep_fn: Callable | None = None,
    node_multiple: int = 1,
    transpose_shards: int = 1,
) -> Iterable[GraphBatch]:
    """Yield device-stacked batches: leaves have leading axis [D, ...].

    ``batch_size`` is per device (global batch = D * batch_size). Training
    drops an incomplete trailing device group (DDP drop_last semantics);
    eval pads it with empty batches so every structure is scored.

    ``buckets > 1`` sources per-size-class batches (bucketed_batch_iterator;
    ``node_cap``/``edge_cap`` are then ignored — each bucket computes its
    own) and groups same-shape batches into device groups, so every device
    in a group runs the same compiled shape. At most ``n_devices - 1``
    batches per shape are dropped per training epoch (the per-shape
    drop_last tail).

    ``prep_fn`` transforms each batch before shape-keying/stacking (dense
    graph sharding attaches per-shard transpose mappings here);
    ``node_multiple`` rounds bucket-computed node capacities up so strips
    divide evenly (capacities_for).
    """
    if buckets > 1:
        source = bucketed_batch_iterator(
            graphs, batch_size, buckets, shuffle=shuffle, rng=rng,
            dense_m=dense_m, in_cap=in_cap, snug=snug, stats=stats,
            edge_dtype=edge_dtype, node_multiple=node_multiple,
            transpose_shards=transpose_shards,
        )
    else:
        source = batch_iterator(
            graphs, batch_size, node_cap, edge_cap, shuffle=shuffle, rng=rng,
            dense_m=dense_m, in_cap=in_cap, snug=snug,
            edge_dtype=edge_dtype, transpose_shards=transpose_shards,
        )
        if stats is not None:
            source = stats.wrap(source)
    if prep_fn is not None:
        source = map(prep_fn, source)
    from cgnn_tpu.data import invariants

    pending: dict[tuple, list[GraphBatch]] = {}
    for b in source:
        key = batch_shape_key(b)
        q = pending.setdefault(key, [])
        q.append(b)
        if len(q) == n_devices:
            # train-time device groups (pad_incomplete=False) must have no
            # empty rows — the empty_batch_like eval-only contract
            yield invariants.maybe_check_any(
                stack_batches(q), dense_m, train=not pad_incomplete
            )
            pending[key] = []
    if pad_incomplete:
        for q in pending.values():
            if q:
                q += [empty_batch_like(q[0])] * (n_devices - len(q))
                yield invariants.maybe_check_any(stack_batches(q), dense_m)


def is_multiprocess_mesh(mesh: Mesh) -> bool:
    """True when ``mesh`` spans devices of more than one jax process —
    the multi-host DP case, where host-local staging must go through
    the global-array layer (parallel/dist.py) because ``device_put``
    can only address local devices."""
    from cgnn_tpu.parallel import dist

    if not dist.active():
        return False
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def shard_leading_axis(tree, mesh: Mesh):
    """Stage a stacked batch: leading axis split over every replica
    (non-'graph') mesh axis.

    Single-process: a plain sharded ``device_put``. Multi-process
    (``jax.distributed``): ``tree`` is this HOST'S local ``[n_local,
    ...]`` stack and the global batch is the process-order concatenation
    of every host's stack (dist.shard_global) — the loader-side per-host
    slicing of multi-host DP."""
    axes = _replica_axes(mesh)
    if is_multiprocess_mesh(mesh):
        from cgnn_tpu.parallel import dist

        return dist.shard_global(tree, mesh, P(axes))

    def put(x):
        return jax.device_put(
            x, NamedSharding(mesh, P(axes, *([None] * (np.ndim(x) - 1)))))
    return jax.tree_util.tree_map(put, tree)


def shard_scan_stack(tree, mesh: Mesh):
    """device_put a STACK of device-stacked batches ([B, D, ...] leaves):
    axis 0 is the scan/step axis (replicated), axis 1 the device axis
    (split over the replica mesh axes) — the staging for ScanEpochDriver
    under data parallelism."""
    axes = _replica_axes(mesh)

    def put(x):
        return jax.device_put(
            x,
            NamedSharding(mesh, P(None, axes, *([None] * (np.ndim(x) - 2)))),
        )
    return jax.tree_util.tree_map(put, tree)


def _squeeze0(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _replica_axes(mesh: Mesh) -> tuple[str, ...]:
    """Every mesh axis that carries data replicas ('graph' shards edges,
    not batches). A multi-host ('dcn', 'data') mesh reduces over both axes —
    XLA routes each partial reduction over the matching fabric."""
    return tuple(a for a in mesh.axis_names if a != "graph")


def make_parallel_train_step(
    mesh: Mesh,
    classification: bool = False,
    loss_fn: Callable | None = None,
    inner_step: Callable | None = None,
    grad_health: bool = False,
    guard: bool = False,
) -> Callable:
    """shard_map-wrapped train step: (replicated state, [D,...] batch).

    The batch's leading device axis is split over every non-'graph' mesh
    axis, so a 1-D ('data',) mesh and a hierarchical ('dcn', 'data')
    multi-host mesh run the same step body.

    ``inner_step`` overrides the default step body entirely (it must already
    be built with ``axis_name='data'`` — e.g. the force-task step; only
    supported on 1-D data meshes). ``grad_health`` adds the in-graph
    grad/update-norm and NaN/Inf metrics to the default body
    (train.step.make_train_step); extra outputs only. ``guard`` wraps the
    body with the in-graph divergence guard (resilience.guard): the
    post-pmean params it checks are replicated, so every device takes the
    same keep-or-skip branch.
    """
    axes = _replica_axes(mesh)
    if inner_step is not None and axes != ("data",):
        raise NotImplementedError(
            f"custom step bodies assume axis_name='data'; mesh has {axes}"
        )
    inner = inner_step or make_train_step(
        classification, axis_name=axes, loss_fn=loss_fn,
        grad_health=grad_health,
    )
    if guard:
        from cgnn_tpu.resilience.guard import guard_step

        inner = guard_step(inner)

    def body(state: TrainState, stacked: GraphBatch):
        return inner(state, _squeeze0(stacked))

    smapped = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(axes)),
        out_specs=(P(), P()),
        check_vma=False,  # grads/stats are pmean-ed -> replicated outputs
    )
    jitted = jax.jit(smapped, donate_argnums=TRAIN_STEP_DONATE)

    def guarded(state: TrainState, stacked: GraphBatch):
        # --check-invariants last line of defense for direct callers that
        # bypass the (already-checked) iterators: a host-side batch with an
        # all-padding device row must not reach a TRAINING step (the
        # empty_batch_like eval-only contract). Device-resident/traced
        # batches skip this (their construction paths were checked).
        from cgnn_tpu.data import invariants

        if invariants.enabled() and isinstance(stacked.graph_mask, np.ndarray):
            gm = stacked.graph_mask
            if (gm.reshape(gm.shape[0], -1).sum(axis=1) == 0).any():
                raise invariants.BatchInvariantError(
                    "training step received a stacked batch with an "
                    "all-padding device row (empty_batch_like is eval-only)"
                )
        return jitted(state, stacked)

    # the underlying jit, exposed for .lower() callers (the graftaudit
    # donation/roofline checks lower the REAL DP program, not a rebuild)
    guarded.jitted = jitted
    return guarded


def make_parallel_eval_step(
    mesh: Mesh,
    classification: bool = False,
    loss_fn: Callable | None = None,
    inner_step: Callable | None = None,
) -> Callable:
    axes = _replica_axes(mesh)
    if inner_step is not None and axes != ("data",):
        raise NotImplementedError(
            f"custom step bodies assume axis_name='data'; mesh has {axes}"
        )
    inner = inner_step or make_eval_step(
        classification, axis_name=axes, loss_fn=loss_fn
    )

    def body(state: TrainState, stacked: GraphBatch):
        return inner(state, _squeeze0(stacked))

    smapped = compat.shard_map(
        body, mesh=mesh, in_specs=(P(), P(axes)), out_specs=P(),
        check_vma=False,
    )
    return jax.jit(smapped)


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place every state leaf replicated across the mesh (the
    global-array path when the mesh spans processes — every host must
    hold the identical state, which resume/restore guarantees)."""
    if is_multiprocess_mesh(mesh):
        from cgnn_tpu.parallel import dist

        return dist.replicate_global(state, mesh)
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), state
    )


def fit_data_parallel(
    state: TrainState,
    train_graphs: Sequence[CrystalGraph],
    val_graphs: Sequence[CrystalGraph],
    *,
    epochs: int,
    batch_size: int,
    node_cap: int,
    edge_cap: int,
    classification: bool = False,
    seed: int = 0,
    print_freq: int = 10,
    on_epoch_end: Callable | None = None,
    log_fn: Callable = print,
    start_epoch: int = 0,
    mesh: Mesh | None = None,
    train_step_fn: Callable | None = None,
    eval_step_fn: Callable | None = None,
    best_metric: str | None = None,
    on_epoch_metrics: Callable | None = None,
    pack_once: bool = False,
    device_resident: bool = False,
    dense_m: int | None = None,
    buckets: int = 1,
    snug: bool = False,
    scan_epochs: bool = False,
    profile_steps: int = 0,
    profile_dir: str = "",
    edge_dtype=np.float32,
    chunk_steps: int | None = None,
    telemetry=None,
    guard: bool = False,
    monitor=None,
    preempt=None,
) -> tuple[TrainState, dict]:
    """DP twin of train.loop.fit; ``batch_size`` is per device.

    Feature parity with the single-device loop (VERDICT r2 #3): ``buckets``
    batches per size class and groups same-shape batches per device group;
    ``scan_epochs`` folds each epoch into one lax.scan dispatch per shape
    (ScanEpochDriver over mesh-sharded stacks); ``profile_steps`` traces
    post-compile steps of the first epoch. None of these are silently
    dropped anymore — unsupported combinations raise.

    ``train_step_fn``/``eval_step_fn`` override the step bodies (they must
    be built with ``axis_name='data'``); ``best_metric`` overrides the
    model-selection key.

    A 2-D ``('data', 'graph')`` mesh (parallel.mesh.make_2d_mesh) activates
    edge-sharded graph parallelism on top of DP: per-device batches keep
    their 'data' row but their edge leaves are split over 'graph'. The
    model in ``state.apply_fn`` must then be built with
    ``edge_axis_name='graph'``.

    ``pack_once`` / ``device_resident`` mirror train.loop.fit: pack (and,
    for device_resident, mesh-shard into HBM) the stacked batches once,
    reshuffling stacked-batch order across epochs.

    ``telemetry`` mirrors train.loop.fit: spans, padding/HBM gauges, and
    — with ``scan_epochs`` at step level — the in-scan per-step stream
    (the driver taps the post-shard_map metrics, one callback per step).
    The DP PER-STEP loop does not stream (its metrics live inside the
    shard_map body); epoch aggregates and gauges still flow.

    ``guard``/``monitor``/``preempt`` mirror train.loop.fit (the
    resilience layer; see that docstring). The guard wraps the step
    INSIDE shard_map — its keep-or-skip condition reads replicated
    post-pmean values, so every device selects the same branch. A
    monitor rollback re-replicates the restored state over the mesh
    automatically.
    """
    from cgnn_tpu.observe import Telemetry
    from cgnn_tpu.parallel.mesh import make_mesh

    telemetry = telemetry or Telemetry.disabled()
    mesh = mesh or make_mesh()
    if dense_m is not None:
        edge_cap = node_cap * dense_m
    graph_shards = int(mesh.shape.get("graph", 1))
    multiproc = is_multiprocess_mesh(mesh)
    if multiproc:
        if graph_shards > 1:
            raise NotImplementedError(
                "edge-sharded ('graph') meshes are single-host for now "
                "(per-conv psums belong on ICI, not DCN)"
            )
        if scan_epochs or device_resident or pack_once:
            raise NotImplementedError(
                "multi-host DP runs the per-step loop (scan/"
                "device-resident staging is host-local); drop "
                "--scan-epochs/--device-resident/--pack-once"
            )
    if graph_shards > 1 and profile_steps:
        raise NotImplementedError(
            "--profile is not supported with edge-sharded ('graph') "
            "meshes; use a pure data mesh"
        )
    if graph_shards > 1 and buckets > 1 and dense_m is None:
        raise NotImplementedError(
            "--buckets with --graph-shards requires the dense layout "
            "(per-size-class capacities shard by node strips)"
        )
    prep_train = prep_val = None
    node_multiple = 1
    transpose_shards = 1
    if graph_shards > 1:
        from cgnn_tpu.parallel.edge_parallel import (
            make_dp_edge_parallel_eval_step,
            make_dp_edge_parallel_train_step,
            prepare_dense_sharded,
            shard_stacked_batch,
        )

        if train_step_fn is not None or eval_step_fn is not None:
            raise NotImplementedError(
                "custom step bodies are not supported with graph sharding"
            )
        n_dev = int(mesh.shape["data"])
        if dense_m is not None:
            # dense fast path composed with node-strip graph sharding
            # (VERDICT r4 #3): round node_cap so every shard owns a whole
            # 8-aligned strip; train batches pack their per-shard
            # transpose mappings DIRECTLY (pack_graphs transpose_shards —
            # no pack-then-rebuild on the host critical path), eval
            # batches drop their mapping fields (prepare_dense_sharded)
            mult = 8 * graph_shards
            node_cap = -(-node_cap // mult) * mult
            edge_cap = node_cap * dense_m
            node_multiple = mult
            transpose_shards = graph_shards
            prep_val = lambda b: prepare_dense_sharded(  # noqa: E731
                b, graph_shards, train=False)
            train_step = make_dp_edge_parallel_train_step(
                mesh, classification, dense=True,
                grad_health=telemetry.step_level, guard=guard)
            eval_step = make_dp_edge_parallel_eval_step(
                mesh, classification, dense=True)
        else:
            # pack at a shard-divisible edge capacity up front (cheaper
            # than re-padding every batch after the fact)
            edge_cap = -(-edge_cap // graph_shards) * graph_shards
            train_step = make_dp_edge_parallel_train_step(
                mesh, classification,
                grad_health=telemetry.step_level, guard=guard)
            eval_step = make_dp_edge_parallel_eval_step(mesh, classification)
        shard_put = lambda b: shard_stacked_batch(b, mesh)  # noqa: E731
    else:
        n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        if multiproc:
            # each host packs device groups for its LOCAL share of the
            # mesh; the global batch is the process-order concatenation
            # (shard_leading_axis stages it as one global array). The
            # CALLER host-shards the graphs (dist.host_shard) so hosts
            # pack disjoint data.
            n_dev_global = n_dev
            n_dev = max(1, n_dev // jax.process_count())
        train_step = make_parallel_train_step(
            mesh, classification, inner_step=train_step_fn,
            grad_health=telemetry.step_level, guard=guard,
        )
        eval_step = make_parallel_eval_step(
            mesh, classification, inner_step=eval_step_fn
        )
        shard_put = lambda b: shard_leading_axis(b, mesh)  # noqa: E731
    state = replicate_state(state, mesh)
    best = -np.inf if classification else np.inf
    history = []
    rng = np.random.default_rng(seed)
    from cgnn_tpu.data.loader import prefetch_to_device
    from cgnn_tpu.train.loop import (
        PackOncePlan,
        ScanEpochDriver,
        profile_wrap,
        resilience_epoch_end,
        run_epoch,
        save_preempted_mid_epoch,
    )

    device_resident = device_resident or scan_epochs
    pack_once = pack_once or device_resident
    pad_stats = PaddingStats()

    def make_train_it():
        # env-gated deterministic fault injection (NaN batches, loader
        # exceptions); unwrapped when no plan is active. Wrapped AROUND
        # parallel_batches, so a poisoned batch is a full stacked device
        # group — every shard sees the fault, like a real bad record
        return faultinject.poison_batches(parallel_batches(
            train_graphs, n_dev, batch_size, node_cap, edge_cap,
            shuffle=True, rng=rng, dense_m=dense_m, buckets=buckets,
            snug=snug, stats=pad_stats, edge_dtype=edge_dtype,
            prep_fn=prep_train, node_multiple=node_multiple,
            transpose_shards=transpose_shards,
        ))

    def make_val_it():
        return parallel_batches(
            val_graphs, n_dev, batch_size, node_cap, edge_cap,
            pad_incomplete=True, dense_m=dense_m, in_cap=0, buckets=buckets,
            snug=snug, edge_dtype=edge_dtype,
            prep_fn=prep_val, node_multiple=node_multiple,
        )

    if multiproc:
        from cgnn_tpu.parallel import dist

        _base_train_it, _base_val_it = make_train_it, make_val_it

        def _equalized(base):
            # every host must run the SAME number of collective steps:
            # a host whose shard packed one more device group than its
            # peers would enter an allreduce nobody else joins (hang,
            # not error) — truncate every epoch to the shortest host.
            # COST, stated honestly: the count requires packing the
            # epoch, so the stacked batches materialize in host RAM up
            # front and the prefetch pack/compute overlap is lost for
            # multi-host runs (a second packing pass can't replace it:
            # the shuffled pack is rng-drawn, so two passes disagree on
            # the count itself). Fine at readiness scale; a streaming
            # upgrade needs a deterministic batch-count plan.
            batches = list(base())
            return iter(batches[: dist.min_over_hosts(len(batches))])

        def make_train_it():
            return _equalized(_base_train_it)

        def make_val_it():
            return _equalized(_base_val_it)

    driver: ScanEpochDriver | None = None
    packed_lists: tuple | None = None
    if scan_epochs:
        if profile_steps:
            log_fn(
                "scan_epochs: --profile is unavailable inside the "
                "whole-epoch scan (epoch-level metrics only)"
            )
        from cgnn_tpu.train.loop import (
            check_device_resident_fit,
            staged_nbytes,
        )

        with telemetry.span("pack"):
            train_list = list(make_train_it())
            val_list = list(make_val_it())
        # per-device share for the precheck: the stacked device axis
        # splits everything over the data shards; under graph sharding
        # the edge leaves (the dominant bytes: [N, M, G] stacks and the
        # per-shard transpose mappings) additionally split over 'graph',
        # while node/graph leaves replicate across it — dividing the
        # whole total by data shards alone would overestimate the share
        # by up to graph_shards x and spuriously kick sharded runs off
        # the scan fast path
        if graph_shards > 1:
            import dataclasses as _dc

            from cgnn_tpu.parallel.edge_parallel import (
                _DENSE_ONLY_FIELDS,
                EDGE_FIELDS,
            )

            sharded_fields = set(EDGE_FIELDS) | set(_DENSE_ONLY_FIELDS)
            e_bytes = o_bytes = 0
            for b in train_list + val_list:
                for f in _dc.fields(b):
                    x = getattr(b, f.name)
                    if x is None:
                        continue
                    if f.name in sharded_fields:
                        e_bytes += x.nbytes
                    else:
                        o_bytes += x.nbytes
            per_device = (e_bytes / (n_dev * graph_shards)
                          + o_bytes / n_dev)
            fits = check_device_resident_fit(int(per_device), n_devices=1,
                                             log_fn=log_fn)
        else:
            staged_bytes = staged_nbytes(train_list + val_list)
            fits = check_device_resident_fit(staged_bytes, n_devices=n_dev,
                                             log_fn=log_fn)
        if fits:
            if graph_shards > 1:
                # 2-D staging: edge leaves + per-shard transpose stacks
                # split over 'graph' inside each data shard; the scan
                # body's dynamic index preserves the inner shardings, so
                # the shard_map step sees the per-step path's layout
                from cgnn_tpu.parallel.edge_parallel import (
                    shard_scan_stack_2d,
                )

                stage = lambda t: shard_scan_stack_2d(t, mesh)  # noqa: E731
            else:
                stage = lambda t: shard_scan_stack(t, mesh)  # noqa: E731
            with telemetry.span("stage_scan_stacks"):
                driver = ScanEpochDriver(
                    train_step, eval_step, train_list, val_list,
                    rng, stage=stage, chunk_steps=chunk_steps,
                    telemetry=telemetry, preempt=preempt,
                )
            telemetry.sample_hbm("post_staging")
        else:
            # loud fallback (see check_device_resident_fit): host-side
            # pack-once, mesh-sharded restaging per epoch
            scan_epochs = False
            device_resident = False
            packed_lists = (train_list, val_list)
    plan = (
        PackOncePlan(
            (lambda: packed_lists[0]) if packed_lists is not None
            else make_train_it,
            (lambda: packed_lists[1]) if packed_lists is not None
            else make_val_it,
            rng,
            device_resident=device_resident, stage=shard_put,
        )
        if pack_once and driver is None
        else None
    )

    telemetry.observe_padding(pad_stats)
    if telemetry.step_level and driver is None:
        # the PR-1 known gap, closed (ISSUE 3): the DP per-step loop now
        # streams step records like the scan path. The tap cannot live
        # INSIDE the shard_map body (per-shard callbacks would emit one
        # partial record per device), but by the time metrics exit the
        # shard_map they are replicated psum totals — so wrap the whole
        # sharded step in an outer jit that stages ONE async callback per
        # step carrying the global sums. The scan driver is excluded on
        # purpose: it stages its own in-scan tap (wrapping here too would
        # double-record every step).
        train_step = jax.jit(telemetry.wrap_train_body(train_step),
                             donate_argnums=TRAIN_STEP_DONATE)
        eval_step = jax.jit(telemetry.wrap_eval_body(eval_step))
    if monitor is not None and monitor.post_restore is None:
        # a rollback restores onto the default device; re-place it
        # replicated over the mesh before the next sharded step
        monitor.post_restore = lambda s: replicate_state(s, mesh)
    preempted = False
    for epoch in range(start_epoch, epochs):
        t0 = time.perf_counter()
        if driver is not None:
            with telemetry.span("epoch", epoch=epoch, driver="scan"):
                state, train_m, val_m = driver.run_epoch_pair(
                    state, first=epoch == start_epoch
                )
            if driver.aborted:
                save_preempted_mid_epoch(state, epoch, on_epoch_end, log_fn)
                preempted = True
                break
            if epoch == start_epoch:
                log_fn(pad_stats.summary())
        else:
            if plan is not None:
                epoch_train, epoch_val = plan.epoch_iterators()
                if device_resident:
                    train_it, val_it = epoch_train, epoch_val
                else:
                    train_it = prefetch_to_device(
                        epoch_train, device_put=shard_put,
                        telemetry=telemetry)
                    val_it = prefetch_to_device(
                        epoch_val, device_put=shard_put, telemetry=telemetry)
            else:
                train_it = prefetch_to_device(
                    make_train_it(), device_put=shard_put, telemetry=telemetry
                )
                val_it = prefetch_to_device(
                    make_val_it(), device_put=shard_put, telemetry=telemetry)
            if epoch == start_epoch and profile_steps:
                train_it = profile_wrap(
                    train_it, profile_steps, profile_dir, log_fn
                )
            with telemetry.span("epoch", epoch=epoch, driver="per_step"):
                state, train_m = run_epoch(
                    train_step, state, train_it, train=True,
                    print_freq=print_freq, epoch=epoch, log_fn=log_fn,
                    telemetry=telemetry,
                )
            if epoch == start_epoch:
                log_fn(pad_stats.summary())
        if train_m["steps"] == 0:
            # drop_last semantics silently discard every incomplete device
            # group; a too-small dataset would otherwise "train" on nothing
            raise ValueError(
                f"no full device group: {len(train_graphs)} training graphs "
                f"cannot fill {n_dev} devices x batch_size {batch_size}; "
                f"reduce --batch-size or the device count"
            )
        train_count = max(train_m.get("count", 1.0), 1.0)
        train_loss = train_m.get("loss", np.nan)

        if driver is None:
            with telemetry.span("eval", epoch=epoch):
                _, val_m = run_epoch(
                    eval_step, state, val_it, train=False, epoch=epoch,
                    log_fn=log_fn, telemetry=telemetry,
                )
        best_key = best_metric or ("correct" if classification else "mae")
        metric = val_m.get(best_key, np.nan)
        is_best = metric > best if classification else metric < best
        if driver is not None and driver.eval_truncated:
            # preemption cut eval short: the metric covers a fraction of
            # the validation set — never let it repoint 'best'
            is_best = False
        if is_best:
            best = metric
        history.append({"epoch": epoch, "train_loss": train_loss, "val": val_m})
        tag = (f"dp x{n_dev_global} over {jax.process_count()} hosts"
               if multiproc else f"dp x{n_dev}") + (
            f" * graph x{graph_shards}" if graph_shards > 1 else ""
        )
        log_fn(
            f"Epoch {epoch} [{tag}]: train loss {train_loss:.4f}"
            f"  val {best_key} {metric:.4f}"
            f"{' *' if is_best else ''}  ({time.perf_counter() - t0:.1f}s)"
        )
        if on_epoch_metrics is not None:
            on_epoch_metrics(
                epoch, {"loss": train_loss, "count": train_count}, val_m
            )
        state, _, preempted = resilience_epoch_end(
            state, epoch, train_m, val_m, is_best, monitor=monitor,
            on_epoch_end=on_epoch_end, preempt=preempt, log_fn=log_fn,
        )
        if preempted:
            break
    out = {"best": best, "history": history}
    if preempted:
        out["preempted"] = True
    return state, out
