"""Device-mesh construction (SURVEY.md §5 distributed backend).

The north-star topology is a v5e-16 — a single ICI domain — so the default
mesh is 1-D ``('data',)``. A second ('dcn') axis for multi-slice scaling
composes with the same step body: grads are pmean-ed over both axes and XLA
routes each reduction over the right fabric.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def make_mesh(
    n_devices: int | None = None, axis: str = "data", devices=None
) -> Mesh:
    """1-D data mesh over the first ``n_devices`` visible devices."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} visible"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def make_2d_mesh(
    graph_shards: int,
    data_shards: int | None = None,
    devices=None,
    axes: tuple[str, str] = ("data", "graph"),
) -> Mesh:
    """('data', 'graph') mesh for DP x edge-sharded graph parallelism.

    ``data_shards`` defaults to every remaining device
    (``len(devices) // graph_shards``). Device order keeps graph shards on
    adjacent devices (the per-conv psum over 'graph' is the latency-critical
    collective; adjacency keeps it on the shortest ICI hops).
    """
    devs = list(devices if devices is not None else jax.devices())
    if data_shards is None:
        data_shards = max(1, len(devs) // graph_shards)
    need = data_shards * graph_shards
    if need > len(devs):
        raise ValueError(
            f"requested {data_shards}x{graph_shards} mesh, "
            f"only {len(devs)} devices visible"
        )
    return Mesh(np.array(devs[:need]).reshape(data_shards, graph_shards), axes)
