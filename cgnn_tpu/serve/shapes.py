"""Warm shape set: the fixed ladder of precompiled batch shapes.

The whole CGCNN-on-XLA lineage rests on one packing insight: dispatch is
cheap exactly when every batch reuses an already-compiled fixed shape
(data/graph.py). Offline that is easy — ``capacities_for`` derives snug
capacities per dataset. Online it is the hard part: traffic arrives one
structure at a time, batch composition varies second to second, and a
recompile (seconds, through a high-latency link) inside a request's
latency budget is an SLO kill. So the serving path inverts the offline
derivation: a SMALL FIXED LADDER of (graph_cap, node_cap, edge_cap)
rungs is quantized ONCE from a calibration sample, every rung is
compiled at startup (through the persistent XLA compile cache, so a
restart warms from disk), and the micro-batcher only ever packs into
rungs from this set — zero recompiles after warmup, by construction.

The same ``ShapeSet`` serves offline: ``train.infer.run_fast_inference``
accepts one in place of its per-bucket capacity derivation, so predict
jobs reuse the serving shapes (and the serving compile cache) instead of
compiling fresh per-dataset programs.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import numpy as np

from cgnn_tpu.data.graph import (
    CrystalGraph,
    GraphBatch,
    capacities_for,
    graph_cap_for,
    pack_graphs,
)


def _align8(n: int) -> int:
    return max(8, -(-int(n) // 8) * 8)


@dataclasses.dataclass(frozen=True, order=True)
class BatchShape:
    """One compiled batch shape (capacities, not contents)."""

    graph_cap: int
    node_cap: int
    edge_cap: int

    def fits(self, n_graphs: int, n_nodes: int, n_edges: int) -> bool:
        return (
            n_graphs <= self.graph_cap
            and n_nodes <= self.node_cap
            and n_edges <= self.edge_cap
        )

    def to_meta(self) -> dict:
        return dataclasses.asdict(self)


class ShapeSet:
    """An ascending ladder of :class:`BatchShape` rungs plus the packing
    parameters (dense layout, edge dtype, target width) every rung shares.

    ``shape_for`` picks the SMALLEST rung that fits a request set — a
    half-empty flush then pays a small program's latency, not the full
    batch shape's. ``admits`` is the oversize gate: a single structure
    that does not fit the largest rung can never be served and is
    rejected at admission, with the observed sizes in the error.
    """

    def __init__(
        self,
        shapes: Sequence[BatchShape],
        *,
        dense_m: int | None = None,
        edge_dtype=np.float32,
        num_targets: int = 1,
        compact=None,
        raw=None,
    ):
        if not shapes:
            raise ValueError("a ShapeSet needs at least one shape")
        self.shapes = tuple(sorted(set(shapes)))
        self.dense_m = dense_m
        self.edge_dtype = edge_dtype
        self.num_targets = num_targets
        # CompactSpec | None: with a spec, pack() stages the raw compact
        # form (data/compact.py — ~12x fewer host bytes written and H2D
        # bytes moved) and the predict step must carry the matching
        # expander (train.step.make_predict_step(expander=...)) so the
        # exact GraphBatch is rebuilt INSIDE the compiled program
        self.compact = compact
        if compact is not None and dense_m is None:
            raise ValueError("compact staging requires the dense layout "
                             "(dense_m)")
        # RawSpec | None (ISSUE 11): with one, the set ALSO compiles a
        # raw-wire program per rung — wire-form (positions, lattice,
        # species) structures stage as RawBatch and the in-program
        # neighbor search builds the graph (ops/neighbor_search.py).
        # The spec's snode_cap/image caps are shared by every rung (the
        # admitted-fits-every-rung floor rule); rung r's raw program
        # holds graph_cap_r structure slots.
        self.raw = raw
        if raw is not None:
            if dense_m is None:
                raise ValueError("raw wire requires the dense layout "
                                 "(dense_m)")
            if raw.dense_m != dense_m:
                raise ValueError(
                    f"raw spec max_num_nbr {raw.dense_m} != layout "
                    f"dense_m {dense_m} (the in-program truncation must "
                    f"match the model's slot layout)"
                )
        for s in self.shapes:
            if dense_m is not None and s.edge_cap != s.node_cap * dense_m:
                raise ValueError(
                    f"dense layout requires edge_cap == node_cap * dense_m "
                    f"for every rung; {s} violates it (dense_m={dense_m})"
                )

    def __len__(self) -> int:
        return len(self.shapes)

    def __iter__(self):
        return iter(self.shapes)

    @property
    def largest(self) -> BatchShape:
        return self.shapes[-1]

    def expander(self):
        """Jit-composable CompactBatch -> GraphBatch reconstruction for
        this set's spec (None without compact staging) — hand it to
        ``train.step.make_predict_step(expander=...)``."""
        if self.compact is None:
            return None
        from cgnn_tpu.data.compact import make_expander

        return make_expander(self.compact)

    def compactable(self, graph: CrystalGraph) -> bool:
        """Can this graph stage compactly under the set's spec? (Always
        False without one; never raises — the serving admission probe.)"""
        return (self.compact is not None
                and self.compact.graph_compactable(graph))

    def raw_expander(self, impl: str = "xla"):
        """Jit-composable RawBatch -> (GraphBatch, overflow, n_edges)
        for this set's raw spec (None without one) — hand it to
        ``train.step.make_predict_step(raw_expander=...)``."""
        if self.raw is None:
            return None
        from cgnn_tpu.ops.neighbor_search import make_raw_expander

        return make_raw_expander(self.raw, edge_dtype=self.edge_dtype,
                                 impl=impl)

    def admits_raw(self, rs) -> bool:
        """Host pre-check: can this wire-form structure be staged raw
        (atom count + periodic image caps, f64)? Always False without a
        raw spec; never raises — the serving admission probe. A False
        here routes the request to the host-featurized fallback, not to
        a rejection."""
        return self.raw is not None and self.raw.admits(rs)

    def pack_raw(self, items: Sequence, shape: BatchShape | None = None):
        """Stage wire-form structures into one rung's RawBatch (default:
        the smallest rung whose graph slots fit them)."""
        if self.raw is None:
            raise ValueError("this shape set carries no raw spec")
        from cgnn_tpu.data.rawbatch import pack_raw

        if shape is None:
            for s in self.shapes:
                if len(items) <= s.graph_cap:
                    shape = s
                    break
            if shape is None:
                raise ValueError(
                    f"{len(items)} structures fit no rung's graph slots"
                )
        return pack_raw(list(items), shape.graph_cap, self.raw,
                        num_targets=self.num_targets)

    def graph_counts(self, graph: CrystalGraph) -> tuple[int, int]:
        """(nodes, edge slots) one graph consumes under this set's layout.

        Dense layout consumes ``nodes * dense_m`` edge slots regardless of
        the true edge count (slot ownership is structural)."""
        if self.dense_m is not None:
            return graph.num_nodes, graph.num_nodes * self.dense_m
        return graph.num_nodes, graph.num_edges

    def admits(self, graph: CrystalGraph) -> bool:
        n, e = self.graph_counts(graph)
        return self.largest.fits(1, n, e)

    def oversize_detail(self, graph: CrystalGraph) -> str:
        n, e = self.graph_counts(graph)
        big = self.largest
        return (
            f"structure has {n} nodes / {e} edge slots; the largest "
            f"compiled shape holds {big.node_cap} nodes / {big.edge_cap} "
            f"edge slots"
        )

    def shape_for(self, n_graphs: int, n_nodes: int,
                  n_edges: int) -> BatchShape | None:
        """Smallest rung fitting the given totals (None = nothing fits)."""
        for s in self.shapes:
            if s.fits(n_graphs, n_nodes, n_edges):
                return s
        return None

    def _resolve(self, graphs: Sequence[CrystalGraph],
                 shape: BatchShape | None) -> BatchShape:
        if shape is not None:
            return shape
        n = sum(g.num_nodes for g in graphs)
        e = sum(self.graph_counts(g)[1] for g in graphs)
        shape = self.shape_for(len(graphs), n, e)
        if shape is None:
            raise ValueError(
                f"{len(graphs)} graphs ({n} nodes) fit no shape in "
                f"{self.shapes}"
            )
        return shape

    def pack(self, graphs: Sequence[CrystalGraph],
             shape: BatchShape | None = None, out=None):
        """Pack ``graphs`` into ``shape`` (default: smallest fitting rung).

        With a compact spec this stages the raw ``CompactBatch`` form
        (``out`` recycles a pooled staging buffer); without one, the
        full-fidelity ``GraphBatch``."""
        shape = self._resolve(graphs, shape)
        if self.compact is not None:
            from cgnn_tpu.data.compact import pack_compact

            return pack_compact(
                list(graphs),
                shape.node_cap,
                shape.edge_cap,
                shape.graph_cap,
                self.compact,
                num_targets=self.num_targets,
                dense_m=self.dense_m,
                out=out,
            )
        return self.pack_full(graphs, shape)

    def pack_full(self, graphs: Sequence[CrystalGraph],
                  shape: BatchShape | None = None) -> GraphBatch:
        """Full-fidelity pack regardless of the compact spec — the
        serving fallback for requests that cannot stage compactly (no
        raw distances / atom rows outside the vocabulary)."""
        shape = self._resolve(graphs, shape)
        return pack_graphs(
            list(graphs),
            shape.node_cap,
            shape.edge_cap,
            shape.graph_cap,
            num_targets=self.num_targets,
            dense_m=self.dense_m,
            # in_cap/over_cap omitted: forward-only batches carry no
            # transpose slots (the backward-pass-only layout)
            edge_dtype=self.edge_dtype,
        )

    def abstract_batches(self, template: CrystalGraph) -> dict:
        """{(rung index, staging form): abstract batch pytree} for every
        program this set compiles — the graftaudit lowering surface.

        Packs one copy of ``template`` per rung (exactly the batches
        ``serve.server.warm()`` dispatches) and maps every leaf to a
        ``jax.ShapeDtypeStruct``, so ``jax.jit(...).lower(state_aval,
        batch_aval)`` sees the same traced programs serving warms —
        without touching a device. Forms: ``"compact"`` and ``"full"``
        for a compact set (warm() compiles both per rung), ``"full"``
        only otherwise."""
        import jax

        def aval(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        out = {}
        for i, shape in enumerate(self.shapes):
            forms = {}
            if self.compact is not None:
                forms["compact"] = self.pack([template], shape=shape)
            forms["full"] = self.pack_full([template], shape=shape)
            if self.raw is not None:
                forms["raw"] = self.pack_raw([self.raw.template()],
                                             shape=shape)
            for form, batch in forms.items():
                out[(i, form)] = jax.tree_util.tree_map(aval, batch)
        return out

    def buffer_key(self, shape: BatchShape) -> tuple:
        """Staging-buffer pool key for one rung (compact sets only)."""
        if self.compact is None:
            raise ValueError("buffer pooling applies to compact staging")
        from cgnn_tpu.data.compact import compact_buffer_key

        return compact_buffer_key(shape.node_cap, self.dense_m,
                                  shape.graph_cap, self.num_targets)

    def buffer_factory(self, shape: BatchShape):
        """() -> fresh staging buffers for one rung (BufferPool factory)."""
        if self.compact is None:
            raise ValueError("buffer pooling applies to compact staging")
        from cgnn_tpu.data.compact import alloc_compact_buffers

        return lambda: alloc_compact_buffers(
            shape.node_cap, self.dense_m, shape.graph_cap, self.num_targets
        )

    def to_meta(self) -> dict:
        return {
            "shapes": [s.to_meta() for s in self.shapes],
            "dense_m": self.dense_m,
            "edge_dtype": np.dtype(self.edge_dtype).name
            if self.edge_dtype is not np.float32 else "float32",
            "num_targets": self.num_targets,
            "compact": self.compact is not None,
            "raw": None if self.raw is None else self.raw.to_meta(),
        }


def plan_shape_set(
    calibration: Sequence[CrystalGraph],
    batch_size: int,
    *,
    rungs: int = 3,
    dense_m: int | None = None,
    edge_dtype=np.float32,
    num_targets: int | None = None,
    compact=None,
    raw=None,
) -> ShapeSet:
    """Quantize a serving ladder from a calibration sample.

    The top rung is the offline-proven snug full-batch shape
    (``capacities_for(snug=True)`` at ``batch_size`` with
    ``graph_cap_for`` slack); each lower rung halves the graph budget and
    scales node/edge capacity proportionally (8-aligned), floored so that
    ANY admitted structure fits EVERY rung — a deadline flush holding one
    lone large structure must still have a rung to land in. ``rungs``
    bounds the compile count: warmup compiles exactly ``len(set)``
    programs, and nothing after warmup ever compiles.
    """
    if not len(calibration):
        raise ValueError("shape planning needs a calibration sample")
    if rungs < 1:
        raise ValueError(f"rungs must be >= 1, got {rungs}")
    node_cap, edge_cap = capacities_for(
        calibration, batch_size, dense_m=dense_m, snug=True
    )
    # any admitted graph must fit the smallest rung (see docstring)
    max_nodes = max(g.num_nodes for g in calibration)
    max_edges = max(g.num_edges for g in calibration)
    if num_targets is None:
        num_targets = int(np.atleast_1d(calibration[0].target).shape[0])
    shapes = []
    for r in range(rungs):
        scale = 2**r
        b = max(1, math.ceil(batch_size / scale))
        nc = _align8(max(math.ceil(node_cap / scale), max_nodes))
        if dense_m is not None:
            ec = nc * dense_m
        else:
            ec = _align8(max(math.ceil(edge_cap / scale), max_edges))
        shapes.append(BatchShape(graph_cap_for(b), nc, ec))
    return ShapeSet(
        shapes,
        dense_m=dense_m,
        edge_dtype=edge_dtype,
        num_targets=num_targets,
        compact=compact,
        raw=raw,
    )
