"""Device inventory + thread-per-device dispatch accounting (ISSUE 5).

The CGCNN workload is embarrassingly parallel at inference — independent
graphs, no cross-request state — yet until this module both forward
paths dispatched every batch to ``jax.devices()[0]``, idling every other
chip on a multi-chip host. ``DeviceSet`` made the device dimension a
first-class part of the dispatch layer.

ENGINE NOTE (ISSUE 10): thread-per-device dispatch is no longer the
only — or the default — multi-device engine. The default for a
multi-device set is the MESH engine (``parallel/executor.py``): one
``Mesh`` + ``NamedSharding`` jitted program per (rung, form, tier)
whose single batch-sharded dispatch covers every device — no router,
no per-device threads, compile count = programs (not programs x N),
one sharded param tree per tier, and the same layer extends multi-host
via ``jax.distributed`` (``parallel/dist.py``). The DeviceSet dispatch
path stays available behind ``--engine threads`` as the A/B baseline,
and this module's ACCOUNTING (per-device dispatch/occupancy stats)
serves both engines — under mesh dispatch the "device" rows are the
mesh shards. The replica-dispatch description below documents the
threads engine:

- **Replicated programs.** ONE jitted ``predict_step`` is shared across
  the set. Dispatch targets a device by computation-follows-data: the
  per-device param replica is committed to its device, the host batch is
  uncommitted, so the call runs where the params live — no explicit
  placement per dispatch. Tracing happens once per (rung, staging form)
  regardless of N (the jit trace cache keys on abstract values, not
  devices); XLA then builds one executable per device at WARMUP, because
  a compiled artifact is bound to its device assignment. After warmup
  nothing ever compiles — the same pin as ISSUE 3, now × N devices (and,
  with precision tiers, × tiers — serve/quantize.py: a tier is its own
  traced program, warmed on every device like any other): the jit cache
  size is ``programs * len(devices)`` and must not grow under load
  (checked per flush by the server, by the loadgen, and by tests).

- **Replicated params** live in :class:`serve.reload.ParamStore` (one
  replica per device, swapped atomically under a single version — see
  reload.py); this module only carries the device inventory and the
  dispatch bookkeeping.

- **Dispatcher accounting.** ``pick()`` chooses the least-loaded device
  (fewest in-flight dispatches, round-robin tie-break), and per-device
  counters (dispatches, busy seconds, window depth) feed the
  ``device_gauges`` rollup in observe/gauges.py.

Device-awareness default (the PR-4 lesson, third time paying off):
``resolve_devices('auto')`` is ALL local devices on an accelerator
backend but a SINGLE device on CPU — host-platform "devices" are slices
of the same cores, so fanning out over them just adds dispatch overhead
and thread contention to the compute they share. The CPU ``auto`` rule
applies to WHICH devices are used; the ``--engine`` flag picks how a
multi-device set is driven (mesh by default, threads for the A/B). An
explicit count (``--devices N``) forces distribution anywhere, which is
how the 8-host-device dryruns
(``--xla_force_host_platform_device_count=8``, the MULTICHIP pattern)
prove distribution, parity, and swap invariants for both engines
in-container.
"""

from __future__ import annotations

import time
from typing import Sequence

from cgnn_tpu.analysis import racecheck


def resolve_devices(spec="auto"):
    """``spec`` -> a concrete list of local jax devices.

    - ``'auto'`` (default): all local devices on accelerator backends;
      just ``[devices()[0]]`` on a CPU backend, where the "devices" are
      slices of the host's own cores (see module docstring);
    - an int (or numeric string) N: the first N local devices, forced
      regardless of backend — errors if fewer exist (a silent clamp
      would fake the distribution a dryrun is trying to prove).
    """
    import jax

    local = list(jax.local_devices())
    if spec is None or spec == "auto":
        if jax.default_backend() == "cpu":
            return local[:1]
        return local
    n = int(spec)
    if n < 1:
        raise ValueError(f"--devices must be >= 1, got {n}")
    if n > len(local):
        raise ValueError(
            f"--devices {n} requested but only {len(local)} local "
            f"device(s) exist (JAX_PLATFORMS="
            f"{jax.default_backend()}; use "
            f"--xla_force_host_platform_device_count for CPU dryruns)"
        )
    return local[:n]


def replicate_state(state, devices: Sequence):
    """One committed copy of ``state`` per device (pytree device_put).

    Replica 0 of a state already resident on ``devices[0]`` is a no-copy
    alias — fine here: replicas are read-only under the forward path.
    """
    import jax

    return tuple(jax.device_put(state, d) for d in devices)


class DeviceSet:
    """The device inventory + dispatch accounting for one forward path.

    Thread-safe: serving runs one dispatch worker PER device plus a
    router; every mutation here is under one lock. The accounting feeds
    ``stats()`` (the server's /stats payload) and ``flush_gauges``
    (telemetry counters/gauges that ``observe.gauges.device_gauges``
    rolls up into run_summary).
    """

    def __init__(self, devices: Sequence | None = None, *, window: int = 16):
        if devices is None:
            devices = resolve_devices("auto")
        devices = list(devices)
        if not devices:
            raise ValueError("a DeviceSet needs at least one device")
        self.devices = tuple(devices)
        self.window = max(1, int(window))
        self._lock = racecheck.make_lock("serve.devices")
        n = len(self.devices)
        self._inflight = [0] * n     # routed or dispatched, not yet fetched
        self._dispatches = [0] * n
        self._busy_s = [0.0] * n     # dispatch->fetch wall per device
        self._max_depth = [0] * n
        self._rr = 0
        self._t0 = time.perf_counter()

    def __len__(self) -> int:
        return len(self.devices)

    # ---- dispatcher ----

    def pick(self) -> int:
        """Least-loaded device index (in-flight count; round-robin tie
        break so idle sets still rotate instead of pinning device 0)."""
        with self._lock:
            n = len(self.devices)
            best, best_load = None, None
            for off in range(n):
                i = (self._rr + off) % n
                load = self._inflight[i]
                if best_load is None or load < best_load:
                    best, best_load = i, load
            self._rr = (best + 1) % n
            return best

    def note_enqueue(self, i: int) -> None:
        with self._lock:
            self._inflight[i] += 1
            self._max_depth[i] = max(self._max_depth[i], self._inflight[i])

    def note_complete(self, i: int, busy_s: float, ok: bool = True) -> None:
        """Retire one routed flush. The in-flight count always drops;
        dispatch/busy accounting only accrues for flushes that actually
        ran (``ok``) — a device whose flushes all FAILED must read as
        idle in the distribution gauges, not as serving work."""
        with self._lock:
            self._inflight[i] = max(0, self._inflight[i] - 1)
            if ok:
                self._dispatches[i] += 1
                self._busy_s[i] += float(busy_s)

    def inflight(self, i: int) -> int:
        with self._lock:
            return self._inflight[i]

    def inflight_depths(self) -> list[int]:
        """Every device's in-flight depth in one lock acquisition — the
        live-observability view (/stats rolling + the /metrics scrape):
        routed-but-unfetched flushes per device, right now."""
        with self._lock:
            return list(self._inflight)

    # ---- accounting ----

    def stats(self) -> list[dict]:
        """One record per device (the /stats + run-summary payload)."""
        wall = max(time.perf_counter() - self._t0, 1e-9)
        with self._lock:
            return [
                {
                    "device_id": i,
                    "device": str(d),
                    "dispatches": self._dispatches[i],
                    "busy_s": round(self._busy_s[i], 4),
                    "occupancy": min(1.0, self._busy_s[i] / wall),
                    "inflight": self._inflight[i],
                    "max_window_depth": self._max_depth[i],
                }
                for i, d in enumerate(self.devices)
            ]

    def flush_gauges(self, telemetry) -> None:
        """Write per-device gauges into ``telemetry`` under the
        ``device{i}_*`` names ``observe.gauges.device_gauges`` rolls up
        (gauges overwrite, so repeated flushes stay idempotent)."""
        if telemetry is None:
            return
        for rec in self.stats():
            i = rec["device_id"]
            telemetry.set_gauge(f"device{i}_dispatches",
                                float(rec["dispatches"]))
            telemetry.set_gauge(f"device{i}_occupancy", rec["occupancy"])
            telemetry.set_gauge(f"device{i}_window_depth",
                                float(rec["max_window_depth"]))
        telemetry.set_gauge("device_count", float(len(self.devices)))
