"""The in-process online inference server (the serve/ core).

``InferenceServer`` is deliberately socket-free: submit() -> future ->
result, driven by one worker thread — the whole request path (admission,
micro-batching, packing, dispatch, hot reload, caching, draining) is
exercisable from a unit test or an in-process load generator with no
ports involved. The stdlib HTTP front-end (serve/http.py) is a thin
translation layer on top.

Request lifecycle::

    submit(graph)
      -> cache hit?  resolve immediately (no queue)
      -> batcher.offer (admission: oversize / queue-full / draining;
         compact-stageability decided here, per request)
    worker (pack_workers > 0 — the default on accelerators):
      feeder: batcher.next_flush() -> expired fail with TIMEOUT
        -> packer pool (data/pipeline.py): pack into the flush's
           precompiled shape — compact raw form when every member can,
           warmed full-fidelity otherwise — into pooled buffers
      dispatch: for each packed flush, in order:
        -> (state, version) = param_store.get(device)  # hot-swap boundary
        -> predict_step(state, batch) -> device_get
        -> resolve each future with (row, version, latency, device_id)
      (so the batcher coalesces flush N+2 while N+1 packs and N runs;
       pack_workers=0 runs the same stages in-line on one thread)
    with devices > 1 the ENGINE decides how the set is driven
      (ISSUE 10): the default 'mesh' engine splits each flush
      round-robin across a Mesh + NamedSharding layout and ONE sharded
      jitted dispatch covers every device (no router, no per-device
      threads; parallel/executor.py); 'threads' keeps the ISSUE-5
      DeviceSet layer — a router assigns each packed flush to the
      least-loaded device and one dispatch thread PER device runs it
      against that device's param replica

Hot reload safety rides on the ``param_store.get()`` placement: the pair
is read once per batch, so a watcher swap lands cleanly between batches
and in-flight work finishes on the params it started with. Every
response carries ``param_version`` so clients (and the loadgen's
hot-swap assertion) can see exactly which weights answered.

``warm()`` compiles every shape in the set before the server accepts
traffic — with the persistent XLA compile cache configured, a restart
replays compilations from disk. After warmup the compile count is
PINNED: the batcher only emits shapes from the warm set, so
``predict_step`` never traces again (asserted by tests via the jit
cache-miss counter, and re-checked per flush when telemetry is on).

Live observability plane (ISSUE 6), all host-side — predictions are
bit-identical with it on or off and nothing new is staged into jitted
code:

- every request gets a trace id at admission (inbound ``X-Request-Id``
  honored) and monotonic stage stamps (queued/packed/dispatched/
  fetched/replied) that ride the ``ServeResult`` and, when telemetry is
  on, land as ``serve.request``/``serve.pack``/``serve.dispatch`` spans
  in the Chrome-trace stream, joined by the flush id co-batched
  requests share;
- ``self.registry`` (observe/export.py) is the scrape point behind
  ``GET /metrics`` and ``stats()["rolling"]``: request counters,
  per-device in-flight depth, and 60 s rolling-window latency/occupancy
  quantiles, live at any moment of the run;
- ``enable_profiling(dir)`` arms the on-demand bounded ``jax.profiler``
  capture behind ``POST /profile`` and SIGUSR2 (one at a time;
  concurrent requests are rejected).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Sequence

import numpy as np

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.data.graph import CrystalGraph
from cgnn_tpu.data.rawbatch import RawStructure, raw_fingerprint
from cgnn_tpu.resilience import faultinject
from cgnn_tpu.serve.batcher import (
    CLASSES,
    DEFAULT_CLASS,
    MALFORMED,
    OVERSIZE,
    TIMEOUT,
    Flush,
    MicroBatcher,
    Request,
    RequestFuture,
    ServeRejection,
)
from cgnn_tpu.serve.cache import ResultCache, structure_fingerprint
from cgnn_tpu.serve.devices import DeviceSet, resolve_devices
from cgnn_tpu.serve.reload import CheckpointWatcher, ParamStore
from cgnn_tpu.serve.shapes import ShapeSet, plan_shape_set


@dataclasses.dataclass
class ServeResult:
    """One answered request."""

    prediction: np.ndarray  # [T] denormalized
    param_version: str
    latency_ms: float
    cached: bool = False
    # precision tier that computed it (serve/quantize.py; 'f32' =
    # checkpoint-native program)
    precision: str = "f32"
    batch_occupancy: float = 0.0  # real graphs / graph slots of its batch
    # which device of the set answered (ISSUE 5); -1 for cache hits — no
    # device computed them, and attributing them to device 0 would skew
    # client-side per-device accounting on a multi-device server
    device_id: int = 0
    # the request's journey (live observability plane): its trace id
    # (minted at admission or inherited from X-Request-Id), the flush it
    # was co-batched into, and the monotonic per-stage stamps
    # (queued/packed/dispatched/fetched/replied; SpanTracer.now_s
    # seconds — cache hits carry only queued/replied)
    trace_id: str = ""
    flush_id: str = ""
    stamps: dict = dataclasses.field(default_factory=dict)
    # which wire form computed it (ISSUE 11): 'raw' = the in-program
    # neighbor search built the graph from (positions, lattice,
    # species); 'featurized' = a host-built graph (client-featurized
    # arrays, the deferred pack-pool featurize, or the cap-overflow
    # fallback)
    wire: str = "featurized"
    # priority class served under (ISSUE 19; batcher.CLASSES) and
    # whether this request rode a higher-class flush's padding slack —
    # a backfilled reply is a normal reply (same program, same rung,
    # own trace id), the flag is accounting, never a quality downgrade
    klass: str = DEFAULT_CLASS
    backfilled: bool = False
    # single-flight miss coalescing (ISSUE 20): this answer was copied
    # from an identical-fingerprint request already in flight instead of
    # entering the batcher — same row the leader computed, own trace id
    coalesced: bool = False


class InferenceServer:
    """Micro-batching online inference over a warm shape set.

    ``state`` is a restored-for-inference TrainState; ``shape_set`` the
    precompiled ladder (shapes.plan_shape_set). ``predict_step`` defaults
    to ``jax.jit(make_predict_step())`` — inject a pre-jitted one to share
    its compile cache with an offline predict path. ``devices`` (a list
    of jax devices, or None for the backend-aware auto resolution) sets
    the dispatch fan-out: params replicate per device, flushes route
    least-loaded, every response records its ``device_id``.
    """

    def __init__(
        self,
        state,
        shape_set: ShapeSet,
        *,
        predict_step: Callable | None = None,
        version: str = "init",
        telemetry=None,
        max_queue: int = 256,
        max_wait_ms: float = 5.0,
        class_max_wait_ms: dict | None = None,
        backfill: bool = True,
        wfq_weights: dict | None = None,
        default_timeout_ms: float | None = 1000.0,
        cache_size: int = 1024,
        single_flight: bool = True,
        pack_workers: int = 1,
        devices=None,
        engine: str = "auto",
        precisions: Sequence[str] = ("f32",),
        model=None,
        featurizer: Callable | None = None,
        raw_precheck: bool = True,
        trace_ring: int = 65536,
        slo_layer: bool = True,
        slo_objectives=None,
        slo_rules=None,
        tsdb_interval_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        log_fn: Callable = print,
    ):
        import jax

        from cgnn_tpu.observe import Telemetry
        from cgnn_tpu.train.step import make_predict_step

        self.shape_set = shape_set
        # the device inventory + per-device accounting (serve/devices.py);
        # None = the backend-aware 'auto' resolution (all accelerator
        # devices; single device on CPU backends). How the devices are
        # DRIVEN is the engine's choice below.
        self.device_set = DeviceSet(devices)
        # execution engine over the device set (ISSUE 10):
        # - 'mesh' (the default with > 1 device): ONE Mesh+NamedSharding
        #   jitted program per (rung, form, tier) whose single dispatch
        #   covers every device — flushes split batch-axis across the
        #   mesh, params live as one replicated tree, no router and no
        #   per-device dispatch threads (parallel/executor.py);
        # - 'threads' (the ISSUE-5 layer, kept for the A/B): per-device
        #   param replicas, least-loaded router, one dispatch thread per
        #   device, programs x N executables.
        # With one device both engines degenerate to the single-device
        # dispatch loop; 'auto' resolves to 'mesh' on a real multi-device
        # set and leaves single-device servers on the classic path.
        if engine not in ("auto", "mesh", "threads"):
            raise ValueError(
                f"engine must be 'auto', 'mesh', or 'threads', "
                f"got {engine!r}"
            )
        if engine == "auto":
            engine = "mesh" if len(self.device_set) > 1 else "threads"
        self.mesh_exec = None
        if engine == "mesh" and len(self.device_set) > 1:
            from cgnn_tpu.parallel.executor import MeshExecutor

            self.mesh_exec = MeshExecutor(self.device_set.devices)
        # report what actually RUNS, not what was requested: a forced
        # 'mesh' on a 1-device set takes the single-device loop, and
        # stats claiming otherwise would let a dryrun assert an engine
        # that never dispatched
        if len(self.device_set) == 1:
            engine = "single"
        elif self.mesh_exec is None:
            engine = "threads"
        self.engine = engine
        # precision tiers (serve/quantize.py): the warmed set a request
        # picks from. 'f32' (the native program) is always present —
        # it is the default tier and the parity baseline. Tier states
        # are derived ONCE here (stable apply_fn identities) and
        # re-derived through the same specs on every hot swap.
        tiers = tuple(dict.fromkeys(("f32", *precisions)))
        tier_specs = None
        if tiers != ("f32",):
            from cgnn_tpu.serve.quantize import build_tier_specs

            if model is None:
                raise ValueError(
                    "precision tiers beyond 'f32' need the model module "
                    "(InferenceServer(model=...)) to derive bf16/int8 "
                    "programs"
                )
            tier_specs = build_tier_specs(model, tiers)
        self.precisions = tiers
        if self.mesh_exec is not None:
            # mesh engine: the store holds ONE mesh-replicated tree per
            # tier (get(0, tier)); a hot swap publishes one sharded
            # param tree under one version — no replica tuples
            self.param_store = ParamStore(
                state, version, tier_specs=tier_specs,
                placer=self.mesh_exec.place_params,
            )
        else:
            self.param_store = ParamStore(state, version,
                                          devices=self.device_set.devices,
                                          tier_specs=tier_specs)
        # wire-form structure handling (ISSUE 11): ``featurizer``
        # (RawStructure -> CrystalGraph, see ``structure_featurizer``)
        # powers the deferred pack-pool featurize and the cap-overflow
        # fallback; ``raw_precheck=False`` skips the host image-cap
        # pre-check at admission so tests/smoke can exercise the
        # IN-PROGRAM overflow flag end to end (production keeps it on —
        # the flag is the safety net, not the primary gate)
        self.featurizer = featurizer
        self._raw_precheck = bool(raw_precheck)
        # a compact shape set rebuilds GraphBatches INSIDE the compiled
        # program (expander); a raw shape set ADDITIONALLY carries the
        # in-program neighbor-search program (raw_expander); the same
        # jitted callable still accepts full-fidelity batches — the
        # fallback for non-compactable/non-raw requests (every form is
        # warmed, so none ever recompiles)
        predict_body = make_predict_step(shape_set.expander(),
                                         shape_set.raw_expander())
        self.predict_step = predict_step or jax.jit(predict_body)
        # the mesh engine's one-dispatch-covers-all-devices program
        # (parallel/executor.py): per (rung, form, tier) there is ONE
        # cache entry and ONE multi-device executable. An injected
        # predict_step is wrapped so the body stays shared.
        self.mesh_predict = None
        if self.mesh_exec is not None:
            self.mesh_predict = self.mesh_exec.shard_predict(
                predict_step or predict_body
            )
        # pack pipeline threads between the batcher and the dispatch
        # loop (data/pipeline.py): packing comes off the flush/dispatch
        # thread so the batcher coalesces the NEXT flush while the
        # current one packs and runs; 0 restores the in-line pack
        self._pack_workers = max(0, int(pack_workers))
        self.telemetry = telemetry or Telemetry.disabled()
        # ---- metrics-truth layer (ISSUE 16) ----
        # mergeable log-bucket histograms beside the rolling quantiles:
        # per-process quantiles are local color — they CANNOT be merged
        # across replicas — while integer bucket counts add associatively
        # and commutatively, so the `_hist` families are what the
        # router's /metrics/fleet pools into one fleet-wide truth. The
        # SLO burn-rate engine and the embedded time-series ring ride
        # the same switch (`slo_layer=False` is the A/B baseline,
        # bench.py --ab slo). Pure host-side bookkeeping: served numbers
        # are bit-exact either way and nothing is staged into jitted
        # code.
        from cgnn_tpu.observe.hist import (
            LATENCY_MS_BOUNDS,
            OCCUPANCY_BOUNDS,
            QUEUE_WAIT_MS_BOUNDS,
            Histogram,
        )
        from cgnn_tpu.observe.slo import SLOEngine, SLOObjective
        from cgnn_tpu.observe.tsdb import TimeSeriesStore, TsdbCollector

        self.hists: dict[str, Histogram] = {}
        self.slo = None
        self.tsdb = None
        self._tsdb_collector = None
        if slo_layer:
            self.hists = {
                "serve_latency_ms_hist": Histogram(LATENCY_MS_BOUNDS),
                "serve_queue_wait_ms_hist": Histogram(QUEUE_WAIT_MS_BOUNDS),
                "serve_flush_occupancy_hist": Histogram(OCCUPANCY_BOUNDS),
            }
            objectives = (tuple(slo_objectives) if slo_objectives else (
                SLOObjective("availability", target=0.999, window_s=300.0),
                SLOObjective("latency", target=0.95,
                             latency_threshold_ms=1000.0, window_s=300.0),
            ))
            # clock matches the server's (injectable for tests); the
            # fire hook reads self.flightrec at fire time, so attaching
            # a recorder later still routes alerts into bundles
            self.slo = SLOEngine(
                objectives, rules=slo_rules, clock=clock,
                on_fire=self._on_slo_fire, on_resolve=self._on_slo_resolve,
            )
            self.tsdb = TimeSeriesStore()
        # priority-class continuous batching (ISSUE 19): per-class wait
        # budgets, padding-slack backfill, and WFQ tenant weights all
        # live in the batcher — the server's share is the per-class
        # metric families below and the slack accounting in dispatch
        self.batcher = MicroBatcher(
            shape_set, max_queue=max_queue, max_wait_ms=max_wait_ms,
            clock=clock,
            queue_wait_hist=self.hists.get("serve_queue_wait_ms_hist"),
            class_max_wait_ms=class_max_wait_ms, backfill=backfill,
            wfq_weights=wfq_weights,
        )
        # per-class latency histograms (labeled members of one family,
        # serve_class_latency_ms_hist{class="..."}) — created lazily
        # like the per-version family so single-class traffic pays one
        # dict miss, not three idle histograms
        self._class_hists: dict[str, object] = {}
        # backfill accounting (the serve_padding_fill_share feed):
        # graph-slot slack offered to backfill vs slots actually filled,
        # accumulated per flush under self._lock
        self._backfill_filled = 0
        self._backfill_slack = 0
        self.default_timeout = (
            None if default_timeout_ms is None else default_timeout_ms / 1000.0
        )
        self.cache = ResultCache(cache_size) if cache_size else None
        # single-flight miss coalescing (ISSUE 20): per-fingerprint
        # waiter table. The FIRST miss for a key enters the batcher as
        # the leader; concurrent identical-fingerprint misses attach as
        # followers and are resolved from the leader's future (success,
        # error, or expiry — the wait is bounded by the leader's own
        # deadline plus the follower's client timeout), so a trending-
        # structure stampede costs one forward pass, not a batch of
        # duplicates. Off (`single_flight=False`) is the A/B baseline:
        # duplicates then enter the batcher and are COUNTED
        # (cache_dup_misses) instead of coalesced.
        self._single_flight = bool(single_flight)
        self._sf_lock = racecheck.make_lock("serve.singleflight")
        self._inflight: dict[str, dict] = {}
        # per-(tier, form, outcome) cache-lookup histograms: labeled
        # members of one family (serve_cache_lookup_ms_hist{...}),
        # created lazily like the per-class family — bucket COUNTS give
        # fleet-mergeable per-(tier, form) hit ratios, values the probe
        # (hash + LRU) cost
        self._cache_hists: dict[tuple, object] = {}
        self._clock = clock
        self._log = log_fn
        self._worker: threading.Thread | None = None
        self._watcher: CheckpointWatcher | None = None
        self._draining = False
        # plain Lock normally; instrumented under CGNN_TPU_RACECHECK=1
        # (lock-order recording + held-by-current for watch_fields)
        self._lock = racecheck.make_lock("serve.server")
        # serving counters (mirrored into telemetry; kept locally so
        # stats() works with telemetry off)
        self.counts: dict[str, int] = {
            "requests": 0, "responses": 0, "cache_hits": 0,
            "cache_coalesced": 0, "cache_dup_misses": 0,
            "cache_fills": 0, "cache_fill_stale": 0,
            "reject_queue_full": 0, "reject_oversize": 0,
            "reject_timeout": 0, "reject_shutdown": 0,
            "reject_malformed": 0, "batches": 0,
        }
        self._latencies: list[float] = []  # recent, bounded (stats())
        self._occupancies: list[float] = []
        # per-rung edge-slot occupancy, last value per rung index (the
        # cap-calibration signal; exported via /metrics and stats())
        self._rung_edge_occ: dict[int, float] = {}
        self.warmed = False
        self._compiles_after_warm = 0
        # expected per-structure feature layout, learned from the warm
        # template: the admission gate that keeps a malformed request
        # from poisoning a whole co-batched flush (pack would raise) or
        # forcing a fresh trace (a recompile after warmup)
        self._feature_dims: tuple[int, int] | None = None
        # ---- live observability plane ----
        # trace ids are ALWAYS minted (cheap: prefix + counter); span
        # emission additionally needs telemetry.spans (plane on) OR the
        # always-on serving span ring below
        self._trace_prefix = os.urandom(3).hex()
        self._trace_seq = itertools.count(1)
        # the cross-process trace ring (ISSUE 15): a bounded SpanTracer
        # that serving spans land in REGARDLESS of telemetry level, so
        # `GET /trace` and the flight recorder can join this process
        # into a fleet trace mid-incident. Host-side ring appends only
        # (predictions bit-exact either way); 0 disables (the A/B
        # baseline, PERF.md §18)
        from cgnn_tpu.observe.spans import SpanTracer

        self.tracer = (SpanTracer(
            process_name=f"serve-{os.getpid()}",
            max_events=int(trace_ring)) if trace_ring else None)
        self._spans_on = (self.telemetry.spans is not None
                          or self.tracer is not None)
        # incident flight recorder (observe/flightrec.py), attached by
        # the entrypoint — None keeps every hook below a no-op
        self.flightrec = None
        # label journal (continual/journal.py, ISSUE 18), attached by
        # the entrypoint — None keeps the serving path journal-free
        self.journal = None
        # per-version latency histograms (ISSUE 18): bounded map of the
        # most recent param versions, rendered as
        # serve_version_latency_ms_hist{param_version="..."} so
        # /metrics/fleet can merge shadow-vs-live latency per version.
        # Rides the slo_layer switch like the other histogram families.
        self._version_hists: "OrderedDict[str, object]" = OrderedDict()
        self._version_hists_cap = 8
        from cgnn_tpu.observe.export import MetricsRegistry, RollingSeries

        # rolling (time-windowed) twins of the run-lifetime SLO series:
        # these answer "what is the p99 NOW", independent of telemetry
        # level, and feed /stats["rolling"] + the /metrics scrape
        self.rolling_window_s = 60.0
        self._lat_rolling = RollingSeries(window_s=self.rolling_window_s)
        self._occ_rolling = RollingSeries(window_s=self.rolling_window_s)
        self.registry = MetricsRegistry(window_s=self.rolling_window_s)
        self.registry.attach_telemetry(self.telemetry)
        self.registry.add_provider("serve", self._registry_snapshot)
        if self.tsdb is not None:
            # one heartbeat for the whole quantitative plane: registry
            # snapshots -> tsdb rings, and the SLO state machines advance
            # on the same tick (alerts resolve even with zero traffic)
            self._tsdb_collector = TsdbCollector(
                self.registry, self.tsdb, interval_s=tsdb_interval_s,
            )
            self._tsdb_collector.add_on_tick(self._slo_tick)
        # on-demand device profiling (observe/profile.py); wired by
        # enable_profiling — None until an output dir is chosen
        self.profiler = None
        # racecheck shared-field tripwire (no-op when the gate is off):
        # every field mutated under self._lock is registered, so a
        # future stats path touching one without the lock is a recorded
        # violation at runtime, not a 3am scrape mystery (the PR-6 bug)
        racecheck.watch_fields(self, self._lock, (
            "counts", "_latencies", "_occupancies", "_draining",
            "_compiles_after_warm", "_rung_edge_occ",
            "_backfill_filled", "_backfill_slack",
        ))
        racecheck.watch_fields(self, self._sf_lock, ("_inflight",))

    # ---- warmup ----

    def warm(self, template: CrystalGraph) -> int:
        """Compile every shape in the set ON EVERY DEVICE; returns the
        program count (traced forms, independent of the device count).

        ``template`` is any admissible structure (it provides feature
        dimensionality); each rung is packed with one copy and executed
        once per device. A compact set warms BOTH staging forms per rung
        — the compact fast path and the full-fidelity fallback a flush
        holding a non-compactable request takes — and every PRECISION
        TIER warms per (rung, form): the post-warmup compile count is
        pinned no matter how traffic mixes, which tier a request picks,
        OR which device a flush lands on: ``len(shape_set) * forms *
        len(precisions)`` traced programs, each built into one
        executable per device here and NEVER again (devices.py module
        docstring). Dispatches run under ``telemetry.warmup()`` so
        compile executions never pollute serving counters."""
        import jax

        self._feature_dims = (template.atom_fea.shape[1],
                              template.edge_fea.shape[1])
        raw_tpl = (self.shape_set.raw.template()
                   if self.shape_set.raw is not None else None)
        n0 = self._jit_cache_size()
        programs = 0
        with self.telemetry.warmup():
            for shape in self.shape_set:
                # pack once per form on the host; each device's replica
                # pulls the same staged batch through its own executable
                forms = [self.shape_set.pack([template], shape=shape)]
                if self.shape_set.compact is not None:
                    forms.append(
                        self.shape_set.pack_full([template], shape=shape))
                if raw_tpl is not None:
                    # the raw-wire program (ISSUE 11): in-program
                    # neighbor search + featurize, one per rung
                    forms.append(
                        self.shape_set.pack_raw([raw_tpl], shape=shape))
                if self.mesh_exec is not None:
                    # mesh engine: the warmed program IS the stacked
                    # sharded one — one dispatch covers every device, so
                    # the compile count is programs, never programs x N
                    n = len(self.mesh_exec)
                    staged_forms = [
                        self.mesh_exec.stage(self.mesh_exec.stack([b] * n))
                        for b in forms
                    ]
                    for tier in self.precisions:
                        state, _ = self.param_store.get(0, tier)
                        for staged in staged_forms:
                            jax.block_until_ready(
                                self.mesh_predict(state, staged))
                        programs += len(staged_forms)
                    continue
                for tier in self.precisions:
                    for i in range(len(self.device_set)):
                        state, _ = self.param_store.get(i, tier)
                        for b in forms:
                            # block_until_ready over the output pytree:
                            # the raw program returns a (preds,
                            # overflow, n_edges) tuple
                            jax.block_until_ready(
                                self.predict_step(state, b))
                    programs += len(forms)
        self.warmed = True
        compiled = (self._jit_cache_size() or 0) - (n0 or 0)
        self._log(
            f"serve: warmed {len(self.shape_set)} shapes / {programs} "
            f"programs on {len(self.device_set)} device(s) "
            f"[{self.engine} engine] / "
            f"{len(self.precisions)} precision tier(s) "
            f"({compiled} fresh compiles"
            f"{', compact-staged' if self.shape_set.compact else ''}"
            f"{', raw-wire' if self.shape_set.raw is not None else ''})"
        )
        return compiled

    def _jit_cache_size(self) -> int | None:
        """The jit cache-miss counter (None when the fn isn't a jax.jit).

        Under the mesh engine the dispatched program is
        ``mesh_predict`` — its cache is the one whose growth after
        warmup would be a recompile."""
        fn = self.mesh_predict if self.mesh_exec is not None \
            else self.predict_step
        try:
            return int(fn._cache_size())
        except AttributeError:
            return None

    # ---- live observability plane ----

    def _mint_trace(self, requested: str | None = None) -> str:
        """A request's trace id: the (sanitized) inbound X-Request-Id
        when the client sent one, a fresh ``req-<prefix>-<seq>`` here
        otherwise. Always minted — the id is how an operator joins an
        HTTP response to its span chain and flush."""
        if requested:
            rid = "".join(c if c.isprintable() and c not in '\\"'
                          else "_" for c in str(requested).strip())
            if rid:
                return rid[:128]
        return f"req-{self._trace_prefix}-{next(self._trace_seq):06x}"

    @staticmethod
    def _stamp() -> float:
        """The per-stage stamp clock (SpanTracer.now_s: perf_counter
        seconds) — deliberately NOT the injectable request clock, so
        stamps line up with the Chrome-trace span timeline even under a
        fake test clock."""
        return time.perf_counter()

    def _span(self, name: str, start_s: float, end_s: float,
              **args) -> None:
        """Emit one retro-stamped hop span to every live sink: the
        telemetry tracer (trace.json at close) and/or the always-on
        serving ring (`GET /trace` + flight-recorder bundles)."""
        spans = self.telemetry.spans
        if spans is not None:
            spans.complete(name, start_s, end_s, **args)
        if self.tracer is not None:
            self.tracer.complete(name, start_s, end_s, **args)

    def _note_request(self, **record) -> None:
        """Feed the flight recorder's recent-request ring (no-op until
        one is attached; one lock + deque append when it is)."""
        fr = self.flightrec
        if fr is not None:
            fr.note_request(record)

    def note_http_status(self, status: int) -> None:
        """HTTP front-end hook: response statuses feed the recorder's
        5xx burst trigger."""
        fr = self.flightrec
        if fr is not None:
            fr.note_status(int(status))

    def attach_flight_recorder(self, recorder) -> None:
        """Wire an observe.flightrec.FlightRecorder into the serving
        path: every finished request lands in its ring, HTTP statuses
        feed its burst trigger (serve/http.py calls note_http_status)."""
        self.flightrec = recorder

    # ---- metrics-truth feeds (ISSUE 16) ----

    def _observe_served(self, latency_ms: float,
                        version: str | None = None,
                        klass: str | None = None) -> None:
        """One answered request into the mergeable latency histogram +
        the SLO good/bad ledger. Cache hits count: a client got an
        answer either way, and the fleet-merged histogram must describe
        the same population clients measure. ``version`` additionally
        lands the sample in that param version's labeled family (ISSUE
        18) so per-version latency survives the fleet merge; ``klass``
        lands it in the priority class's labeled family (ISSUE 19) and
        routes it to class-scoped SLO objectives."""
        h = self.hists.get("serve_latency_ms_hist")
        if h is not None:
            h.observe(latency_ms)
            if version is not None:
                with self._lock:
                    vh = self._version_hists.get(version)
                    if vh is None:
                        from cgnn_tpu.observe.hist import (
                            LATENCY_MS_BOUNDS,
                            Histogram,
                        )

                        vh = self._version_hists[version] = Histogram(
                            LATENCY_MS_BOUNDS)
                        while len(self._version_hists) > \
                                self._version_hists_cap:
                            self._version_hists.popitem(last=False)
                vh.observe(latency_ms)
            if klass is not None:
                ch = self._class_hists.get(klass)
                if ch is None:
                    from cgnn_tpu.observe.hist import (
                        LATENCY_MS_BOUNDS,
                        Histogram,
                    )

                    with self._lock:
                        ch = self._class_hists.setdefault(
                            klass, Histogram(LATENCY_MS_BOUNDS))
                ch.observe(latency_ms)
        if self.slo is not None:
            self.slo.record(True, latency_ms, klass=klass)

    def _observe_cache_lookup(self, tier: str, form: str, outcome: str,
                              lookup_ms: float) -> None:
        """One cache probe into its (tier, form, outcome)-labeled
        histogram (ISSUE 20). The bucket COUNTS are the point: they
        merge across replicas like any histogram family, so
        /metrics/fleet derives fleet-wide per-(tier, form) hit ratios
        from hit-count / (hit-count + miss-count); the observed values
        are the probe (hash + LRU) cost in ms."""
        if not self.hists:
            return
        key = (str(tier), str(form), str(outcome))
        h = self._cache_hists.get(key)
        if h is None:
            from cgnn_tpu.observe.hist import LATENCY_MS_BOUNDS, Histogram

            with self._lock:
                h = self._cache_hists.setdefault(
                    key, Histogram(LATENCY_MS_BOUNDS))
        h.observe(lookup_ms)

    def _singleflight_done(self, fp: str, fut) -> None:
        """Leader completion: drain the waiter-table entry for ``fp``
        and answer every coalesced follower from the leader's outcome
        (runs on whichever thread resolved the leader's future)."""
        with self._sf_lock:
            entry = self._inflight.pop(fp, None)
        if not entry:
            return
        followers = entry["followers"]
        if not followers:
            return
        try:
            res = fut.result(0)
            err = None
        except BaseException as e:  # noqa: BLE001 — relayed verbatim
            res, err = None, e
        for w in followers:
            self._resolve_coalesced(w, res, err)

    def _resolve_coalesced(self, w: dict, res, err) -> None:
        """Answer one coalesced follower: the leader's row under the
        follower's own trace id / latency / class accounting (a
        coalesced reply is a served response — it must feed the same
        latency distributions clients measure)."""
        fut = w["future"]
        if err is not None:
            self._count("cache_coalesced_errors")
            fut.set_error(err)
            return
        replied = self._stamp()
        latency_ms = (self._clock() - w["t0"]) * 1e3
        fut.set_result(ServeResult(
            prediction=res.prediction, param_version=res.param_version,
            latency_ms=latency_ms, cached=res.cached,
            device_id=res.device_id, trace_id=w["trace_id"],
            precision=w["tier"],
            stamps={"queued": w["queued"], "replied": replied},
            wire=res.wire, klass=w["klass"], coalesced=True,
        ))
        self._record_latency(latency_ms)
        self._lat_rolling.add(latency_ms)
        self._observe_served(latency_ms, version=res.param_version,
                             klass=w["klass"])
        self._count("responses")
        self._count(f"responses_class_{w['klass']}")
        self.telemetry.observe_value("serve_latency_ms", latency_ms)
        if self._spans_on:
            args = {"trace_id": w["trace_id"], "coalesced": True}
            if w["trace_parent"]:
                args["parent"] = w["trace_parent"]
            self._span("serve.request", w["queued"], replied, **args)
        self._note_request(
            trace_id=w["trace_id"], status="ok", cached=bool(res.cached),
            param_version=res.param_version, precision=w["tier"],
            wire=res.wire, latency_ms=latency_ms)
        self._journal_served(
            graph=w["graph"], fingerprint=w["fingerprint"],
            trace_id=w["trace_id"], prediction=res.prediction,
            version=res.param_version, wire=res.wire)

    def cache_fill(self, fingerprint: str, prediction, param_version: str,
                   precision: str | None = None,
                   wire: str = "featurized") -> bool:
        """Peer-fill receiver (ISSUE 20): the fleet router replays a row
        a NON-owner replica just computed into this (owner) replica's
        cache, so the next hot-key request hits here. Purely an
        optimization — the row is version-checked against the LIVE
        param version at fill time AND revalidated at hit time
        (serve/cache.py), so a stale fill can never be served. The
        fingerprint arrives in edge form ('raw:'-prefixed or bare) and
        is qualified here with the same fs:/tier rules as submit().
        Returns True when the row was cached."""
        if self.cache is None or not fingerprint:
            return False
        fp = str(fingerprint)
        if fp.startswith("raw:") and wire != "raw":
            fp = "fs:" + fp[len("raw:"):]
        tier = precision or "f32"
        if tier != "f32":
            fp = f"{tier}:{fp}"
        version = str(param_version)
        if version != self.param_store.version:
            self._count("cache_fill_stale")
            return False
        row = np.asarray(prediction, np.float32)
        self.cache.put(fp, (row, version))
        self._count("cache_fills")
        return True

    def attach_journal(self, journal) -> None:
        """Wire a continual/journal.LabelJournal into the answer path:
        every served response appends a replayable record the late
        ``POST /label`` joins ground truth onto (ISSUE 18)."""
        self.journal = journal

    def _journal_served(self, *, graph, fingerprint, trace_id, prediction,
                        version, wire) -> None:
        """One answered request into the label journal (no-op until one
        is attached). The payload is the request re-encoded in its wire
        form, so the continual trainer replays EXACTLY what was served
        through the same graph_from_json path the HTTP handler uses."""
        j = self.journal
        if j is None:
            return
        try:
            pred = float(np.asarray(prediction).reshape(-1)[0])
        except (TypeError, ValueError):
            pred = None
        payload = None
        if wire == "featurized" and isinstance(graph, CrystalGraph):
            payload = {"graph": {
                "atom_fea": np.asarray(graph.atom_fea).tolist(),
                "edge_fea": np.asarray(graph.edge_fea).tolist(),
                "centers": np.asarray(graph.centers).tolist(),
                "neighbors": np.asarray(graph.neighbors).tolist(),
                "id": graph.cif_id,
            }}
        elif isinstance(graph, RawStructure):
            payload = {"structure": {
                "frac_coords": np.asarray(graph.frac_coords).tolist(),
                "lattice": np.asarray(graph.lattice).tolist(),
                "numbers": np.asarray(graph.numbers).tolist(),
                "id": graph.cif_id,
            }}
        j.note_served(trace_id=trace_id, payload=payload, prediction=pred,
                      param_version=version, fingerprint=fingerprint,
                      ts=time.time())

    def _record_slo_bad(self, klass: str | None = None) -> None:
        """One failed request (dispatch failure / deadline expiry) into
        the error-budget ledger. Admission rejections (queue-full,
        oversize, malformed) are NOT budget burn — they are the server
        protecting itself or the client's fault (the 429/400 class)."""
        if self.slo is not None:
            self.slo.record(False, 0.0, klass=klass)

    def _slo_tick(self) -> None:
        """Collector heartbeat: advance the alert state machines so
        pending->firing (for_s held) and firing->resolved happen on the
        clock, not only when traffic arrives."""
        if self.slo is not None:
            self.slo.evaluate()

    def _on_slo_fire(self, tr: dict) -> None:
        """Burn-rate alert FIRING -> incident capture: the reason names
        the objective (``slo_burn_<objective>``) so the flight-recorder
        bundle manifest identifies the alert — the fleet_smoke pin."""
        self._log(
            f"serve: SLO ALERT firing: objective={tr['objective']} "
            f"rule={tr['rule']} burn_fast={tr['burn_fast']:.2f} "
            f"burn_slow={tr['burn_slow']:.2f} (factor {tr['factor']:g})"
        )
        fr = self.flightrec
        if fr is not None:
            fr.trigger(
                f"slo_burn_{tr['objective']}",
                detail=(f"rule={tr['rule']} "
                        f"burn_fast={tr['burn_fast']:.3f} "
                        f"burn_slow={tr['burn_slow']:.3f} "
                        f"factor={tr['factor']:g}"),
            )

    def _on_slo_resolve(self, tr: dict) -> None:
        self._log(
            f"serve: SLO alert resolved: objective={tr['objective']} "
            f"rule={tr['rule']}"
        )

    def trace_window(self, since_s: float | None = None) -> dict | None:
        """The `GET /trace` body: this process's span ring as a
        joinable window (observe/trace_join.py), or None when neither
        the serving ring nor telemetry spans exist."""
        tracer = self.tracer or self.telemetry.spans
        if tracer is None:
            return None
        w = tracer.window(since_s=since_s)
        w["role"] = "replica"
        return w

    def enable_profiling(self, out_dir: str, *,
                         default_duration_s: float = 1.0,
                         max_duration_s: float = 10.0):
        """Wire on-demand device profiling (POST /profile + SIGUSR2)
        into ``out_dir``; returns the ProfileCapture (gated: concurrent
        captures are rejected, never stacked)."""
        from cgnn_tpu.observe.profile import ProfileCapture

        self.profiler = ProfileCapture(
            out_dir, spans=self.telemetry.spans,
            default_duration_s=default_duration_s,
            max_duration_s=max_duration_s, log_fn=self._log,
        )
        return self.profiler

    def _registry_snapshot(self) -> dict:
        """The serve provider for ``self.registry``: request counters,
        live queue/in-flight gauges, and the rolling-window SLO series —
        all readable with telemetry OFF (the registry's telemetry source
        contributes the rest when the plane is on). The pipeline_* and
        device* names are emitted from here too so every scrape carries
        the three metric families CI checks, whatever the config."""
        with self._lock:
            # copy under the lock: _count() inserts NEW keys concurrently
            # and a mid-iteration resize would raise, costing the scrape
            # the whole serve provider; _draining/_compiles_after_warm
            # are mutated under this lock too (graftcheck GC-LOCKSHARE)
            counts = dict(self.counts)
            draining = self._draining
            compiles_after_warm = self._compiles_after_warm
            rung_occ = dict(self._rung_edge_occ)
            backfill_filled = self._backfill_filled
            backfill_slack = self._backfill_slack
        counters = {f"serve_{k}": float(v) for k, v in counts.items()}
        tcounters = self.telemetry.counters()
        for name in ("pipeline_jobs", "pipeline_pack_s", "pipeline_wait_s"):
            counters[name] = float(tcounters.get(name, 0.0))
        # the ISSUE-11 overflow counter under its own (unprefixed) name:
        # /metrics renders it as ingest_cap_overflow_total, the name the
        # loadgen's zero-overflow assertion scrapes
        counters["ingest_cap_overflow"] = float(
            counts.get("ingest_cap_overflow", 0))
        gauges = {
            "serve_queue_depth": float(self.batcher.depth),
            "serve_draining": float(draining),
            "serve_warmed": float(self.warmed),
            "serve_recompiles_after_warm": float(compiles_after_warm),
            "serve_rolling_window_s": self.rolling_window_s,
            "pipeline_pack_workers": float(self._pack_workers),
            "device_count": float(len(self.device_set)),
            "serve_engine_mesh": float(self.mesh_exec is not None),
            "ingest_raw_wire": float(self.shape_set.raw is not None),
        }
        for rung, occ in sorted(rung_occ.items()):
            gauges[f"ingest_rung{rung}_edge_occupancy"] = float(occ)
        # padding-slack backfill (ISSUE 19): what share of the graph
        # slots higher-class flushes would have PADDED was instead
        # filled with lower-class goodput. 0 with backfill off or under
        # pure single-class load — the bench A/B's headline gauge.
        gauges["serve_backfill_enabled"] = float(self.batcher.backfill)
        gauges["serve_padding_fill_share"] = (
            backfill_filled / backfill_slack if backfill_slack else 0.0)
        counters["serve_backfill_filled_slots"] = float(backfill_filled)
        counters["serve_backfill_slack_slots"] = float(backfill_slack)
        # result-cache truth (ISSUE 20): ONE consistent snapshot under
        # the cache's own lock — scraping the bare hits/misses
        # attributes could pair a pre-increment hits with a
        # post-increment misses (a hit ratio that never existed)
        if self.cache is not None:
            hits, misses, size, capacity = self.cache.snapshot()
            counters["serve_cache_lookup_hits"] = float(hits)
            counters["serve_cache_lookup_misses"] = float(misses)
            gauges["serve_cache_size"] = float(size)
            gauges["serve_cache_capacity"] = float(capacity)
        gauges["serve_single_flight"] = float(self._single_flight)
        from cgnn_tpu.observe.gauges import cache_gauges

        gauges.update(cache_gauges(counters, gauges))
        # the cross-process observability layer's own health (ISSUE 15)
        gauges["observe_trace_ring"] = float(self.tracer is not None)
        if self.tracer is not None:
            gauges["observe_trace_dropped"] = float(self.tracer.dropped)
        fr = self.flightrec
        if fr is not None:
            frs = fr.stats()
            gauges["flightrec_bundles"] = float(frs["bundles"])
            gauges["flightrec_suppressed"] = float(frs["suppressed"])
        for i, depth in enumerate(self.device_set.inflight_depths()):
            gauges[f"device{i}_inflight"] = float(depth)
        if self.profiler is not None:
            gauges["profile_captures"] = float(self.profiler.captures)
            gauges["profile_busy"] = float(self.profiler.busy)
        series = {}
        for name, roll in (("serve_latency_ms", self._lat_rolling),
                           ("serve_batch_occupancy", self._occ_rolling)):
            q = roll.quantiles()
            if q:
                series[name] = q
        out = {"counters": counters, "gauges": gauges, "series": series}
        # the metrics-truth layer (ISSUE 16): mergeable histogram
        # snapshots under distinct `_hist` names — the summary families
        # above keep their names (one TYPE per family), the histogram
        # families are what /metrics/fleet pools across replicas
        if self.hists:
            out["histograms"] = {
                name: h.snapshot() for name, h in self.hists.items()
            }
            with self._lock:
                vhists = list(self._version_hists.items())
            if vhists:
                # per-param-version latency (ISSUE 18): labeled members
                # of one family, keyed name{param_version="..."} — the
                # canary gate's scrapeable shadow-vs-live comparison
                from cgnn_tpu.observe.hist import format_labels

                for ver, vh in vhists:
                    key = ("serve_version_latency_ms_hist"
                           + format_labels({"param_version": str(ver)}))
                    out["histograms"][key] = vh.snapshot()
            with self._lock:
                chists = list(self._class_hists.items())
            if chists:
                # per-priority-class latency (ISSUE 19): labeled members
                # of one family, keyed name{class="..."} — what lets the
                # autoscaler and fleet SLO views see classes instead of
                # one aggregate, and they merge across replicas like any
                # histogram family
                from cgnn_tpu.observe.hist import format_labels

                for kl, chh in sorted(chists):
                    key = ("serve_class_latency_ms_hist"
                           + format_labels({"class": str(kl)}))
                    out["histograms"][key] = chh.snapshot()
            with self._lock:
                cache_hists = list(self._cache_hists.items())
            if cache_hists:
                # per-(tier, form) cache hit ratio (ISSUE 20): labeled
                # members of one family keyed
                # name{tier=...,form=...,outcome=...} — the bucket
                # counts merge across replicas, so /metrics/fleet can
                # state the FLEET-wide hit ratio per tier and wire form
                from cgnn_tpu.observe.hist import format_labels

                for (tier, frm, outcome), hh in sorted(cache_hists):
                    key = ("serve_cache_lookup_ms_hist" + format_labels(
                        {"tier": tier, "form": frm, "outcome": outcome}))
                    out["histograms"][key] = hh.snapshot()
        if self.slo is not None:
            gauges.update(self.slo.gauges())
        if self.tsdb is not None:
            ts = self.tsdb.stats()
            gauges["tsdb_series"] = float(ts["series"])
            gauges["tsdb_points"] = float(ts["points"])
            gauges["tsdb_dropped_series"] = float(ts["dropped_series"])
        return out

    # ---- lifecycle ----

    def start(self) -> "InferenceServer":
        # the deadlock watchdog (racecheck-gated): any heartbeating
        # serve/pack/watcher thread silent past the bound triggers a
        # named faulthandler dump of every stack
        racecheck.start_watchdog(bound_s=30.0, log_fn=self._log)
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._serve_loop, daemon=True, name="cgnn-serve"
            )
            self._worker.start()
        if self._watcher is not None:
            self._watcher.start()
        if self._tsdb_collector is not None:
            self._tsdb_collector.start()
        return self

    def attach_watcher(self, manager, poll_interval_s: float = 2.0,
                       log_fn: Callable | None = None) -> CheckpointWatcher:
        """Wire hot checkpoint reload (reload.py) to ``manager``'s dir.

        The cache clears on every swap — cached rows are only valid for
        the version that computed them."""
        template, _ = self.param_store.get()
        self._watcher = CheckpointWatcher(
            manager, self.param_store, template,
            poll_interval_s=poll_interval_s, telemetry=self.telemetry,
            on_swap=lambda _v: self.cache.clear() if self.cache else None,
            log_fn=log_fn or self._log,
        )
        if self._worker is not None and self._worker.is_alive():
            self._watcher.start()
        return self._watcher

    @property
    def watcher(self) -> CheckpointWatcher | None:
        """The attached reload watcher (None before attach_watcher) —
        the POST /reload-control pin/gate endpoint drives it."""
        return self._watcher

    def install_signal_handlers(self):
        """SIGTERM/SIGINT -> graceful drain (resilience.preempt plumbing).

        Returns the PreemptionHandler; the caller's main thread decides
        what to do after the drain (serve.py shuts the HTTP listener and
        exits 0)."""
        from cgnn_tpu.resilience.preempt import PreemptionHandler

        handler = PreemptionHandler(
            log_fn=self._log,
            action="draining the serving queue (in-flight requests will "
                   "be answered; new ones rejected 503)",
        )
        handler.add_callback(self.begin_drain)
        return handler.install()

    def begin_drain(self) -> None:
        """Stop admitting; already-queued requests still get answers.
        Quick and thread-safe (called from signal handlers)."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self.batcher.close()
        self._log("serve: draining (no new requests; flushing queue)")

    def drain(self, timeout_s: float = 30.0) -> bool:
        """begin_drain + wait for the worker to finish the queue.
        True when the drain completed within the timeout."""
        self.begin_drain()
        if self._watcher is not None:
            self._watcher.stop()
        if self._tsdb_collector is not None:
            self._tsdb_collector.stop()
        if self._worker is not None:
            self._worker.join(timeout=timeout_s)
            done = not self._worker.is_alive()
        else:
            # never started: flush synchronously so accepted work still
            # gets answers
            self._serve_loop()
            done = True
        if self.profiler is not None:
            # exiting while jax.profiler holds an active trace segfaults
            # in the backend; a drain waits out an in-flight capture
            # (bounded: captures are capped at max_duration_s)
            self.profiler.wait_idle()
        self.telemetry.set_gauge("serve_drained_clean", float(done))
        # per-device occupancy/dispatch gauges -> run_summary (the
        # observe.gauges.device_gauges rollup reads these names)
        self.device_set.flush_gauges(self.telemetry)
        return done

    # ---- request path ----

    def _check_wellformed(self, graph: CrystalGraph) -> None:
        """Admission-time structural validation: a malformed graph must
        fail ALONE (400) — packed, it would either blow up pack_graphs
        (failing every innocent co-batched request) or, flushed alone,
        trace a fresh program shape (a recompile after warmup)."""
        problems = []
        if self._feature_dims is not None:
            nd, ed = self._feature_dims
            if np.ndim(graph.atom_fea) != 2 or graph.atom_fea.shape[1] != nd:
                problems.append(
                    f"atom_fea must be [N, {nd}], got "
                    f"{np.shape(graph.atom_fea)}"
                )
            if np.ndim(graph.edge_fea) != 2 or graph.edge_fea.shape[1] != ed:
                problems.append(
                    f"edge_fea must be [E, {ed}], got "
                    f"{np.shape(graph.edge_fea)}"
                )
        n, e = graph.num_nodes, graph.num_edges
        if n < 1:
            problems.append("structure has no atoms")
        if len(graph.edge_fea) != e:
            problems.append(
                f"{e} edges but {len(graph.edge_fea)} edge feature rows"
            )
        for name in ("centers", "neighbors"):
            idx = np.asarray(getattr(graph, name))
            if len(idx) and (idx.min() < 0 or idx.max() >= n):
                problems.append(
                    f"{name} indices outside [0, {n}) "
                    f"(min {idx.min()}, max {idx.max()})"
                )
        if problems:
            raise ServeRejection(MALFORMED, "; ".join(problems))

    def _check_wellformed_raw(self, rs: RawStructure) -> None:
        """Admission-time validation of a wire-form structure: shape,
        species range, finite geometry, invertible lattice — everything
        the in-program search (or the fallback featurizer) would choke
        on must fail ALONE at the door (400)."""
        from cgnn_tpu.data.elements import MAX_Z

        problems = []
        if rs.num_nodes < 1:
            problems.append("structure has no atoms")
        z = rs.numbers
        if len(z) and (z.min() < 1 or z.max() > MAX_Z):
            problems.append(
                f"species outside the element table [1, {MAX_Z}] "
                f"(min {z.min()}, max {z.max()})"
            )
        if not (np.isfinite(rs.frac_coords).all()
                and np.isfinite(rs.lattice).all()):
            problems.append("non-finite coordinates or lattice")
        elif abs(float(np.linalg.det(rs.lattice))) < 1e-6:
            problems.append("degenerate lattice (volume ~ 0)")
        if problems:
            raise ServeRejection(MALFORMED, "; ".join(problems))

    def _admit_form(self, rs: RawStructure) -> str:
        """'raw' when the wire-form structure fits the raw rung caps
        (host f64 pre-check — or just the structural atom-slot cap with
        ``raw_precheck=False``, leaving the image decision to the
        in-program flag), else 'feat' (deferred pack-pool featurize)."""
        spec = self.shape_set.raw
        if spec is not None:
            if self._raw_precheck:
                if spec.admits(rs):
                    return "raw"
            elif 1 <= rs.num_nodes <= spec.snode_cap:
                return "raw"
        if self.featurizer is None:
            raise ServeRejection(
                MALFORMED,
                "wire-form structure cannot be served: "
                + (self.shape_set.raw.oversize_detail(rs)
                   if self.shape_set.raw is not None
                   else "raw wire is not enabled")
                + " and no fallback featurizer is configured",
            )
        return "feat"

    def submit(self, graph,
               timeout_ms: float | None = None,
               trace_id: str | None = None,
               precision: str | None = None,
               trace_parent: str | None = None,
               klass: str | None = None,
               tenant: str | None = None,
               fingerprint: str | None = None) -> RequestFuture:
        """Admit one structure; returns its future (raises ServeRejection
        on malformed / queue-full / oversize / draining). ``graph`` is a
        featurized ``CrystalGraph`` OR a wire-form ``RawStructure``
        (ISSUE 11): wire-form structures that fit the raw rung caps are
        staged raw (the in-program neighbor search builds the graph);
        the rest are featurized ON THE PACK POOL at pack time — never
        on this thread, so one large structure cannot head-of-line-block
        admission. ``trace_id`` carries an inbound X-Request-Id; absent,
        one is minted here — admission is where a request's journey
        starts. ``trace_parent`` carries an inbound X-Trace-Parent span
        id (observe/tracectx.py): the upstream attempt span this
        request's serve.request span nests under in a joined fleet
        trace. ``precision`` picks the serving tier (None = 'f32'); a
        tier the server did not warm is rejected AT ADMISSION —
        flushing it would trace a fresh program (a recompile after
        warmup). ``klass`` picks the priority class (ISSUE 19;
        batcher.CLASSES, default 'interactive') and ``tenant`` the WFQ
        fair-queuing tenant — an unknown class is MALFORMED at
        admission, because silently defaulting it would change the
        request's scheduling contract. ``fingerprint`` carries an
        inbound edge-computed content hash (X-Fingerprint, ISSUE 20):
        the fleet router hashes the wire arrays ONCE per request, this
        replica only qualifies the key (fs:/tier prefixes) instead of
        re-hashing — a hint whose shape mismatches the admitted form is
        ignored and the key re-derived locally."""
        now = self._clock()
        queued = self._stamp()
        tid = self._mint_trace(trace_id)
        tier = precision or "f32"
        kl = klass or DEFAULT_CLASS
        is_raw_wire = isinstance(graph, RawStructure)
        form = "feat"
        self._count("requests")
        try:
            if tier not in self.precisions:
                raise ServeRejection(
                    MALFORMED,
                    f"precision {tier!r} not in this server's warmed "
                    f"tiers {list(self.precisions)}",
                )
            if kl not in CLASSES:
                raise ServeRejection(
                    MALFORMED,
                    f"unknown priority class {kl!r} "
                    f"(have: {list(CLASSES)})",
                )
            if is_raw_wire:
                self._check_wellformed_raw(graph)
                form = self._admit_form(graph)
                if form == "feat" and self.shape_set.dense_m is None:
                    # COO layout: a flush's edge budget needs the TRUE
                    # edge count, which only featurization knows — the
                    # legacy inline path (the dense layout, where slot
                    # ownership is structural, defers to the pack pool)
                    try:
                        graph = self.featurizer(graph)
                    except Exception as e:  # noqa: BLE001 — reject alone
                        raise ServeRejection(
                            MALFORMED,
                            f"structure featurization failed: {e}",
                        ) from None
                    is_raw_wire = False
                    self._check_wellformed(graph)
            else:
                self._check_wellformed(graph)
        except ServeRejection as e:
            self._count(f"reject_{e.reason}")
            raise
        lookup_t0 = self._clock()
        if self.cache is None:
            fp = None
        else:
            fp = None
            if fingerprint:
                # edge-computed hash (ISSUE 20): trusted only when its
                # shape matches the admitted form — raw-wire requests
                # carry a 'raw:'-prefixed hash, featurized ones a bare
                # hex digest. A mismatched hint (e.g. a raw hash after
                # the COO inline featurize above) falls back to local
                # hashing rather than alias the two keyspaces.
                cand = str(fingerprint)
                if is_raw_wire and cand.startswith("raw:"):
                    fp = cand
                elif not is_raw_wire and ":" not in cand:
                    fp = cand
            if fp is None:
                if is_raw_wire:
                    # content hash of the wire encoding (see below for
                    # the form qualification)
                    fp = raw_fingerprint(graph)
                else:
                    fp = structure_fingerprint(graph)
            if is_raw_wire and form != "raw":
                # form-qualified so a row computed by the raw program
                # ('raw:...') never answers a host-featurized request
                # ('fs:...') — the two programs agree only to f32
                # roundoff, and a cached row is (params, structure,
                # PROGRAM)-determined (serve/cache.py)
                fp = "fs:" + fp[len("raw:"):]
        if fp is not None and tier != "f32":
            # cached rows are (params, structure, TIER)-determined:
            # tier-qualify the key so an f32 answer can never serve an
            # int8 request (or vice versa). f32 keeps the bare legacy key.
            fp = f"{tier}:{fp}"
        if fp is not None:
            hit = self.cache.get(fp)
            lookup_ms = (self._clock() - lookup_t0) * 1e3
            if hit is not None:
                row, version = hit
                # entries are version-tagged and only served while their
                # version is still live: the swap's cache.clear() is bulk
                # eviction, but a batch IN FLIGHT across the swap writes
                # its old-version rows AFTER the clear — this check is
                # what actually guarantees no stale science is served
                if version == self.param_store.version:
                    self._count("cache_hits")
                    self._observe_cache_lookup(tier, form, "hit",
                                               lookup_ms)
                    fut = RequestFuture()
                    replied = self._stamp()
                    latency_ms = (self._clock() - now) * 1e3
                    fut.set_result(ServeResult(
                        prediction=row, param_version=version,
                        latency_ms=latency_ms, cached=True,
                        device_id=-1, trace_id=tid, precision=tier,
                        stamps={"queued": queued, "replied": replied},
                        wire="raw" if form == "raw" else "featurized",
                        klass=kl,
                    ))
                    # cache hits ARE served responses: they must feed the
                    # same latency distributions clients measure, or the
                    # scraped rolling p99 and a loadgen's own p99 describe
                    # different populations under a warm cache
                    self._record_latency(latency_ms)
                    self._lat_rolling.add(latency_ms)
                    self._observe_served(latency_ms, version=version,
                                         klass=kl)
                    self._count(f"responses_class_{kl}")
                    self.telemetry.observe_value("serve_latency_ms",
                                                 latency_ms)
                    if self._spans_on:
                        args = {"trace_id": tid, "cached": True}
                        if trace_parent:
                            args["parent"] = trace_parent
                        self._span("serve.request", queued, replied,
                                   **args)
                    self._note_request(
                        trace_id=tid, status="ok", cached=True,
                        param_version=version, precision=tier,
                        wire="raw" if form == "raw" else "featurized",
                        latency_ms=latency_ms)
                    self._journal_served(
                        graph=graph, fingerprint=fp, trace_id=tid,
                        prediction=row, version=version,
                        wire="raw" if form == "raw" else "featurized")
                    return fut
            # a stale-version hit is a miss for accounting: the row
            # cannot be served, a forward pass (or a coalesce onto one)
            # is what answers the request
            self._observe_cache_lookup(tier, form, "miss", lookup_ms)
        timeout = (timeout_ms / 1000.0 if timeout_ms is not None
                   else self.default_timeout)
        req = Request(
            graph=graph,
            enqueued=now,
            deadline=None if timeout is None else now + timeout,
            fingerprint=fp,
            # decided once here: a flush packs compact only when EVERY
            # member can (batcher.Request docstring). Deferred-featurize
            # structures resolve their probe at pack time, on the pool.
            compactable=(False if is_raw_wire
                         else self.shape_set.compactable(graph)),
            trace_id=tid,
            stamps={"queued": queued},
            precision=tier,
            form=form,
            trace_parent=str(trace_parent or ""),
            klass=kl,
            tenant=str(tenant or ""),
        )
        # single-flight miss coalescing (ISSUE 20): one leader per
        # in-flight fingerprint; concurrent identical misses attach to
        # its future instead of entering the batcher. With coalescing
        # OFF duplicates proceed (the A/B baseline) but are counted —
        # cache_dup_misses is the figure the bench hard-asserts to 0
        # when coalescing is on.
        follower = None
        dup_in_flight = False
        if fp is not None:
            with self._sf_lock:
                entry = self._inflight.get(fp)
                if entry is None:
                    self._inflight[fp] = {"req": req, "followers": []}
                elif self._single_flight:
                    follower = {
                        "future": RequestFuture(), "trace_id": tid,
                        "queued": queued, "t0": now, "klass": kl,
                        "tier": tier, "form": form,
                        "trace_parent": str(trace_parent or ""),
                        "graph": graph, "fingerprint": fp,
                    }
                    entry["followers"].append(follower)
                else:
                    dup_in_flight = True
            if follower is not None:
                self._count("cache_coalesced")
                return follower["future"]
            if dup_in_flight:
                self._count("cache_dup_misses")
            else:
                # the leader's completion — success, error, or expiry,
                # from whichever thread resolves it — drains the waiter
                # table entry and answers every follower
                req.future.add_done_callback(
                    lambda f, _fp=fp: self._singleflight_done(_fp, f))
        try:
            self.batcher.offer(req)
        except ServeRejection as e:
            if fp is not None and not dup_in_flight:
                # the leader never entered the batcher: drop the table
                # entry and relay the rejection to any follower that
                # attached in the window (they would otherwise wait on
                # a future nothing will ever resolve)
                with self._sf_lock:
                    cur = self._inflight.get(fp)
                    waiters = ()
                    if cur is not None and cur.get("req") is req:
                        waiters = self._inflight.pop(fp)["followers"]
                for w in waiters:
                    w["future"].set_error(e)
            self._count(f"reject_{e.reason}")
            raise
        return req.future

    def predict(self, graph: CrystalGraph,
                timeout_ms: float | None = None,
                trace_id: str | None = None,
                precision: str | None = None,
                trace_parent: str | None = None,
                klass: str | None = None,
                tenant: str | None = None,
                fingerprint: str | None = None) -> ServeResult:
        """Blocking convenience: submit + wait."""
        fut = self.submit(graph, timeout_ms=timeout_ms, trace_id=trace_id,
                          precision=precision, trace_parent=trace_parent,
                          klass=klass, tenant=tenant,
                          fingerprint=fingerprint)
        # wait slightly past the serving deadline: expiry is delivered by
        # the worker, not by this caller racing it
        timeout = (timeout_ms / 1000.0 if timeout_ms is not None
                   else self.default_timeout)
        return fut.result(None if timeout is None else timeout + 30.0)

    # ---- the worker ----

    def _serve_loop(self) -> None:
        if self.mesh_exec is not None:
            return self._serve_loop_mesh()
        if len(self.device_set) > 1:
            return self._serve_loop_multidev()
        if self._pack_workers > 0:
            return self._serve_loop_pipelined()
        while True:
            racecheck.heartbeat()
            flush = self.batcher.next_flush()
            if flush is None:
                return
            self._process(flush)

    def _flushes(self):
        """The live flush stream: expiries are delivered HERE, before
        the pack stage, so a timed-out client hears promptly instead of
        queueing behind the pipeline's in-flight flushes."""
        while True:
            flush = self.batcher.next_flush()
            if flush is None:
                return
            self._fail_expired(flush)
            if flush.requests:
                yield flush

    def _make_pack_one(self, pool):
        def pack_one(flush: Flush):
            t0 = time.perf_counter()
            try:
                batch, buf = self._pack_flush(flush, pool)
                err = None
            except Exception as e:  # noqa: BLE001 — fail the flush, not the stream
                batch = buf = None
                err = e
            t1 = time.perf_counter()
            # the 'packed' hop: stamped on the flush (shared by its
            # co-batched members) and emitted as a span keyed by
            # flush_id + the member trace ids
            flush.stamps["packed"] = t1
            if self._spans_on:  # skip arg-building when off
                self._span("serve.pack", t0, t1, flush_id=flush.flush_id,
                           n=len(flush.requests),
                           trace_ids=flush.trace_ids(),
                           error=repr(err) if err is not None else "")
            self.telemetry.observe_value("serve_pack_s", t1 - t0)
            return flush, batch, buf, err

        return pack_one

    def _packed_stream(self, pool):
        """(flush, batch, buf, err) stream: through the parallel pack
        pipeline when ``pack_workers > 0``, in-line otherwise."""
        from cgnn_tpu.data.pipeline import parallel_pack

        pack_one = self._make_pack_one(pool)
        if self._pack_workers > 0:
            return iter(parallel_pack(
                self._flushes(), pack_one, workers=self._pack_workers,
                telemetry=self.telemetry, raise_on_error=False,
                name="cgnn-serve-pack",
            ))
        return map(pack_one, self._flushes())

    def _serve_loop_pipelined(self) -> None:
        """The single-device pack-overlapped worker: batcher -> packer
        pool -> dispatch.

        ``parallel_pack`` (data/pipeline.py) runs the flush stream
        through ``_pack_workers`` packer threads with order-restoring
        reassembly, so while THIS thread dispatches flush N and blocks
        on its fetch, flush N+1 is already packing and the batcher is
        coalescing N+2 — packing leaves the dispatch critical path.
        Order preservation keeps response FIFO fairness. Pack errors are
        delivered per flush (the poisoned flush fails alone; admission
        validation makes them unlikely). Pooled staging buffers recycle
        after the flush's blocking fetch — the device is done with them.
        """
        from cgnn_tpu.data.pipeline import BufferPool

        pool = BufferPool()
        stream = self._packed_stream(pool)
        while True:
            racecheck.heartbeat()
            t0 = time.perf_counter()
            try:
                item = next(stream)
            except StopIteration:
                return
            except Exception as e:  # noqa: BLE001 — flush-stream error: keep serving
                self._log(f"serve: pack pipeline error: {e!r}")
                continue
            # dispatch-side stall waiting on the packers (the ingest
            # starvation signal; run_summary p50/p95/p99 via series)
            self.telemetry.observe_value("pipeline_wait_s",
                                         time.perf_counter() - t0)
            self._run_flush(*item, pool=pool)

    def _serve_loop_multidev(self) -> None:
        """The device-parallel worker: batcher -> packer pool -> router
        -> one dispatch thread PER device (ISSUE 5).

        The router assigns each packed flush to the least-loaded device
        (DeviceSet.pick: fewest in-flight, round-robin tie-break) and
        hands it to that device's dispatch thread over a bounded queue —
        the per-device in-flight window. Each device thread reads its
        (params, version) replica pair once per flush, dispatches, and
        BLOCKS on the fetch before touching the next flush, so per
        device execution is FIFO and a pooled staging buffer is released
        only after the fetch proves its dispatch completed — the ISSUE-4
        BufferPool contract, per device. Responses stay FIFO per device;
        cross-device completion order is whatever the hardware does (the
        price of using more than one chip).
        """
        import queue as queue_mod

        from cgnn_tpu.data.pipeline import BufferPool

        pool = BufferPool()
        n = len(self.device_set)
        qs = [queue_mod.Queue(maxsize=self.device_set.window)
              for _ in range(n)]

        def device_worker(i: int) -> None:
            while True:
                racecheck.heartbeat()
                try:
                    # bounded get: the idle tick is what lets the
                    # racecheck watchdog tell 'no traffic routed here'
                    # from 'wedged mid-dispatch'
                    item = qs[i].get(timeout=1.0)
                except queue_mod.Empty:
                    continue
                if item is None:
                    return
                self._run_flush(*item, pool=pool, device=i, routed=True)

        workers = [
            threading.Thread(target=device_worker, args=(i,), daemon=True,
                             name=f"serve-dispatch-{i}")
            for i in range(n)
        ]
        for t in workers:
            t.start()
        stream = self._packed_stream(pool)
        try:
            while True:
                racecheck.heartbeat()
                t0 = time.perf_counter()
                try:
                    item = next(stream)
                except StopIteration:
                    return
                except Exception as e:  # noqa: BLE001 — keep serving
                    self._log(f"serve: pack pipeline error: {e!r}")
                    continue
                self.telemetry.observe_value("pipeline_wait_s",
                                             time.perf_counter() - t0)
                i = self.device_set.pick()
                # in-flight accounting BEFORE the put so pick() sees the
                # routed-but-unstarted load of every device
                self.device_set.note_enqueue(i)
                qs[i].put(item)
        finally:
            for q in qs:
                q.put(None)
            for t in workers:
                t.join()

    def _serve_loop_mesh(self) -> None:
        """The mesh-engine worker (ISSUE 10): batcher -> packer pool ->
        ONE sharded dispatch per flush.

        Each packed flush is already split round-robin across the mesh
        (``_pack_flush``): per-shard sub-batches of one common rung,
        stacked on the device axis. The single dispatch thread stages
        the stack batch-axis-sharded (each device receives exactly its
        slice) and runs ONE jitted call that covers every device — the
        least-loaded router, the per-device queues, and the N dispatch
        threads of the threads engine do not exist here. FIFO response
        order is global (one dispatch stream), and the hot-swap boundary
        is unchanged: one (params, version) read per flush, now of the
        single sharded tree.
        """
        stream = self._packed_stream(None)  # mesh packs fresh stacks;
        #                                     the pooled-buffer recycle
        #                                     contract belongs to the
        #                                     per-device engines
        while True:
            racecheck.heartbeat()
            t0 = time.perf_counter()
            try:
                item = next(stream)
            except StopIteration:
                return
            except Exception as e:  # noqa: BLE001 — keep serving
                self._log(f"serve: pack pipeline error: {e!r}")
                continue
            self.telemetry.observe_value("pipeline_wait_s",
                                         time.perf_counter() - t0)
            self._run_flush_mesh(*item)

    def _run_flush_mesh(self, flush: Flush, packed, buf, err) -> None:
        """Mesh twin of ``_run_flush``: one dispatch serves every shard,
        so accounting touches every shard the split populated, and a
        failed flush still fails alone."""
        counts = packed[1] if packed is not None else []
        shards = [i for i, c in enumerate(counts) if c > 0]
        for i in shards:
            self.device_set.note_enqueue(i)
        t0 = time.perf_counter()
        ok = False
        try:
            if err is not None:
                raise err
            self._dispatch_flush_mesh(flush, packed)
            ok = True
        except Exception as e:  # noqa: BLE001 — fail the flush, not the server
            self._log(f"serve: batch failed (mesh): {e!r}")
            for r in flush.requests:
                if not r.future.done():
                    r.future.set_error(e)
                    self._record_slo_bad(klass=r.klass)
                    self._note_request(
                        trace_id=r.trace_id, status="dispatch_failed",
                        error=repr(e), precision=r.precision,
                        flush_id=flush.flush_id)
        finally:
            busy = time.perf_counter() - t0
            # the shards ran CONCURRENTLY under one dispatch: each
            # participating shard was busy for the flush wall, which
            # keeps per-device occupancy comparable with the threads
            # engine's per-flush accounting
            for i in shards:
                self.device_set.note_complete(i, busy, ok=ok)

    def _dispatch_flush_mesh(self, flush: Flush, packed) -> None:
        import jax

        # same chaos point as the single-device path (ISSUE 14)
        faultinject.dispatch_point()
        stacked, counts, sub_shape = packed
        n = len(self.mesh_exec)
        reqs = flush.requests
        tier = flush.precision
        # the hot-swap boundary: ONE (sharded params, version) pair read
        # per flush — a reload landing after this line affects the NEXT
        # flush; this one keeps its dispatch-time tree alive by reference
        state, version = self.param_store.get(0, tier)
        pre = self._jit_cache_size()
        dispatched = self._stamp()
        flush.stamps["dispatched"] = dispatched
        staged = self.mesh_exec.stage(stacked)
        # tree_map(np.array, ...): a true host copy of every gathered
        # result leaf — the raw program returns a (preds, overflow,
        # n_edges) tuple (device_get ALIASES device buffers on CPU —
        # GC-ALIAS)
        res = jax.tree_util.tree_map(
            np.array, jax.device_get(self.mesh_predict(state, staged)))
        overflow = raw_edges = None
        if flush.form == "raw":
            out, overflow, raw_edges = res
        else:
            out = res
        fetched = self._stamp()
        flush.stamps["fetched"] = fetched
        post = self._jit_cache_size()
        if self.warmed and pre is not None and post is not None and post > pre:
            with self._lock:
                self._compiles_after_warm += post - pre
            self.telemetry.counter_add("serve_recompiles_after_warm",
                                       post - pre)
            self._log(
                f"serve: UNEXPECTED recompile after warmup "
                f"(mesh shape {sub_shape}); latency SLO was broken "
                f"this batch"
            )
        if self._spans_on:  # skip arg-building when off
            self._span("serve.dispatch", dispatched, fetched,
                       flush_id=flush.flush_id, engine="mesh", shards=n,
                       shape=str(sub_shape), trace_ids=flush.trace_ids())
        now = self._clock()
        # real graphs over the slots the mesh dispatch actually ran
        occupancy = len(reqs) / (n * sub_shape.graph_cap)
        for i, c in enumerate(counts):
            if c > 0:
                self._count(f"batches_device{i}")
        # same accounting as the threads engine, over the n shards the
        # dispatch spanned (raw_edges comes back [n_shards, G'])
        self._note_edge_occupancy(flush, raw_edges, shape=sub_shape,
                                  n_shards=n)
        wire = "raw" if flush.form == "raw" else "featurized"
        for j, r in enumerate(reqs):
            # request j sat at (shard j % N, row j // N): the
            # round-robin split coordinate (executor.split_round_robin)
            shard, row = j % n, j // n
            if overflow is not None and overflow[shard, row]:
                self._fallback_overflow(r)
                continue
            prediction = out[shard, row].copy()
            latency_ms = (now - r.enqueued) * 1e3
            if self.cache is not None and r.fingerprint is not None:
                self.cache.put(r.fingerprint, (prediction, version))
            replied = self._stamp()
            stamps = {**r.stamps, **flush.stamps, "replied": replied}
            r.future.set_result(ServeResult(
                prediction=prediction, param_version=version,
                latency_ms=latency_ms, batch_occupancy=occupancy,
                device_id=shard, trace_id=r.trace_id, precision=tier,
                flush_id=flush.flush_id, stamps=stamps, wire=wire,
                klass=r.klass, backfilled=r.backfilled,
            ))
            if self._spans_on:  # skip arg-building when off
                args = {"trace_id": r.trace_id,
                        "flush_id": flush.flush_id, "device": shard,
                        "queue_ms": round(
                            (stamps["packed"] - stamps["queued"]) * 1e3,
                            3),
                        "dispatch_ms": round((fetched - dispatched) * 1e3,
                                             3)}
                if r.trace_parent:
                    args["parent"] = r.trace_parent
                self._span("serve.request", stamps["queued"], replied,
                           **args)
            self._note_request(
                trace_id=r.trace_id, status="ok", param_version=version,
                precision=tier, wire=wire, flush_id=flush.flush_id,
                device=shard, latency_ms=latency_ms, stamps=stamps)
            self._journal_served(
                graph=r.graph, fingerprint=r.fingerprint,
                trace_id=r.trace_id, prediction=prediction,
                version=version, wire=wire)
            self._record_latency(latency_ms)
            self._lat_rolling.add(latency_ms)
            self._observe_served(latency_ms, version=version,
                                 klass=r.klass)
            self.telemetry.observe_value("serve_latency_ms", latency_ms)
            self._count("responses")
            self._count(f"responses_class_{r.klass}")
            if r.backfilled:
                self._count("responses_backfilled")
            if wire == "raw":
                self._count("responses_raw")
            if tier != "f32":
                self._count(f"responses_{tier}")
        self._count("batches")
        self._note_flush_backfill(flush)
        with self._lock:
            self._occupancies.append(occupancy)
            del self._occupancies[:-4096]
        self._occ_rolling.add(occupancy)
        oh = self.hists.get("serve_flush_occupancy_hist")
        if oh is not None:
            oh.observe(occupancy)
        self.telemetry.observe_value("serve_batch_occupancy", occupancy)
        self.telemetry.set_gauge("serve_queue_depth", self.batcher.depth)

    def _fail_expired(self, flush: Flush) -> None:
        for r in flush.expired:
            self._record_slo_bad(klass=r.klass)
            self._count("reject_timeout")
            self._note_request(trace_id=r.trace_id, status="timeout",
                              precision=r.precision)
            r.future.set_error(ServeRejection(
                TIMEOUT,
                f"deadline exceeded after "
                f"{(self._clock() - r.enqueued) * 1e3:.1f} ms in queue",
            ))

    def _featurize_pending(self, flush: Flush) -> None:
        """Resolve deferred wire-form structures in a featurized flush:
        featurize HERE — this runs on the pack pool (or the worker's
        pack stage), never on the admission thread, so one large
        structure cannot head-of-line-block admission (the ISSUE-11
        bugfix). A structure the featurizer rejects fails ALONE (its
        future gets the error; co-batched members keep flying)."""
        keep = []
        for r in flush.requests:
            if not isinstance(r.graph, RawStructure):
                keep.append(r)
                continue
            try:
                if self.featurizer is None:
                    raise ValueError("no fallback featurizer configured")
                g = self.featurizer(r.graph)
                self._check_wellformed(g)
            except Exception as e:  # noqa: BLE001 — fail THIS request only
                self._count("reject_malformed")
                r.future.set_error(ServeRejection(
                    MALFORMED, f"structure featurization failed: {e}"))
                continue
            r.graph = g
            r.compactable = self.shape_set.compactable(g)
            keep.append(r)
        flush.requests = keep

    def _pack_flush(self, flush: Flush, pool=None):
        """-> (batch, pool buffer or None). Raw-wire flushes stage the
        RawBatch form (near-zero host work — the in-program search
        builds the graph); featurized flushes first resolve any
        deferred wire-form structures (``_featurize_pending``), then
        compact staging when the shape set carries a spec AND every
        request in the flush is compactable, full-fidelity otherwise.

        Under the mesh engine the packed form is the SPLIT one: the
        flush's graphs round-robined across the mesh, each shard packed
        into one common rung, stacked on the device axis —
        ``(stacked, per-shard real counts, rung)``."""
        if flush.form != "raw":
            self._featurize_pending(flush)
            if not flush.requests:
                raise ValueError("every request in the flush failed "
                                 "featurization")
        graphs = [r.graph for r in flush.requests]
        if flush.form == "raw":
            self._count("pack_raw")
            if self.mesh_exec is not None:
                groups, sub_shape, counts = self.mesh_exec.plan_flush(
                    graphs, self.shape_set)
                stacked = self.mesh_exec.stack(
                    [self.shape_set.pack_raw(g, shape=sub_shape)
                     for g in groups])
                return (stacked, counts, sub_shape), None
            return self.shape_set.pack_raw(graphs, shape=flush.shape), None
        if self.mesh_exec is not None:
            groups, sub_shape, counts = self.mesh_exec.plan_flush(
                graphs, self.shape_set)
            compact = (self.shape_set.compact is not None
                       and all(r.compactable for r in flush.requests))
            pack = (self.shape_set.pack if compact
                    else self.shape_set.pack_full)
            stacked = self.mesh_exec.stack(
                [pack(g, shape=sub_shape) for g in groups])
            if self.shape_set.compact is not None:
                self._count("pack_compact" if compact else "pack_full")
            return (stacked, counts, sub_shape), None
        if self.shape_set.compact is not None:
            if all(r.compactable for r in flush.requests):
                buf = None
                if pool is not None:
                    key = self.shape_set.buffer_key(flush.shape)
                    buf = (key, pool.acquire(
                        key, self.shape_set.buffer_factory(flush.shape)))
                batch = self.shape_set.pack(
                    graphs, shape=flush.shape,
                    out=None if buf is None else buf[1],
                )
                self._count("pack_compact")
                return batch, buf
            self._count("pack_full")
            return self.shape_set.pack_full(graphs, shape=flush.shape), None
        return self.shape_set.pack(graphs, shape=flush.shape), None

    def _process(self, flush: Flush) -> None:
        """The in-line (pack_workers=0) flush path: expire, pack,
        dispatch — all on the calling thread (same stamp/span/telemetry
        discipline as the pipelined pack stage)."""
        self._fail_expired(flush)
        if not flush.requests:
            return
        self._run_flush(*self._make_pack_one(None)(flush), pool=None)

    def _run_flush(self, flush: Flush, batch, buf, err, *, pool,
                   device: int = 0, routed: bool = False) -> None:
        """Dispatch one packed flush on ``device`` with the shared
        error/accounting/buffer-release discipline: a failed flush fails
        alone (its futures get the error, the server keeps serving), the
        device's in-flight count and busy time are maintained exactly
        once per flush, and a pooled staging buffer is released only
        AFTER the blocking fetch inside ``_dispatch_flush`` proved the
        device consumed it. ``routed`` marks flushes whose enqueue was
        already counted by the multidev router."""
        if not routed:
            self.device_set.note_enqueue(device)
        t0 = time.perf_counter()
        ok = False
        try:
            if err is not None:
                raise err
            self._dispatch_flush(flush, batch, device=device)
            ok = True
        except Exception as e:  # noqa: BLE001 — fail the flush, not the server
            self._log(f"serve: batch failed (device {device}): {e!r}")
            for r in flush.requests:
                if not r.future.done():
                    r.future.set_error(e)
                    self._record_slo_bad(klass=r.klass)
                    self._note_request(
                        trace_id=r.trace_id, status="dispatch_failed",
                        error=repr(e), precision=r.precision,
                        flush_id=flush.flush_id, device=device)
        finally:
            self.device_set.note_complete(device,
                                          time.perf_counter() - t0, ok=ok)
            if buf is not None and pool is not None:
                pool.release(*buf)

    def _dispatch_flush(self, flush: Flush, batch, device: int = 0) -> None:
        import jax

        # serve-side chaos point (resilience/faultinject.py, ISSUE 14):
        # deterministic dispatch exception / wedge / slowdown — a no-op
        # without a CGNN_TPU_FAULTS plan
        faultinject.dispatch_point()
        reqs = flush.requests
        # the hot-swap boundary: one consistent (params, version) REPLICA
        # pair per batch, read from the dispatch device's slot FOR THE
        # FLUSH'S PRECISION TIER — a reload landing after this line
        # affects the NEXT batch; this one keeps its dispatch-time
        # replica alive by reference and finishes on it
        tier = flush.precision
        state, version = self.param_store.get(device, tier)
        pre = self._jit_cache_size()
        dispatched = self._stamp()
        flush.stamps["dispatched"] = dispatched
        # tree_map(np.array, ...), not asarray: a true host copy of
        # every output leaf (device_get ALIASES device buffers on CPU —
        # graftcheck GC-ALIAS) so response rows never share memory with
        # a buffer the pool is about to recycle
        res = jax.tree_util.tree_map(
            np.array, jax.device_get(self.predict_step(state, batch)))
        overflow = raw_edges = None
        if flush.form == "raw":
            # the raw program's output contract (train/step.py): a
            # (predictions, cap_overflow, n_edges) tuple
            out, overflow, raw_edges = res
        else:
            out = res
        fetched = self._stamp()
        flush.stamps["fetched"] = fetched
        post = self._jit_cache_size()
        if self.warmed and pre is not None and post is not None and post > pre:
            # a recompile after warmup is a policy bug (the batcher left
            # the warm shape set) — LOUD, and counted for the loadgen.
            # Under the lock: one dispatch thread PER device writes this
            # (a bare += loses updates across threads; GC-LOCKSHARE)
            with self._lock:
                self._compiles_after_warm += post - pre
            self.telemetry.counter_add("serve_recompiles_after_warm",
                                       post - pre)
            self._log(
                f"serve: UNEXPECTED recompile after warmup "
                f"(shape {flush.shape}); latency SLO was broken this batch"
            )
        # the dispatch->fetch hop (device compute + transfer), one span
        # per flush with the co-batched trace ids as the join keys
        if self._spans_on:  # skip arg-building when off
            self._span("serve.dispatch", dispatched, fetched,
                       flush_id=flush.flush_id, device=device,
                       shape=str(flush.shape), trace_ids=flush.trace_ids())
        now = self._clock()
        occupancy = len(reqs) / flush.shape.graph_cap
        self._count(f"batches_device{device}")
        self._note_edge_occupancy(flush, raw_edges)
        wire = "raw" if flush.form == "raw" else "featurized"
        for i, r in enumerate(reqs):
            if overflow is not None and overflow[i]:
                # the in-program cap-overflow flag (INVARIANTS.md): this
                # structure's lattice needs more periodic images than
                # the rung provides — its row was computed from a
                # TRUNCATED graph and must never be served. Route it to
                # the host-featurized fallback form instead.
                self._fallback_overflow(r)
                continue
            row = out[i].copy()
            latency_ms = (now - r.enqueued) * 1e3
            if self.cache is not None and r.fingerprint is not None:
                self.cache.put(r.fingerprint, (row, version))
            replied = self._stamp()
            stamps = {**r.stamps, **flush.stamps, "replied": replied}
            r.future.set_result(ServeResult(
                prediction=row, param_version=version,
                latency_ms=latency_ms, batch_occupancy=occupancy,
                device_id=device, trace_id=r.trace_id, precision=tier,
                flush_id=flush.flush_id, stamps=stamps, wire=wire,
                klass=r.klass, backfilled=r.backfilled,
            ))
            # the whole journey, one span per request: admission ->
            # reply, args carrying the flush join key and stage stamps
            # (plus the upstream attempt span when one propagated in —
            # the cross-process nesting key)
            if self._spans_on:  # skip arg-building when off
                args = {"trace_id": r.trace_id,
                        "flush_id": flush.flush_id, "device": device,
                        "queue_ms": round(
                            (stamps["packed"] - stamps["queued"]) * 1e3,
                            3),
                        "dispatch_ms": round((fetched - dispatched) * 1e3,
                                             3)}
                if r.trace_parent:
                    args["parent"] = r.trace_parent
                self._span("serve.request", stamps["queued"], replied,
                           **args)
            self._note_request(
                trace_id=r.trace_id, status="ok", param_version=version,
                precision=tier, wire=wire, flush_id=flush.flush_id,
                device=device, latency_ms=latency_ms, stamps=stamps)
            self._journal_served(
                graph=r.graph, fingerprint=r.fingerprint,
                trace_id=r.trace_id, prediction=row,
                version=version, wire=wire)
            self._record_latency(latency_ms)
            self._lat_rolling.add(latency_ms)
            self._observe_served(latency_ms, version=version,
                                 klass=r.klass)
            # per REQUEST, not per batch: the run-summary quantiles must
            # describe the same distribution stats() does (PERF.md §10)
            self.telemetry.observe_value("serve_latency_ms", latency_ms)
            self._count("responses")
            self._count(f"responses_class_{r.klass}")
            if r.backfilled:
                self._count("responses_backfilled")
            if wire == "raw":
                self._count("responses_raw")
            if tier != "f32":
                self._count(f"responses_{tier}")
        self._count("batches")
        self._note_flush_backfill(flush)
        with self._lock:
            self._occupancies.append(occupancy)
            del self._occupancies[:-4096]
        self._occ_rolling.add(occupancy)
        oh = self.hists.get("serve_flush_occupancy_hist")
        if oh is not None:
            oh.observe(occupancy)
        self.telemetry.observe_value("serve_batch_occupancy", occupancy)
        self.telemetry.set_gauge("serve_queue_depth", self.batcher.depth)

    # ---- raw-wire overflow + occupancy bookkeeping (ISSUE 11) ----

    def _fallback_overflow(self, r) -> None:
        """Route one overflow-flagged raw request to the featurized
        fallback: re-offer it as a deferred-featurize request sharing
        the SAME future/trace/deadline (the pack pool featurizes it, a
        featurized flush answers it). Runs on the dispatch thread —
        cheap (no featurization here), and the counter is the telemetry
        the loadgen/smoke pin."""
        self._count("ingest_cap_overflow")
        self.telemetry.counter_add("ingest_cap_overflow", 1)
        if self.featurizer is None:
            r.future.set_error(ServeRejection(
                OVERSIZE,
                self.shape_set.raw.oversize_detail(r.graph)
                + " (in-program cap-overflow flag; no fallback "
                  "featurizer configured)",
            ))
            return
        fallback = Request(
            graph=r.graph, enqueued=r.enqueued, deadline=r.deadline,
            future=r.future, fingerprint=None, compactable=False,
            trace_id=r.trace_id, stamps=r.stamps, precision=r.precision,
            form="feat", trace_parent=r.trace_parent,
            # the re-offer keeps the request's scheduling contract: same
            # class and tenant, never a silent downgrade (INVARIANTS.md)
            klass=r.klass, tenant=r.tenant,
        )
        try:
            self.batcher.offer(fallback)
        except ServeRejection as e:
            self._count(f"reject_{e.reason}")
            r.future.set_error(e)

    def _note_edge_occupancy(self, flush: Flush, raw_edges,
                             shape=None, n_shards: int = 1) -> None:
        """Per-rung edge-slot occupancy — the cap-calibration signal
        (observe/gauges.py ``ingest_gauges``; /metrics). For raw
        flushes the TRUE edge count comes back from the program
        (``n_edges``); featurized flushes count host-known edges. The
        mesh engine passes its common rung + shard count (the dispatch
        spanned ``n_shards`` copies of the rung's slots) — ONE
        accounting shared by both engines, so the formula cannot
        drift between them."""
        shape = shape or flush.shape
        try:
            rung = self.shape_set.shapes.index(shape)
        except ValueError:
            return
        if flush.form == "raw":
            if raw_edges is None:
                return
            spec = self.shape_set.raw
            slots = (n_shards * shape.graph_cap * spec.snode_cap
                     * spec.dense_m)
            occ = float(np.asarray(raw_edges).sum()) / max(slots, 1)
        else:
            occ = sum(r.graph.num_edges for r in flush.requests) \
                / max(n_shards * shape.edge_cap, 1)
        with self._lock:
            self._rung_edge_occ[rung] = occ
        self.telemetry.set_gauge(f"ingest_rung{rung}_edge_occupancy", occ)

    def _note_flush_backfill(self, flush: Flush) -> None:
        """Per-flush backfill accounting (ISSUE 19): how many graph
        slots the chosen rung had to spare after the head-class prefix,
        and how many of them lower-class requests actually filled — the
        serve_padding_fill_share numerator/denominator. Only flushes
        that OFFERED slack count, so the gauge reads "of the padding
        backfill could have converted, how much did it"."""
        if not flush.slack_slots:
            return
        with self._lock:
            self._backfill_filled += flush.n_backfilled
            self._backfill_slack += flush.slack_slots

    # ---- bookkeeping ----

    def _count(self, key: str) -> None:
        with self._lock:
            self.counts[key] = self.counts.get(key, 0) + 1
        self.telemetry.counter_add(f"serve_{key}", 1)

    def _record_latency(self, latency_ms: float) -> None:
        with self._lock:
            self._latencies.append(latency_ms)
            del self._latencies[:-8192]

    def latency_quantiles(self) -> dict:
        """{p50, p95, p99, mean, count} over recent responses."""
        with self._lock:
            vals = list(self._latencies)
        if not vals:
            return {}
        arr = np.asarray(vals)
        p50, p95, p99 = np.percentile(arr, [50, 95, 99])
        return {"p50": float(p50), "p95": float(p95), "p99": float(p99),
                "mean": float(arr.mean()), "count": len(vals)}

    def rolling_quantiles(self) -> dict:
        """Live rolling-window latency quantiles (the /metrics view)."""
        return self._lat_rolling.quantiles()

    def stats(self) -> dict:
        with self._lock:
            counts = dict(self.counts)
            occ = list(self._occupancies)
            draining = self._draining
            compiles_after_warm = self._compiles_after_warm
            rung_occ = dict(self._rung_edge_occ)
            backfill_filled = self._backfill_filled
            backfill_slack = self._backfill_slack
        out = {
            "counts": counts,
            "queue_depth": self.batcher.depth,
            "param_version": self.param_store.version,
            # which execution layer drives the devices (ISSUE 10):
            # 'mesh' = one sharded dispatch covers the set,
            # 'threads' = per-device dispatch threads (the A/B engine)
            "engine": self.engine,
            "devices": self.device_set.stats(),
            "draining": draining,
            "latency_ms": self.latency_quantiles(),
            # the live plane (ISSUE 6): rolling-window quantiles — what
            # the last `rolling_window_s` seconds looked like, not the
            # whole run — plus each device's in-flight depth right now
            "rolling": {
                "window_s": self.rolling_window_s,
                "latency_ms": self._lat_rolling.quantiles(),
                "batch_occupancy": self._occ_rolling.quantiles(),
                "device_inflight": self.device_set.inflight_depths(),
            },
            "batch_occupancy_mean": float(np.mean(occ)) if occ else 0.0,
            "shapes": [s.to_meta() for s in self.shape_set],
            "precisions": list(self.precisions),
            # priority serving (ISSUE 19): the per-class answer counts
            # and the padding->goodput conversion the bench A/B pins
            "priority": {
                "backfill": self.batcher.backfill,
                "class_wait_ms": {
                    c: round(w * 1e3, 3)
                    for c, w in self.batcher.class_wait.items()
                },
                "responses_by_class": {
                    c: counts.get(f"responses_class_{c}", 0)
                    for c in CLASSES
                },
                "backfilled_responses": counts.get(
                    "responses_backfilled", 0),
                "padding_fill_share": (
                    backfill_filled / backfill_slack
                    if backfill_slack else 0.0),
                "slack_slots": backfill_slack,
            },
            "recompiles_after_warm": compiles_after_warm,
            "ingest": {
                "compact": self.shape_set.compact is not None,
                "raw": self.shape_set.raw is not None,
                "cap_overflows": counts.get("ingest_cap_overflow", 0),
                "rung_edge_occupancy": {
                    str(k): v for k, v in sorted(rung_occ.items())
                },
                "pack_workers": self._pack_workers,
                "pack_s": self.telemetry.series_quantiles("serve_pack_s"),
                "pipeline_wait_s": self.telemetry.series_quantiles(
                    "pipeline_wait_s"),
            },
        }
        if self.cache is not None:
            cstats = self.cache.stats()
            with self._sf_lock:
                inflight_keys = len(self._inflight)
            cstats.update({
                "single_flight": self._single_flight,
                "inflight_keys": inflight_keys,
                "coalesced": counts.get("cache_coalesced", 0),
                "dup_misses": counts.get("cache_dup_misses", 0),
                "fills": counts.get("cache_fills", 0),
                "fill_stale": counts.get("cache_fill_stale", 0),
            })
            out["cache"] = cstats
        if self._watcher is not None:
            out["reload"] = {"swaps": self._watcher.swaps,
                             "skips": self._watcher.skips,
                             **self._watcher.control()}
        if self.journal is not None:
            out["journal"] = self.journal.stats()
        # the metrics-truth layer (ISSUE 16): error-budget accounting +
        # alert states, and the embedded time-series store's own health
        if self.slo is not None:
            out["slo"] = self.slo.state()
        if self.tsdb is not None:
            out["tsdb"] = self.tsdb.stats()
        return out


def structure_featurizer(data_cfg) -> Callable:
    """RawStructure -> CrystalGraph via the checkpoint's featurization
    config (the deferred pack-pool featurize + cap-overflow fallback;
    http.py's JSON featurizer delegates here so online requests are
    featurized exactly like the training data was)."""
    from cgnn_tpu.data.dataset import featurize_structure
    from cgnn_tpu.data.structure import Structure

    cfg = data_cfg.featurize_config()
    gdf = cfg.gdf()

    def featurize(rs: RawStructure) -> CrystalGraph:
        s = Structure(rs.lattice, rs.frac_coords, rs.numbers)
        target = (rs.target if rs.target is not None
                  else np.zeros(1, np.float32))
        return featurize_structure(s, target, cfg, rs.cif_id, gdf,
                                   target_mask=rs.target_mask)

    return featurize


def plan_from_state(meta: dict) -> dict:
    """Model/packing knobs serve needs from a checkpoint's meta dict."""
    from cgnn_tpu.config import DataConfig, ModelConfig

    model_cfg = ModelConfig.from_meta(meta.get("model", {}))
    data_cfg = DataConfig.from_meta(meta.get("data", {}))
    return {"model_cfg": model_cfg, "data_cfg": data_cfg,
            "task": meta.get("task", "regression")}


def load_server(
    ckpt_dir: str,
    *,
    batch_size: int = 64,
    rungs: int = 3,
    calibration: Sequence[CrystalGraph] | None = None,
    calibration_n: int = 256,
    tag: str = "latest",
    telemetry=None,
    max_queue: int = 256,
    max_wait_ms: float = 5.0,
    class_max_wait_ms: dict | None = None,
    backfill: bool = True,
    wfq_weights: dict | None = None,
    default_timeout_ms: float | None = 1000.0,
    cache_size: int = 1024,
    compact: str = "auto",
    wire: str = "auto",
    raw_precheck: bool = True,
    pack_workers: int | None = None,
    devices: str | int = "auto",
    engine: str = "auto",
    precision: str = "f32",
    trace_ring: int = 65536,
    slo_layer: bool = True,
    slo_objectives=None,
    slo_rules=None,
    watch: bool = True,
    warm: bool = True,
    poll_interval_s: float = 2.0,
    profile_dir: str = "",
    log_fn: Callable = print,
):
    """Boot an InferenceServer from a training checkpoint directory.

    Shared by serve.py (HTTP) and scripts/serve_loadgen.py (in-process):
    restores the verified checkpoint, rebuilds the model, plans the shape
    ladder from ``calibration`` (default: synthetic structures drawn with
    the checkpoint's own featurization config), warms every shape, and —
    with ``watch`` — attaches the hot-reload watcher to ``ckpt_dir``.

    ``compact='auto'`` (default) serves compact-staged when the backend
    is an ACCELERATOR and the calibration sample probes stageable
    (data/compact.py); on a CPU backend the device IS the host, so
    shrinking H2D bytes buys nothing while the on-device re-expansion
    costs real compute — measured on this container's loadgen: compact
    serving on CPU is throughput-neutral with a worse p99, on the
    tunneled TPU it is the ISSUE-4 win. ``'on'`` forces it (the A/B
    leg), ``'off'`` forces full-fidelity packing.

    ``pack_workers`` sizes the pack pipeline between the batcher and
    the dispatch loop (0 = pack in-line on the worker thread); default
    ``None`` follows the same device rule — 1 on accelerators (pack
    overlaps remote dispatch), 0 on CPU (an overlap thread only steals
    cores from the compute it would overlap with).

    ``precision`` names the tiers to WARM, comma-separated (e.g.
    ``'f32,bf16,int8'`` — serve/quantize.py); requests then pick a tier
    per call (default f32). Every warmed tier multiplies the warmup
    compile count and never compiles after.

    ``devices`` (ISSUE 5) selects the dispatch set: ``'auto'`` = every
    local device on accelerator backends, one device on CPU (host
    "devices" share the same cores — serve/devices.py); an int forces
    that many anywhere, which is how the 8-host-device dryrun proves
    distribution in-container.

    ``engine`` (ISSUE 10) selects HOW a multi-device set is driven:
    ``'mesh'`` (the ``'auto'`` default whenever more than one device is
    resolved) batch-shards every flush across a ``Mesh`` +
    ``NamedSharding`` layout and runs ONE jitted dispatch covering all
    devices — compile count = programs, one sharded param tree per
    tier, no router threads (parallel/executor.py); ``'threads'`` keeps
    the ISSUE-5 thread-per-device DeviceSet layer (per-device replicas,
    least-loaded routing, programs x N executables) for the A/B.
    Either engine serves bit-exact predictions; hot reload swaps
    atomically under one version in both.

    -> (server, dict of the bits callers reuse: manager, meta, configs,
    template graph, the calibration sample).
    """
    import jax

    from cgnn_tpu.config import build_model
    from cgnn_tpu.data.dataset import load_synthetic
    from cgnn_tpu.train import (
        CheckpointManager,
        Normalizer,
        create_train_state,
        make_optimizer,
    )

    mgr = CheckpointManager(ckpt_dir, log_fn=log_fn)
    if not mgr.exists(tag):
        raise FileNotFoundError(f"no {tag!r} checkpoint under {ckpt_dir}")
    meta = mgr.read_meta(tag)
    cfg = plan_from_state(meta)
    if cfg["task"] == "force":
        raise NotImplementedError(
            "online serving covers property prediction; the force task's "
            "per-atom output extraction is offline-only (predict.py)"
        )
    model_cfg, data_cfg = cfg["model_cfg"], cfg["data_cfg"]
    # serving admits any structure that fits the ladder: widen
    # training-set-derived bounds (ModelConfig.for_arbitrary_inputs —
    # the cgconv window contract)
    model_cfg = model_cfg.for_arbitrary_inputs()
    model = build_model(model_cfg, data_cfg, cfg["task"])
    if calibration is None:
        # keep_geometry: raw-wire spec planning (below) calibrates its
        # periodic image caps from the calibration LATTICES; the graphs'
        # packed shapes are unchanged (pack_graphs always allocates the
        # geometry fields)
        calibration = load_synthetic(
            calibration_n, data_cfg.featurize_config(), seed=0,
            keep_geometry=True,
        )
    dense_m = model_cfg.dense_m or None
    edge_dtype = (jax.numpy.bfloat16 if model_cfg.dtype == "bfloat16"
                  else np.float32)
    on_accelerator = jax.default_backend() != "cpu"
    device_list = resolve_devices(devices)
    if pack_workers is None:
        # accelerators overlap packing with remote dispatch; on CPU an
        # overlap thread steals the cores it would overlap with — but a
        # FORCED multi-device set (the dryrun case) gets one packer so
        # the router + per-device dispatch threads are actually fed
        pack_workers = 1 if on_accelerator or len(device_list) > 1 else 0
    want_compact = (compact == "on"
                    or (compact == "auto" and on_accelerator))
    compact_spec = None
    if want_compact and dense_m is not None:
        from cgnn_tpu.data.compact import CompactSpec, CompactUnsupported

        try:
            compact_spec = CompactSpec.build(
                list(calibration), data_cfg.featurize_config().gdf(),
                dense_m=dense_m, edge_dtype=edge_dtype,
            )
        except CompactUnsupported as e:
            log_fn(f"serve: compact staging unavailable ({e}); "
                   f"full-fidelity packing")
    # raw wire (ISSUE 11): like compact, 'auto' engages on accelerator
    # backends only — on CPU the host IS the device, so moving the
    # neighbor search "on device" just moves it between host cores while
    # paying padded per-structure slots; 'raw' forces (the CI smoke and
    # A/B legs), 'featurized' disables
    if wire not in ("auto", "raw", "featurized"):
        raise ValueError(
            f"wire must be 'auto', 'raw', or 'featurized', got {wire!r}"
        )
    want_raw = wire == "raw" or (wire == "auto" and on_accelerator)
    raw_spec = None
    if want_raw and dense_m is not None:
        from cgnn_tpu.data.rawbatch import RawUnsupported, plan_raw_spec

        fcfg = data_cfg.featurize_config()
        try:
            raw_spec = plan_raw_spec(
                list(calibration), fcfg.gdf(), fcfg.radius, dense_m,
            )
        except RawUnsupported as e:
            log_fn(f"serve: raw wire unavailable ({e}); "
                   f"featurized wire only")
    elif want_raw:
        log_fn("serve: raw wire requires the dense layout; "
               "featurized wire only")
    shape_set = plan_shape_set(
        calibration, batch_size, rungs=rungs, dense_m=dense_m,
        edge_dtype=edge_dtype, num_targets=model_cfg.num_targets,
        compact=compact_spec, raw=raw_spec,
    )
    template = calibration[0]
    # model init reads the expanded form regardless of staging mode
    example = shape_set.pack_full([template])
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.identity(model_cfg.num_targets), rng=jax.random.key(0),
    )
    state = mgr.restore_for_inference(state, tag)
    # label with what the verifying chain ACTUALLY loaded — it can fall
    # back past a corrupt newest save, and a wrong label here would both
    # mis-tag every response and pin the watcher (newest == "current")
    version = mgr.last_restored or tag
    precisions = tuple(
        t.strip() for t in str(precision).split(",") if t.strip()
    ) or ("f32",)
    server = InferenceServer(
        state, shape_set, version=version, telemetry=telemetry,
        max_queue=max_queue, max_wait_ms=max_wait_ms,
        class_max_wait_ms=class_max_wait_ms, backfill=backfill,
        wfq_weights=wfq_weights,
        default_timeout_ms=default_timeout_ms, cache_size=cache_size,
        pack_workers=pack_workers, devices=device_list, engine=engine,
        precisions=precisions, model=model,
        featurizer=structure_featurizer(data_cfg),
        raw_precheck=raw_precheck, trace_ring=trace_ring,
        slo_layer=slo_layer, slo_objectives=slo_objectives,
        slo_rules=slo_rules, log_fn=log_fn,
    )
    # ``warm=False`` (ISSUE 14): the caller compiles later — serve.py
    # binds its HTTP listener FIRST so /healthz can report ready=False
    # for the whole warmup window instead of connection-refused (a
    # router cannot tell refused-because-warming from dead)
    if warm:
        server.warm(template)
    if profile_dir:
        server.enable_profiling(profile_dir)
    if watch:
        server.attach_watcher(mgr, poll_interval_s=poll_interval_s,
                              log_fn=log_fn)
    return server, {
        "manager": mgr, "meta": meta, "model_cfg": model_cfg,
        "data_cfg": data_cfg, "template": template,
        "calibration": calibration,
    }
