"""LRU result cache keyed by a structure fingerprint.

Production graph-property traffic is heavily repeated (the same
trending structures queried by many users), and the forward pass is
deterministic given (params, structure) — so identical queries within
one param version can be answered from memory. The fingerprint hashes
the FEATURIZED arrays (atom features, edge features, connectivity), not
object identity, so equal structures hit regardless of which client
sent them.

Precision tiers (serve/quantize.py) are part of the key, not the value:
the server prefixes non-f32 fingerprints with the tier
(``"int8:<sha>"``), because a cached row is determined by (params,
structure, PROGRAM) — an f32 answer served to an int8 request would
silently undo the precision the client asked for (and vice versa), and
the tier-isolation test pins exactly that (tests/test_serve.py
TestPrecisionServing).

Staleness across hot param swaps is handled in TWO layers, both load-
bearing (server.py): entries are stored version-tagged, ``(row,
param_version)``, and REVALIDATED against the live version at hit time
— this is the correctness guarantee, because a micro-batch in flight
across a swap writes its old-version rows AFTER the swap fires; the
swap's ``cache.clear()`` (reload.py on_swap) is only bulk eviction so
dead entries stop occupying LRU slots. Do not remove the hit-time
version check in favor of the clear — that reintroduces the in-flight-
writer race (pinned by tests/test_serve.py hot-reload atomicity).
"""

from __future__ import annotations

import collections
import hashlib
import threading

import numpy as np

from cgnn_tpu.data.graph import CrystalGraph


def structure_fingerprint(graph: CrystalGraph) -> str:
    """Content hash of a featurized structure (layout-qualified).

    blake2b, not sha1: faster in software (no SHA-NI dependency — on
    accelerator hosts whose CPUs lack it, sha1 falls off a cliff) and
    this is an in-memory cache key with no persisted state, so the hash
    can change between releases without a migration. The per-host
    sha1/blake2b ratio is measured by ``bench.py --ab cachepart``
    (``fingerprint_hash_us``). digest_size=20 keeps the hex length
    sha1-compatible for logs and tier prefixes.
    """
    h = hashlib.blake2b(digest_size=20)
    for arr in (graph.atom_fea, graph.edge_fea, graph.centers,
                graph.neighbors):
        a = np.ascontiguousarray(arr)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class ResultCache:
    """Thread-safe bounded LRU: fingerprint -> prediction row."""

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._data: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: str):
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                self.hits += 1
                return self._data[key]
            self.misses += 1
            return None

    def put(self, key: str, value) -> None:
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def snapshot(self) -> tuple:
        """Consistent ``(hits, misses, size, capacity)`` under the lock.

        ``hits``/``misses`` are mutated under ``_lock``; scraping the
        bare attributes from another thread could pair a pre-increment
        ``hits`` with a post-increment ``misses`` (a hit ratio that
        never existed). All metrics/stats readers go through here.
        """
        with self._lock:
            return (self.hits, self.misses, len(self._data), self.capacity)

    def stats(self) -> dict:
        hits, misses, size, capacity = self.snapshot()
        return {
            "size": size,
            "capacity": capacity,
            "hits": hits,
            "misses": misses,
        }
