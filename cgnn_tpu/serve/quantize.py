"""Low-precision inference programs: the serving precision ladder.

The training stack already runs bf16 compute where it wants to
(``--bf16``); serving adds a REQUEST-level precision dial (ISSUE 9,
ROADMAP item 2's second front): every rung of the warm shape ladder is
compiled once per precision tier at warmup, and each request picks its
tier — f32 fidelity for calibration traffic, bf16 for the bulk, int8
weights for maximum throughput — with zero post-warmup recompiles, the
same pin the shape ladder lives by.

Tiers (``TIERS``):

- ``f32``  — the checkpoint-native program (whatever dtype it was
  trained with; the label means "no serving-side degradation").
- ``bf16`` — bf16 activations: the SAME parameters applied through a
  bf16-compute clone of the model (f32 master weights cast in-program,
  exactly like ``--bf16`` training). No new state, half the MXU cost
  and activation HBM traffic on TPU.
- ``int8`` — int8 weights + bf16 activations: every 2-D ``kernel``
  parameter is replaced by a per-output-channel symmetric int8
  quantization (scale = absmax/127 per column) carried as a
  :class:`QuantizedKernel` pytree leaf; the compiled program stores
  weights in HBM at 1/4 the bytes and dequantizes into the matmul
  (``q.astype(bf16) * scale`` — XLA fuses it into the operand read).
  Biases, BatchNorm parameters/statistics, and the normalizer stay f32.

Mechanics: a tier is a ``TierSpec`` — a param transform plus an
``apply_fn``. Tier states share ONE jitted ``predict_step``: the
``apply_fn`` is a static pytree field of ``TrainState``, so each tier
traces its own cache entry at warmup and never again (the specs are
built ONCE per server; hot reload re-applies the same transform with
the same apply_fn object, so a swap cannot retrace). Accuracy is gated,
not assumed: ``scripts/quant_parity.py`` + tests/test_quantize.py pin
prediction-MAE ratio vs f32 <= 1.005 on the cached synthetic set.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import numpy as np
from flax import struct

TIERS = ("f32", "bf16", "int8")


# sub-channel granularity: scales are per (input-dim block, output
# channel). Per-column alone measured prediction-MAE drift at the edge
# of the 0.5% gate on small models (1.006-1.012); 32-row blocks halve
# the per-group absmax and bring the measured ratio to ~1.002-1.003
# with margin (tests/test_quantize.py). Scale storage is q_bytes/32 —
# noise next to the 4x weight-byte win.
_QBLOCK = 32


class QuantizedKernel(struct.PyTreeNode):
    """Blocked symmetric int8 weight: q [in, out] int8 with f32 scales
    per (32-row input block, output channel). ``in_dim`` is static so
    dequantization can undo the block padding."""

    q: Any  # [blocks*_QBLOCK, out] int8 (input dim padded to the block)
    scale: Any  # [blocks, out] f32
    in_dim: int = struct.field(pytree_node=False, default=0)


def quantize_kernel(w, block: int = _QBLOCK) -> QuantizedKernel:
    """Blocked symmetric int8 quantization of a 2-D [in, out] kernel."""
    import jax.numpy as jnp

    w32 = np.asarray(w, np.float32)
    in_dim, out = w32.shape
    b = max(1, min(block, in_dim))
    pad = (-in_dim) % b
    wp = np.pad(w32, ((0, pad), (0, 0)))
    wb = wp.reshape(-1, b, out)
    absmax = np.abs(wb).max(axis=1)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(wb / scale[:, None, :]), -127, 127).astype(np.int8)
    return QuantizedKernel(
        q=jnp.asarray(q.reshape(-1, out)),
        scale=jnp.asarray(scale),
        in_dim=in_dim,
    )


def _path_names(path) -> list:
    return [getattr(k, "key", getattr(k, "name", None)) for k in path]


# modules whose kernels stay full-precision: the embedding (first
# touch of the input) and the output head (its error lands 1:1 on the
# prediction) — both byte-negligible next to the conv fc_full kernels
# that carry the HBM win, and skipping them is what keeps the measured
# prediction-MAE drift inside the 0.5% gate (tests/test_quantize.py:
# quantizing them read 1.008, skipping them well under 1.005).
_KEEP_FULL_PRECISION = ("embedding", "fc_out")


def quantize_params(params):
    """Replace 2-D float ``kernel`` leaves with QuantizedKernel.

    Biases, BN scale/bias, and the ``_KEEP_FULL_PRECISION`` modules'
    kernels pass through untouched — int8 error concentrates where the
    bytes are, and the accuracy-critical edges stay exact. Output-width
    <= 8 kernels (per-task head columns, tiny fc_out variants) are
    skipped by the same logic."""
    import jax

    def convert(path, leaf):
        arr = np.asarray(leaf)
        names = _path_names(path)
        if (names[-1] == "kernel" and arr.ndim == 2
                and arr.shape[1] > 8
                and not any(n in _KEEP_FULL_PRECISION for n in names)
                and np.issubdtype(arr.dtype, np.floating)):
            return quantize_kernel(arr)
        return leaf

    return jax.tree_util.tree_map_with_path(
        convert, params, is_leaf=lambda x: isinstance(x, QuantizedKernel)
    )


def dequantize_params(params, dtype=None):
    """QuantizedKernel leaves -> dense kernels (in-program: XLA folds
    the cast+multiply into the matmul operand read; weights live in HBM
    as int8 + the tiny scale grid).

    The q*scale product is computed in f32 and THEN cast (``dtype``
    None = leave f32 for the model's own compute-dtype cast): rounding
    the scale to bf16 before the multiply double-rounds every weight —
    measured as the difference between passing and failing the 0.5%
    MAE-drift gate on small models."""
    import jax
    import jax.numpy as jnp

    def expand(leaf):
        if isinstance(leaf, QuantizedKernel):
            out = leaf.q.shape[-1]
            qb = leaf.q.astype(jnp.float32).reshape(
                leaf.scale.shape[0], -1, out
            )
            w = (qb * leaf.scale[:, None, :]).reshape(-1, out)
            w = w[: leaf.in_dim]
            return w if dtype is None else w.astype(dtype)
        return leaf

    return jax.tree_util.tree_map(
        expand, params, is_leaf=lambda x: isinstance(x, QuantizedKernel)
    )


@dataclasses.dataclass(frozen=True)
class TierSpec:
    """One precision tier: how to derive its state from the native one.

    ``transform`` maps the native param tree to the tier's; ``apply_fn``
    is the tier's model apply (a STABLE object — built once per server —
    so the jit trace cache never sees a fresh identity on hot reload).
    """

    name: str
    apply_fn: Callable
    transform: Callable

    def state_for(self, state):
        """Native serving state -> this tier's state. The optimizer
        state is dropped (``opt_state=()``): inference never reads it,
        and replicating it per tier x device would triple the HBM the
        params take."""
        return state.replace(
            params=self.transform(state.params),
            apply_fn=self.apply_fn,
            opt_state=(),
        )


def build_tier_specs(model, precisions: Sequence[str]) -> dict:
    """{tier: TierSpec} for the requested precision set.

    ``model`` is the native model MODULE (its ``.apply`` must be the
    serving state's apply_fn); the bf16 clone is derived from it, so
    any architecture the serving path hosts quantizes without a config
    round-trip. Build this ONCE per server (see module docstring).
    """
    import jax.numpy as jnp

    unknown = set(precisions) - set(TIERS)
    if unknown:
        raise ValueError(f"unknown precision tier(s) {sorted(unknown)}; "
                         f"valid: {TIERS}")
    specs: dict[str, TierSpec] = {}
    bf16_model = None
    if {"bf16", "int8"} & set(precisions):
        bf16_model = model.clone(dtype=jnp.bfloat16)
    for tier in precisions:
        if tier == "f32":
            specs[tier] = TierSpec("f32", model.apply, lambda p: p)
        elif tier == "bf16":
            specs[tier] = TierSpec("bf16", bf16_model.apply, lambda p: p)
        else:  # int8
            apply = _make_int8_apply(bf16_model)
            specs[tier] = TierSpec("int8", apply, quantize_params)
    return specs


def _make_int8_apply(bf16_model) -> Callable:
    """The int8 tier's apply_fn: dequantize INSIDE the program, then run
    the bf16 model. Built once; the closure identity is the jit key."""

    def apply_int8(variables, *args, **kwargs):
        variables = dict(variables)
        # dequantize in f32; the bf16 model's own compute casts once
        variables["params"] = dequantize_params(variables["params"])
        return bf16_model.apply(variables, *args, **kwargs)

    return apply_int8
