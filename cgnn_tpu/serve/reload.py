"""Hot checkpoint reload: pick up newly trained params without a restart.

A trainer keeps committing versioned saves (``ckpt-%08d/`` —
train/checkpoint.py) into a directory the server watches. The watcher
polls for a newer COMMITTED save (manifest present = committed, the
PR-2 protocol), restores it through the integrity-verifying chain
(``restore_for_inference`` on the explicit save name — crc-checked
against the manifest, never a blind load), and atomically swaps the
:class:`ParamStore` reference between batches.

Atomicity is by publication, not locking-the-world: the serving worker
reads ``(state, version)`` ONCE per micro-batch, so a swap landing
mid-batch changes nothing for that batch — in-flight requests finish on
the params they started with, zero drops, and every response records the
param version that computed it (the loadgen's hot-swap assertion keys on
exactly this).

A save that fails verification is skipped with a logged report and
remembered, so a corrupt upload neither takes the server down nor gets
retried in a hot loop; the next good save supersedes it.
"""

from __future__ import annotations

import sys
import threading
from typing import Callable

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.train.checkpoint import CheckpointManager


class ParamStore:
    """Atomic (state, version) holder the serving workers read per batch.

    With ``devices`` (ISSUE 5, serve/devices.py) the store holds ONE
    REPLICA PER DEVICE: ``get(i)`` returns device i's committed copy
    paired with the single shared version. ``swap`` builds every replica
    FIRST (the slow part — N device_puts — runs outside the lock, on the
    watcher thread) and then publishes the whole tuple and the version
    in one locked assignment, so no reader can ever observe a torn set:
    every ``get`` sees either all-old or all-new replicas, under exactly
    one version. In-flight flushes that already read their (state,
    version) pair keep their dispatch-time replica alive by reference
    and finish on it — the ISSUE-3 per-batch atomicity, now per-device.
    """

    def __init__(self, state, version: str = "init", devices=None,
                 tier_specs=None, placer=None):
        # precision tiers (serve/quantize.py): {tier: TierSpec} built
        # ONCE by the server. Each swap re-derives every tier's state
        # through the SAME spec (stable apply_fn identity), so a hot
        # reload can never retrace a warmed program. None = f32 only.
        #
        # ``placer`` (the mesh engine, parallel/executor.py): a
        # state -> placed-state callable replacing per-device
        # replication with ONE sharded (mesh-replicated) tree per tier —
        # get(0, tier) returns it, and a hot swap publishes one tree
        # under one version instead of an N-replica tuple.
        if placer is not None and devices is not None:
            raise ValueError(
                "ParamStore takes devices (per-replica mode) OR placer "
                "(one sharded tree), not both"
            )
        self._lock = racecheck.make_lock("serve.paramstore")
        self._devices = tuple(devices) if devices else None
        self._placer = placer
        self._specs = dict(tier_specs) if tier_specs else None
        self._states = self._build(state)
        self._version = version

    def _build(self, state) -> dict:
        """{tier: (replica per device, ...)} — the native state IS the
        f32 tier; derived tiers transform it before replication."""
        tiers = {"f32": state}
        if self._specs is not None:
            for name, spec in self._specs.items():
                if name != "f32":
                    tiers[name] = spec.state_for(state)
        return {t: self._replicate(s) for t, s in tiers.items()}

    def _replicate(self, state) -> tuple:
        if self._placer is not None:
            # mesh engine: ONE mesh-placed tree; every dispatch reads
            # slot 0 (the mesh, not the store, owns device placement)
            return (self._placer(state),)
        if self._devices is None:
            return (state,)
        from cgnn_tpu.serve.devices import replicate_state

        return replicate_state(state, self._devices)

    @property
    def tiers(self) -> tuple:
        with self._lock:
            return tuple(self._states)

    def get(self, device_index: int = 0, tier: str = "f32"):
        """-> (state replica for ``device_index``/``tier``, version)."""
        with self._lock:
            return self._states[tier][device_index], self._version

    @property
    def version(self) -> str:
        with self._lock:
            return self._version

    def swap(self, state, version: str) -> None:
        # derive tiers + replicate OUTSIDE the lock: quantization and N
        # device transfers must not stall every dispatch worker's get()
        states = self._build(state)
        with self._lock:
            self._states = states
            self._version = version


class CheckpointWatcher:
    """Polls a checkpoint directory and hot-swaps verified params.

    ``poll_once`` is the synchronous, testable unit; ``start`` runs it on
    a daemon thread every ``poll_interval_s``. ``template_state`` is any
    state with the right pytree structure (the serving state itself) —
    restores build a fresh state from it, never mutate it.
    """

    def __init__(
        self,
        manager: CheckpointManager,
        store: ParamStore,
        template_state,
        *,
        poll_interval_s: float = 2.0,
        telemetry=None,
        on_swap: Callable | None = None,
        coordinator: Callable | None = None,
        log_fn: Callable | None = None,
        gate: str | None = None,
        pin: str | None = None,
    ):
        self._mgr = manager
        self._store = store
        self._template = template_state
        self.poll_interval = poll_interval_s
        self._telemetry = telemetry
        self._on_swap = on_swap
        # cross-host agreement hook (parallel/dist.ReloadCoordinator):
        # called EVERY poll with the locally-newest committed save; what
        # it returns is what this host swaps to (None = not this round).
        # Each call is a collective in multi-host runs — drive poll_once
        # in lockstep across processes when one is set.
        self._coordinator = coordinator
        self._log = log_fn or (lambda m: print(m, file=sys.stderr))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # verified-bad saves: never retried (a corrupt file stays corrupt)
        self._skipped: set[str] = set()
        # ---- promotion guard (ISSUE 18) ----
        # Ungated newest_committed() chasing is only safe while the ONLY
        # committer is the promotion path itself. A continual trainer
        # committing candidates into the SAME shared directory made that
        # assumption false: every fleet replica would auto-swap to an
        # unevaluated candidate, making the rolling-promotion invariant
        # vacuous. ``gate`` caps what this watcher may auto-swap to (the
        # newest save it has been TOLD is approved); saves sorting above
        # it are held until the canary gate raises it. ``pin`` overrides
        # everything: converge on exactly that committed save (up OR
        # down — the canary replica evaluating a candidate, and the
        # rollback path returning it to the fleet version). Both are
        # mutated from the HTTP control thread while the watcher thread
        # reads them, hence the dedicated lock.
        self._ctl_lock = racecheck.make_lock("serve.reloadctl")
        self._gate = gate
        self._pin = pin
        self.swaps = 0
        self.skips = 0
        self.gate_holds = 0

    # ---- promotion-guard control (ISSUE 18) ----

    def set_pin(self, name: str | None) -> None:
        """Pin to exactly ``name`` (a committed ``ckpt-%08d`` save);
        None clears the pin and resumes gate/newest behaviour."""
        with self._ctl_lock:
            self._pin = name

    def set_gate(self, name: str | None) -> None:
        """Newest save this watcher may auto-swap to; None = chase
        ``newest_committed()`` unguarded (the pre-ISSUE-18 behaviour,
        right only when the trainer IS the promotion path)."""
        with self._ctl_lock:
            self._gate = name

    def control(self) -> dict:
        """The guard state + current version (the /reload-control view)."""
        with self._ctl_lock:
            pin, gate = self._pin, self._gate
            swaps, gate_holds = self.swaps, self.gate_holds
        return {"pin": pin, "gate": gate, "version": self._store.version,
                "swaps": swaps, "gate_holds": gate_holds}

    # ---- the synchronous unit ----

    def poll_once(self) -> bool:
        """Check for a newer committed save; swap if it verifies.

        Returns True iff a swap happened. Never raises on a bad
        checkpoint — it logs the skip report, counts it, and keeps
        serving the current params (a corrupt upload must not take the
        serving path down). A CROSS-HOST COORDINATION failure (only
        possible with a ``coordinator``) does raise: a shared checkpoint
        directory that never shows the agreed commit marker is a fatal
        desync, and swallowing it would leave the peer hosts blocked at
        the swap barrier — loud beats silently hung."""
        with self._ctl_lock:
            pin, gate = self._pin, self._gate
        if pin is not None:
            # exact-version override: the canary path. Downgrades are
            # deliberate here (rollback returns the canary to the fleet
            # version); an uncommitted pin just retries next poll — the
            # candidate may still be mid-commit.
            if pin == self._store.version or pin in self._skipped:
                return False
            if not self._mgr.is_committed(pin):
                return False
            target = pin
        else:
            newest = self._mgr.newest_committed()
            if self._coordinator is not None:
                # multi-host: every host polls in lockstep and swaps only
                # to the save process 0 announced, after the shared
                # barrier — a reload lands version-consistent on every
                # process
                newest = self._coordinator(newest)
            if newest is None or newest == self._store.version:
                return False
            if newest in self._skipped:
                return False
            target = newest
            if gate is not None and newest > gate:
                # ungated candidate: hold the line at the gate. If the
                # gate itself is newer than what we serve, converge on
                # IT (the fleet-wide promotion broadcast); otherwise
                # keep serving what we have. ckpt-%08d names compare
                # lexically, so > is version order.
                cur = self._store.version
                if (gate == cur or gate in self._skipped
                        or (cur.startswith("ckpt-") and gate < cur)
                        or not self._mgr.is_committed(gate)):
                    with self._ctl_lock:
                        self.gate_holds += 1
                    return False
                target = gate
        try:
            state = self._mgr.restore_for_inference(self._template, target)
        except Exception as e:  # noqa: BLE001 — skip, keep serving
            with self._ctl_lock:
                self.skips += 1
            if self._coordinator is None:
                # single-host: a verified-bad save stays bad — never
                # hot-retried. Under a coordinator the peers already
                # swapped past the shared barrier, so a transient
                # restore failure here (fs lag on a blob) must RETRY
                # next round or this host serves stale params forever
                # while reporting nothing — the exact divergence the
                # coordinator exists to prevent.
                self._skipped.add(target)
            report = "; ".join(self._mgr.last_restore_report) or repr(e)
            self._log(
                f"hot reload: SKIPPING {target} (integrity/restore "
                f"failure: {report}); still serving "
                f"{self._store.version}"
                + ("" if self._coordinator is None
                   else "; will retry next coordinated round")
            )
            if self._telemetry is not None:
                self._telemetry.counter_add("serve_reload_skipped", 1)
            return False
        old = self._store.version
        self._store.swap(state, target)
        with self._ctl_lock:
            self.swaps += 1
        self._log(f"hot reload: swapped params {old} -> {target}")
        if self._telemetry is not None:
            self._telemetry.counter_add("serve_reloads", 1)
        if self._on_swap is not None:
            self._on_swap(target)
        return True

    # ---- the background thread ----

    def start(self) -> "CheckpointWatcher":
        if self._coordinator is not None:
            # coordinated polls are COLLECTIVES: a free-running daemon
            # thread on its own timer enters a blocking collective while
            # its peers sleep (or after one died) and hangs every host.
            # Drive poll_once from a lockstep loop instead — the
            # multihost smoke's probe is the pattern.
            raise ValueError(
                "a coordinated watcher must be driven by lockstep "
                "poll_once() calls, not the background thread "
                "(scripts/multihost_reload_probe.py)"
            )
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="reload-watcher"
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            racecheck.heartbeat()
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — watcher must survive
                self._log(f"hot reload: poll error (will retry): {e!r}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
