"""Thin stdlib HTTP front-end over the in-process InferenceServer.

Deliberately minimal: ``http.server.ThreadingHTTPServer`` + JSON, no
framework dependency (the container bakes in the jax stack, nothing
else). All serving logic — batching, deadlines, backpressure, reload —
lives in server.py; this module only translates wire <-> core:

- ``POST /predict``  body ``{"graph": {...}}`` (featurized arrays:
  atom_fea [N,D], edge_fea [E,G], centers [E], neighbors [E]) or
  ``{"structure": {...}}`` (lattice [3,3], frac_coords [N,3], numbers
  [N]) — the RAW WIRE format (ISSUE 11, ~100x fewer bytes): admitted
  in O(1) and either staged straight into the in-program
  neighbor-search program (response ``"wire": "raw"``) or featurized
  ON THE PACK POOL with the checkpoint's config (``"featurized"``) —
  never synchronously on this handler thread. Response:
  ``{"prediction": [T], "param_version", "latency_ms", "cached",
  "wire", "trace_id", "flush_id", "stamps"}``. An inbound ``X-Request-Id``
  header (or body ``trace_id``) becomes the request's trace id; the
  response echoes it in the ``X-Request-Id`` header and carries the
  monotonic stage stamps (queued/packed/dispatched/fetched/replied) so
  a slow request is attributable to its stage from the client side.
- ``GET /healthz``   liveness AND readiness (ISSUE 14): ``ok`` says
  the process is up; ``ready`` says it can serve at its warm latency —
  200 only once ``warm()`` has compiled the shape set and the server is
  not draining, 503 (+ Retry-After) otherwise. A fleet router keys on
  ``ready``: a warming replica looks alive but would eat traffic into
  cold-compile latency.
- ``GET /stats``     the server's full stats() dict (SLO numbers,
  including the live ``rolling`` window + per-device in-flight depth).
- ``GET /metrics``   Prometheus text exposition from the server's
  export registry (observe/export.py): serve_* counters, device
  gauges (one ``device`` label per chip), pipeline_* counters, and
  rolling-window latency/occupancy summaries — scrape mid-load. With
  the SLO layer on (ISSUE 16) the scrape additionally carries the
  MERGEABLE ``*_hist`` histogram families (latency, queue wait, flush
  occupancy) the router's ``/metrics/fleet`` pools, plus ``slo_*``
  error-budget gauges.
- ``GET /timeseries`` the embedded multi-resolution history
  (observe/tsdb.py): ``?name=<series>&res=<10s|1m|10m>`` returns the
  bounded ring of ``{t, count, sum, min, max, last, mean}`` buckets;
  no ``name`` returns the queryable index.
- ``POST /profile``  bounded on-demand ``jax.profiler`` capture (body
  ``{"duration_ms": 500}``); 409 while one is running (captures are
  rejected, never stacked), 501 when no profile dir was configured.

Rejections map to the HTTP codes clients expect from a loaded service:
429 queue-full (back off), 413 oversize (never retry), 504 deadline
exceeded, 503 draining/warming (retry elsewhere). The backpressure
codes (429, 503) carry a ``Retry-After`` header so well-behaved clients
and the fleet router back off for a concrete interval instead of
hammering a loaded or draining replica.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

import numpy as np

from cgnn_tpu.data.graph import CrystalGraph
from cgnn_tpu.data.rawbatch import RawStructure
from cgnn_tpu.observe.log import bind_trace
from cgnn_tpu.observe.metrics_io import jsonfinite
from cgnn_tpu.observe.tracectx import TRACE_PARENT_HEADER, parse_parent
from cgnn_tpu.resilience import faultinject
from cgnn_tpu.serve.batcher import (
    MALFORMED,
    OVERSIZE,
    QUEUE_FULL,
    SHUTDOWN,
    TIMEOUT,
    ServeRejection,
)
from cgnn_tpu.serve.server import InferenceServer

_REJECT_STATUS = {
    MALFORMED: 400,
    QUEUE_FULL: 429,
    OVERSIZE: 413,
    TIMEOUT: 504,
    SHUTDOWN: 503,
}

# backpressure responses name a concrete back-off (ISSUE 14): a full
# queue clears within a couple of flush intervals (seconds at most); a
# draining replica needs its restart window. 4xx/504 rejections are
# about the REQUEST — retrying them sooner or later changes nothing, so
# they carry no header.
_RETRY_AFTER_S = {
    QUEUE_FULL: 1,
    SHUTDOWN: 5,
}


def graph_from_json(payload: dict) -> CrystalGraph:
    """Rebuild a featurized CrystalGraph from its JSON arrays."""
    try:
        return CrystalGraph(
            atom_fea=np.asarray(payload["atom_fea"], np.float32),
            edge_fea=np.asarray(payload["edge_fea"], np.float32),
            centers=np.asarray(payload["centers"], np.int32),
            neighbors=np.asarray(payload["neighbors"], np.int32),
            target=np.zeros(1, np.float32),
            cif_id=str(payload.get("id", "")),
        )
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed graph payload: {e}") from None


def structure_from_json(payload: dict) -> RawStructure:
    """JSON structure dict -> wire-form RawStructure (ISSUE 11).

    NO featurization happens here — the server decides per request
    whether the structure stages raw (the in-program neighbor search
    builds the graph) or gets featurized on the PACK POOL (never on
    this HTTP thread, so one large structure cannot head-of-line-block
    admission — the old handler featurized synchronously right here)."""
    try:
        return RawStructure(
            np.asarray(payload["frac_coords"], np.float64),
            np.asarray(payload["lattice"], np.float64),
            np.asarray(payload["numbers"], np.int32),
            cif_id=str(payload.get("id", "")),
        )
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed structure payload: {e}") from None


def make_structure_featurizer(data_cfg) -> Callable[[dict], CrystalGraph]:
    """JSON structure dict -> CrystalGraph via the checkpoint's
    featurization config (kept for offline callers; the serving path
    now admits wire-form structures directly — see structure_from_json
    — and featurizes on the pack pool via server.structure_featurizer)."""
    from cgnn_tpu.serve.server import structure_featurizer

    featurize_raw = structure_featurizer(data_cfg)

    def featurize(payload: dict) -> CrystalGraph:
        return featurize_raw(structure_from_json(payload))

    return featurize


def make_handler(server: InferenceServer):
    """Build the request-handler class bound to ``server``.

    No featurizer here (ISSUE 11): wire-form ``structure`` payloads
    admit directly as :class:`RawStructure` and the SERVER owns
    featurization (on the pack pool, when a request can't stage raw)."""

    class ServeHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # quiet: per-request stderr lines are not operator signal under
        # load; telemetry carries the aggregates
        def log_message(self, fmt, *args):  # noqa: ARG002
            pass

        def _reply(self, status: int, payload: dict,
                   headers: dict | None = None) -> None:
            # a NaN prediction must reach the client as null, not as a
            # bare NaN token no strict JSON parser accepts (graftcheck
            # GC-JSONFINITE). The recursive rebuild is the RARE path:
            # allow_nan=False serializes the all-finite common case in
            # one C-level pass and only a ValueError pays for jsonfinite.
            try:
                body = json.dumps(payload, allow_nan=False).encode()
            except ValueError:
                body = json.dumps(jsonfinite(payload)).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, status: int, text: str,
                        content_type: str) -> None:
            body = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path == "/healthz":
                # liveness vs READINESS (ISSUE 14): 200 only when the
                # warm shape set is compiled and the server is taking
                # work — a router must not route traffic into a warming
                # (cold-compile latency) or draining replica. serve.py
                # binds the listener BEFORE warm(), so this signal is
                # real for the whole boot window.
                draining = server.stats()["draining"]
                ready = server.warmed and not draining
                payload = {
                    "ok": True,
                    "ready": ready,
                    "warmed": server.warmed,
                    "draining": draining,
                    "param_version": server.param_store.version,
                    "queue_depth": server.batcher.depth,
                }
                if ready:
                    self._reply(200, payload)
                else:
                    self._reply(503, payload,
                                headers={"Retry-After":
                                         str(_RETRY_AFTER_S[SHUTDOWN])})
            elif self.path == "/stats":
                self._reply(200, server.stats())
            elif self.path == "/metrics":
                # the Prometheus scrape: live registry state, rendered
                # in the text exposition format (version 0.0.4)
                self._reply_text(
                    200, server.registry.prometheus_text(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif self.path.split("?", 1)[0] == "/timeseries":
                # the embedded time-series store (observe/tsdb.py,
                # ISSUE 16): bounded multi-resolution history of every
                # registry scalar — "what was the p99 ten minutes ago"
                # without an external scraper
                self._do_timeseries()
            elif self.path.split("?", 1)[0] == "/trace":
                # the fleet-join surface (ISSUE 15): this process's
                # bounded span ring as a self-describing window —
                # dropped count + retained bounds included, so the
                # joiner can mark truncation instead of rendering a
                # silently partial tree. ?since=<unix-s> for
                # incremental pulls.
                self._do_trace()
            elif self.path == "/flightrec":
                # what a PEER's incident dump pulls: the recent-request
                # ring + live metrics snapshot (observe/flightrec.py)
                if server.flightrec is None:
                    self._reply(501, {
                        "error": "flight recorder not configured "
                                 "(serve.py --flightrec-dir)",
                    })
                else:
                    self._reply(200, server.flightrec.snapshot())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _do_timeseries(self) -> None:
            from urllib.parse import parse_qs, urlsplit

            if server.tsdb is None:
                self._reply(501, {
                    "error": "time-series store disabled "
                             "(serve.py --no-slo)",
                })
                return
            q = parse_qs(urlsplit(self.path).query)
            name = (q.get("name") or [""])[0]
            res = (q.get("res") or ["10s"])[0]
            if not name:
                # no name = the index: what can be queried, at which
                # resolutions, and the store's own bounds/health
                self._reply(200, {
                    "names": server.tsdb.names(),
                    "resolutions": server.tsdb.resolutions(),
                    "stats": server.tsdb.stats(),
                })
                return
            try:
                points = server.tsdb.query(name, res)
            except KeyError as e:
                # a typo'd resolution must 400, not silently return []
                self._reply(400, {"error": str(e)})
                return
            self._reply(200, {"name": name, "res": res,
                              "points": points})

        def _do_trace(self) -> None:
            from cgnn_tpu.observe.trace_join import parse_since_query

            since, err = parse_since_query(self.path)
            if err:
                self._reply(400, {"error": err})
                return
            window = server.trace_window(since_s=since)
            if window is None:
                self._reply(501, {
                    "error": "span ring disabled "
                             "(serve.py --trace-ring 0)",
                })
            else:
                self._reply(200, window)

        def _do_profile(self, payload: dict) -> None:
            from cgnn_tpu.observe.profile import ProfileBusy

            if server.profiler is None:
                self._reply(501, {
                    "error": "profiling not configured "
                             "(serve.py --profile-dir)",
                })
                return
            duration_ms = payload.get("duration_ms")
            try:
                record = server.profiler.capture(
                    None if duration_ms is None
                    else float(duration_ms) / 1000.0
                )
            except ProfileBusy as e:
                self._reply(409, {"error": str(e), "reason": "busy"})
                return
            except Exception as e:  # noqa: BLE001 — report, keep serving
                self._reply(500, {"error": repr(e)})
                return
            self._reply(200, {"ok": True, **record})

        def _do_label(self, payload: dict) -> None:
            # late ground truth -> the label journal's exactly-once
            # join (continual/journal.py, ISSUE 18). 'already' is a 200:
            # a retransmitted label is acknowledged, never re-applied.
            if server.journal is None:
                self._reply(501, {
                    "error": "label journal not configured "
                             "(serve.py --journal)",
                })
                return
            try:
                label = float(payload["label"])
            except (KeyError, TypeError, ValueError) as e:
                self._reply(400, {"error": f"malformed label: {e}"})
                return
            trace_id = payload.get("trace_id")
            fingerprint = payload.get("fingerprint")
            if trace_id is None and fingerprint is None:
                self._reply(400, {
                    "error": "label needs a 'trace_id' or a 'fingerprint'",
                })
                return
            status = server.journal.join(
                label, trace_id=trace_id, fingerprint=fingerprint)
            self._reply(200 if status != "unmatched" else 404,
                        {"status": status})

        def _do_reload_control(self, payload: dict) -> None:
            # canary plane (ISSUE 18): pin this replica to an exact
            # version, or raise its auto-swap gate — the promotion
            # broadcast. Keys absent = untouched; present-null = clear.
            w = server.watcher
            if w is None:
                self._reply(501, {
                    "error": "no reload watcher attached "
                             "(serve.py --reload)",
                })
                return
            try:
                if "pin" in payload:
                    w.set_pin(payload["pin"])
                if "gate" in payload:
                    w.set_gate(payload["gate"])
            except (TypeError, ValueError) as e:
                self._reply(400, {"error": str(e)})
                return
            self._reply(200, w.control())

        def do_POST(self):  # noqa: N802
            # serve-side chaos point (resilience/faultinject.py):
            # close the socket without a response — the way a dying
            # replica presents to a client mid-request. Exercises the
            # fleet router's transport-error retry path. /predict ONLY:
            # the fault contract is "every N-th /predict", and eating a
            # /profile ordinal would both drop the wrong request and
            # shift the advertised cadence.
            if self.path == "/predict" and faultinject.drop_connection():
                self.close_connection = True
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
            except ValueError as e:
                self._reply(400, {"error": f"malformed JSON body: {e}"})
                return
            if self.path == "/profile":
                self._do_profile(payload)
                return
            if self.path == "/label":
                self._do_label(payload)
                return
            if self.path == "/reload-control":
                self._do_reload_control(payload)
                return
            if self.path == "/cache-fill":
                # peer-fill (ISSUE 20): the fleet router replays a row a
                # NON-owner replica computed into this owner's cache.
                # Version-checked at fill time and revalidated at hit
                # time (serve/cache.py) — a stale or malformed fill is
                # reported, never served
                try:
                    filled = server.cache_fill(
                        payload.get("fingerprint", ""),
                        payload.get("prediction", ()),
                        payload.get("param_version", ""),
                        precision=payload.get("precision"),
                        wire=str(payload.get("wire", "featurized")),
                    )
                except (TypeError, ValueError) as e:
                    self._reply(400, {"error": f"malformed fill: {e}"})
                    return
                self._reply(200, {"filled": bool(filled)})
                return
            if self.path != "/predict":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return

            def _preply(status: int, body_payload: dict,
                        headers: dict | None = None) -> None:
                # every /predict status feeds the flight recorder's
                # 5xx burst trigger (a no-op without a recorder)
                server.note_http_status(status)
                self._reply(status, body_payload, headers=headers)

            if not server.warmed:
                # readiness guard: admitting now would either queue the
                # request behind the whole warmup or trace a cold
                # program — both break the latency contract /healthz
                # readiness promises the router
                _preply(503, {
                    "error": "server is warming (shape set compiling)",
                    "reason": SHUTDOWN,
                }, headers={"Retry-After": str(_RETRY_AFTER_S[SHUTDOWN])})
                return
            try:
                if "graph" in payload:
                    graph = graph_from_json(payload["graph"])
                elif "structure" in payload:
                    # wire-form admission: the server stages it raw or
                    # featurizes it on the pack pool — NOT on this
                    # handler thread (the pre-ISSUE-11 head-of-line
                    # blocker)
                    graph = structure_from_json(payload["structure"])
                else:
                    raise ValueError(
                        "payload needs 'graph' (featurized arrays) "
                        "or 'structure' (positions/lattice/numbers)"
                    )
            except ValueError as e:
                _preply(400, {"error": str(e)})
                return
            timeout_ms = payload.get("timeout_ms")
            # per-request tracing: an inbound X-Request-Id (or a body
            # trace_id) becomes the trace id minted at admission; an
            # inbound X-Trace-Parent (or body trace_parent) names the
            # upstream span — the router's attempt — this request's
            # serve.request span nests under in a joined fleet trace
            trace_id = (self.headers.get("X-Request-Id")
                        or payload.get("trace_id"))
            _, trace_parent = parse_parent(
                self.headers.get(TRACE_PARENT_HEADER)
                or payload.get("trace_parent"))
            # edge-computed content hash (ISSUE 20): the router hashed
            # the wire arrays once; the replica only qualifies the key
            fingerprint = (self.headers.get("X-Fingerprint")
                           or payload.get("fingerprint"))
            # bind the inbound trace id as this handler thread's log
            # context: under a fleet, EVERY replica request carries the
            # router's X-Request-Id, so --log-json lines emitted while
            # this thread works (rejection logs, reload messages on
            # this thread) grep by trace id. Worker-thread logs (e.g.
            # a flush failure) are outside this scope by construction.
            with bind_trace(trace_id or ""):
                try:
                    result = server.predict(
                        graph, timeout_ms=timeout_ms, trace_id=trace_id,
                        precision=payload.get("precision"),
                        trace_parent=trace_parent,
                        # priority serving (ISSUE 19): body 'class' (or
                        # the 'priority' alias) + WFQ 'tenant' ride the
                        # fleet transport verbatim; absent keeps the
                        # single-class legacy contract
                        klass=(payload.get("class")
                               or payload.get("priority")),
                        tenant=payload.get("tenant"),
                        fingerprint=fingerprint,
                    )
                except ServeRejection as e:
                    headers = None
                    if e.reason in _RETRY_AFTER_S:
                        headers = {
                            "Retry-After": str(_RETRY_AFTER_S[e.reason])}
                    _preply(_REJECT_STATUS.get(e.reason, 500), {
                        "error": str(e), "reason": e.reason,
                    }, headers=headers)
                    return
                except TimeoutError:
                    _preply(504, {"error": "result wait timed out",
                                  "reason": TIMEOUT})
                    return
                except Exception as e:  # noqa: BLE001 — a failed flush
                    # must surface as a TYPED 500, not a closed socket:
                    # the fleet router retries it on a sibling replica
                    # (the dispatch-exception chaos leg drives exactly
                    # this path)
                    _preply(500, {"error": repr(e),
                                  "reason": "dispatch_failed"})
                    return
            _preply(200, {
                "prediction": result.prediction.tolist(),
                "param_version": result.param_version,
                "latency_ms": result.latency_ms,
                "cached": result.cached,
                "batch_occupancy": result.batch_occupancy,
                "device_id": result.device_id,
                "precision": result.precision,
                "wire": result.wire,
                "trace_id": result.trace_id,
                "flush_id": result.flush_id,
                "stamps": result.stamps,
                "class": result.klass,
                "backfilled": result.backfilled,
                "coalesced": result.coalesced,
            }, headers={"X-Request-Id": result.trace_id})

    return ServeHandler


class _ServeHTTPServer(ThreadingHTTPServer):
    # the stdlib default listen backlog is 5: under a CPU-bound burst
    # (e.g. raw-wire requests whose search competes with the handler
    # threads for cores) the kernel RSTs connection number six instead
    # of queueing it — a spurious transport error the batcher's OWN
    # backpressure (429) should be the one to refuse. 128 matches a
    # production listener; the admission queue stays the real limit.
    request_queue_size = 128


def make_http_server(server: InferenceServer, host: str = "127.0.0.1",
                     port: int = 8437) -> ThreadingHTTPServer:
    """Bind the front-end (call ``.serve_forever()`` on the result;
    ``.shutdown()`` from another thread stops it — the drain path)."""
    return _ServeHTTPServer((host, port), make_handler(server))
