"""Online serving subsystem (ISSUE 3): the production front door.

``predict.py`` covers offline batch jobs; this package serves live
traffic — single-structure requests, coalesced by a deadline-driven
micro-batcher into a FIXED precompiled shape ladder (zero recompiles
after warmup), with bounded-queue backpressure, per-request deadlines,
hot checkpoint reload (zero-drop param swaps between batches), an LRU
result cache, and graceful SIGTERM drain. The core is socket-free
(server.InferenceServer); http.py is the thin stdlib front-end and
../serve.py the entrypoint.
"""

from cgnn_tpu.serve.batcher import (
    CLASSES,
    DEFAULT_CLASS,
    MALFORMED,
    OVERSIZE,
    QUEUE_FULL,
    SHUTDOWN,
    TIMEOUT,
    Flush,
    MicroBatcher,
    Request,
    RequestFuture,
    ServeRejection,
    parse_kv_spec,
)
from cgnn_tpu.serve.cache import ResultCache, structure_fingerprint
from cgnn_tpu.serve.devices import DeviceSet, replicate_state, resolve_devices
from cgnn_tpu.serve.reload import CheckpointWatcher, ParamStore
from cgnn_tpu.serve.server import InferenceServer, ServeResult, load_server
from cgnn_tpu.serve.shapes import BatchShape, ShapeSet, plan_shape_set

__all__ = [
    "BatchShape",
    "CLASSES",
    "CheckpointWatcher",
    "DEFAULT_CLASS",
    "DeviceSet",
    "Flush",
    "InferenceServer",
    "MALFORMED",
    "MicroBatcher",
    "OVERSIZE",
    "ParamStore",
    "QUEUE_FULL",
    "Request",
    "RequestFuture",
    "ResultCache",
    "SHUTDOWN",
    "ServeRejection",
    "ServeResult",
    "ShapeSet",
    "TIMEOUT",
    "load_server",
    "parse_kv_spec",
    "plan_shape_set",
    "replicate_state",
    "resolve_devices",
    "structure_fingerprint",
]
