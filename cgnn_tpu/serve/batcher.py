"""Dynamic micro-batcher: coalesce single-structure requests into
fixed-shape batches under a latency deadline.

The queueing policy in one sentence: FIFO requests accumulate until the
head batch would overflow the LARGEST precompiled shape ("shape-full")
or the OLDEST queued request has waited ``max_wait_ms`` ("deadline"),
whichever comes first — so under load batches run full (throughput) and
under trickle traffic no request waits more than one flush interval
(latency), and in neither case does packing ever leave the warm shape
set (shapes.py), so no request ever waits on a recompile.

Admission control happens at ``offer``:

- bounded queue (``max_queue``): a full queue REJECTS instead of
  buffering unboundedly — the client sees backpressure (HTTP 429) while
  the server keeps serving its current load at its current latency;
- oversize structures (don't fit the largest shape even alone) are
  rejected with the observed sizes — queueing one would wedge the head
  of the FIFO forever;
- a closed (draining) batcher rejects new work but keeps flushing what
  it already accepted — the SIGTERM drain path.

Per-request deadlines are enforced at flush time: a request whose
deadline passed while queued is returned in ``Flush.expired`` (never
packed) so the caller can fail it promptly — serving a reply the client
already gave up on wastes a batch slot.

Everything here is pure host-side data-structure logic with an
injectable clock: the decision core (``poll``) is synchronously testable
with a fake clock; ``next_flush`` adds the blocking condition-variable
loop the server's worker thread runs.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.data.graph import CrystalGraph
from cgnn_tpu.serve.shapes import BatchShape, ShapeSet

# rejection reasons (stable strings: telemetry counter suffixes and HTTP
# error payloads key on them)
QUEUE_FULL = "queue_full"
OVERSIZE = "oversize"
TIMEOUT = "timeout"
SHUTDOWN = "shutdown"
MALFORMED = "malformed"


class ServeRejection(RuntimeError):
    """A request the server declines to process; ``reason`` is one of the
    module-level rejection constants."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(detail or reason)


class RequestFuture:
    """One request's pending result (threading.Event + slot)."""

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._error: BaseException | None = None

    def set_result(self, result) -> None:
        self._result = result
        self._done.set()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None):
        if not self._done.wait(timeout):
            raise TimeoutError("request still pending")
        if self._error is not None:
            raise self._error
        return self._result


@dataclasses.dataclass
class Request:
    """A queued single-structure prediction request."""

    graph: CrystalGraph
    enqueued: float  # monotonic seconds
    deadline: float | None  # absolute monotonic; None = no deadline
    future: RequestFuture = dataclasses.field(default_factory=RequestFuture)
    fingerprint: str | None = None
    # slot budget under the shape set's layout, computed once at admission
    nodes: int = 0
    edges: int = 0
    # can this graph stage compactly (raw distances present + consistent,
    # atom rows in the vocabulary)? Decided ONCE at admission — a flush
    # whose requests are all compactable packs the raw CompactBatch form;
    # any non-compactable member demotes its flush to full-fidelity
    # packing (both programs are warmed, so neither path ever recompiles)
    compactable: bool = False
    # per-request trace identity (minted at admission; an inbound
    # X-Request-Id is honored) + the monotonic per-stage stamps
    # (SpanTracer.now_s clock): queued / packed / dispatched / fetched /
    # replied — the live-observability request journey
    trace_id: str = ""
    stamps: dict = dataclasses.field(default_factory=dict)
    # inbound cross-process span parent (observe/tracectx.py): the
    # upstream attempt span this request's serve.request span nests
    # under in a joined fleet trace; "" when the request arrived with
    # no X-Trace-Parent (this process roots its own tree)
    trace_parent: str = ""
    # precision tier (serve/quantize.py TIERS), validated at admission
    # against the server's warmed set: a flush runs ONE program, so
    # co-batched requests must share a tier — the batcher cuts a flush
    # at every tier boundary in the FIFO (see _take_locked)
    precision: str = "f32"
    # staging form (ISSUE 11): 'feat' = a featurized CrystalGraph (or a
    # wire-form structure the pack stage will featurize on the pool —
    # graph then holds the RawStructure until pack time), 'raw' = staged
    # as a RawBatch for the in-program neighbor search. Like precision,
    # a flush runs ONE program, so the FIFO cuts at form boundaries.
    form: str = "feat"


@dataclasses.dataclass
class Flush:
    """One batcher decision: requests to pack (into ``shape``) plus any
    requests whose deadline expired while queued."""

    requests: list
    shape: BatchShape | None
    expired: list
    reason: str = ""  # 'shape_full' | 'tier_boundary' | 'deadline' | 'drain' | ''
    # batch identity: co-batched requests carry DISTINCT trace ids but
    # share this flush id — the join key between a request's trace and
    # the flush-level pack/dispatch/fetch spans
    flush_id: str = ""
    # per-flush stage stamps (packed/dispatched/fetched), merged into
    # every member request's journey at reply time
    stamps: dict = dataclasses.field(default_factory=dict)
    # the tier every member shares (dispatch picks this tier's program
    # + param variant; serve/quantize.py)
    precision: str = "f32"
    # the staging form every member shares ('feat' | 'raw'; ISSUE 11)
    form: str = "feat"

    def __bool__(self) -> bool:
        return bool(self.requests or self.expired)

    def trace_ids(self) -> list:
        return [r.trace_id for r in self.requests]


class MicroBatcher:
    """Bounded FIFO + the flush policy described in the module docstring."""

    def __init__(
        self,
        shape_set: ShapeSet,
        *,
        max_queue: int = 256,
        max_wait_ms: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        queue_wait_hist=None,
    ):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.shape_set = shape_set
        self.max_queue = max_queue
        self.max_wait = max_wait_ms / 1000.0
        self._clock = clock
        # mergeable queue-wait histogram (observe/hist.py, ISSUE 16):
        # each fired request's enqueue->flush wait lands here at the
        # flush decision, the queueing truth independent of pack/dispatch
        # time downstream. None keeps the hot path untouched.
        self.queue_wait_hist = queue_wait_hist
        self._queue: list[Request] = []
        # a plain Condition normally; instrumented (lock-order + held-by
        # tracking) under CGNN_TPU_RACECHECK=1 — racecheck.make_condition
        # returns threading.Condition() when the gate is off
        self._cond = racecheck.make_condition("serve.batcher")
        self._closed = False
        self._flush_seq = 0

    # ---- admission ----

    def offer(self, request: Request) -> None:
        """Admit or reject (raises ServeRejection; never blocks)."""
        n, e = self.shape_set.graph_counts(request.graph)
        request.nodes, request.edges = n, e
        if not self.shape_set.largest.fits(1, n, e):
            raise ServeRejection(
                OVERSIZE, self.shape_set.oversize_detail(request.graph)
            )
        with self._cond:
            if self._closed:
                raise ServeRejection(SHUTDOWN, "server is draining")
            if len(self._queue) >= self.max_queue:
                raise ServeRejection(
                    QUEUE_FULL,
                    f"request queue at capacity ({self.max_queue})",
                )
            self._queue.append(request)
            self._cond.notify_all()

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    # ---- flush policy ----

    def _take_locked(self, now: float) -> tuple[list, list, bool]:
        """(batchable FIFO prefix, expired, hit-boundary). The _locked
        suffix is the graftcheck GC-LOCKSHARE contract: callers hold
        self._cond.

        A precision-tier change in the FIFO is a batch boundary exactly
        like shape-full: the head tier's prefix fires NOW (one program
        per flush), the next tier starts the next batch — strict FIFO is
        preserved (no reordering around the boundary) and a mixed queue
        degrades to smaller flushes, never to head-of-line blocking.
        A staging-FORM change (featurized vs raw wire, ISSUE 11) is the
        same kind of boundary: raw and featurized flushes run different
        warmed programs."""
        big = self.shape_set.largest
        take: list[Request] = []
        expired: list[Request] = []
        n_nodes = n_edges = 0
        full = False
        boundary = False
        key: tuple | None = None
        for req in self._queue:
            if req.deadline is not None and now >= req.deadline:
                expired.append(req)
                continue
            if key is None:
                key = (req.precision, req.form)
            elif (req.precision, req.form) != key:
                boundary = True  # tier/form cut: fire the head prefix now
                break
            if not big.fits(len(take) + 1, n_nodes + req.nodes,
                            n_edges + req.edges):
                full = True
                break
            take.append(req)
            n_nodes += req.nodes
            n_edges += req.edges
        # graph slots saturated = full even with nothing else queued (a
        # later arrival could never join this batch anyway)
        return (take, expired, full or len(take) >= big.graph_cap,
                boundary)

    def poll(self, now: float | None = None) -> Flush | None:
        """Non-blocking flush decision at time ``now``.

        Returns a Flush when the policy says fire (shape-full, oldest
        waited past ``max_wait``, draining, or deadline expiries need
        delivering), else None. Pure given the clock — the unit-testable
        core of the batcher."""
        now = self._clock() if now is None else now
        with self._cond:
            take, expired, full, boundary = self._take_locked(now)
            waited = (
                take and now - min(r.enqueued for r in take) >= self.max_wait
            )
            if full or boundary or waited or (self._closed and take):
                # tier_boundary gets its own reason: conflating it with
                # shape_full would inflate the ladder-tuning signal with
                # tier-fragmentation flushes (they can be nearly empty)
                reason = ("shape_full" if full
                          else "tier_boundary" if boundary
                          else "deadline" if waited else "drain")
                fired = take
            elif expired:
                # nothing to pack yet, but expiries must not sit until
                # the next natural flush — deliver them now
                reason, fired = "", []
            else:
                return None
            drop = set(map(id, fired)) | set(map(id, expired))
            self._queue = [r for r in self._queue if id(r) not in drop]
            shape = None
            if fired:
                shape = self.shape_set.shape_for(
                    len(fired),
                    sum(r.nodes for r in fired),
                    sum(r.edges for r in fired),
                )
            if self.queue_wait_hist is not None:
                for r in fired:
                    self.queue_wait_hist.observe((now - r.enqueued) * 1e3)
            self._flush_seq += 1
            return Flush(fired, shape, expired, reason,
                         flush_id=f"flush-{self._flush_seq:06d}",
                         precision=(fired[0].precision if fired
                                    else "f32"),
                         form=(fired[0].form if fired else "feat"))

    def next_flush(self) -> Flush | None:
        """Block until the policy fires (worker-thread API).

        Returns None exactly once the batcher is closed AND empty — the
        worker's signal to exit after the drain is complete."""
        while True:
            # ticks every <= max_wait even when idle, so the racecheck
            # deadlock watchdog can tell 'no traffic' from 'wedged'
            racecheck.heartbeat()
            with self._cond:
                if self._closed and not self._queue:
                    return None
                if not self._queue:
                    self._cond.wait(timeout=self.max_wait)
                    continue
                oldest = min(r.enqueued for r in self._queue)
                remaining = self.max_wait - (self._clock() - oldest)
                closed = self._closed  # read under the lock (GC-LOCKSHARE)
            if remaining > 0 and not closed:
                # sleep until the deadline can fire (a new arrival that
                # makes the batch shape-full wakes us early)
                with self._cond:
                    self._cond.wait(timeout=remaining)
            flush = self.poll()
            if flush is not None:
                return flush

    # ---- drain ----

    def close(self) -> None:
        """Stop admitting; queued work still flushes (graceful drain)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed
