"""Priority-class continuous micro-batcher: coalesce single-structure
requests into fixed-shape batches under per-class latency deadlines,
backfilling padding slack with lower-class work.

The queueing policy in one paragraph: requests carry a priority CLASS
(``interactive`` / ``batch`` / ``scavenger``; CLASSES) and accumulate in
one bounded queue. A flush is cut for the HEAD class — the
highest-priority class present, unless a lower class has aged past its
own per-class wait budget (starvation freedom: a scavenger request
cannot sit forever behind a saturated interactive stream). Within the
head class, requests are ordered by weighted fair queuing across
tenants (per-tenant virtual finish times, so one heavy tenant cannot
starve the rest), and the flush fires when the head batch would
overflow the LARGEST precompiled shape ("shape_full"), the head class's
oldest request has waited its class budget ("deadline"), or the head
prefix hits a (class, tier, form) cut boundary ("tier_boundary" — one
program per flush). After the rung is chosen for the head prefix,
BACKFILL (ISSUE 19) fills the rung's remaining graph/node/edge slack
with lower-class requests sharing the head's (tier, form): padded slots
become goodput without delaying the head flush (the rung is already
chosen and fires NOW) and without ever leaving the warm shape set — so
in no case does packing wait on a recompile.

Admission control happens at ``offer``:

- bounded queue (``max_queue``): a full queue REJECTS instead of
  buffering unboundedly — the client sees backpressure (HTTP 429) while
  the server keeps serving its current load at its current latency;
- oversize structures (don't fit the largest shape even alone) are
  rejected with the observed sizes — queueing one would wedge the head
  of the FIFO forever;
- an unknown priority class is MALFORMED — silently mapping it to a
  default would quietly change the request's scheduling contract;
- a closed (draining) batcher rejects new work but keeps flushing what
  it already accepted — the SIGTERM drain path.

Per-request deadlines are enforced at flush time: a request whose
deadline passed while queued is returned in ``Flush.expired`` (never
packed) so the caller can fail it promptly — serving a reply the client
already gave up on wastes a batch slot.

Everything here is pure host-side data-structure logic with an
injectable clock: the decision core (``poll``) is synchronously testable
with a fake clock; ``next_flush`` adds the blocking condition-variable
loop the server's worker thread runs.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.data.graph import CrystalGraph
from cgnn_tpu.serve.shapes import BatchShape, ShapeSet

# rejection reasons (stable strings: telemetry counter suffixes and HTTP
# error payloads key on them)
QUEUE_FULL = "queue_full"
OVERSIZE = "oversize"
TIMEOUT = "timeout"
SHUTDOWN = "shutdown"
MALFORMED = "malformed"

# priority classes (ISSUE 19), rank order = scheduling order (index 0
# preempts index 1, ...). Stable strings: they ride HTTP payloads,
# metric label values, and counter suffixes, so renaming one is a wire
# protocol change.
CLASSES = ("interactive", "batch", "scavenger")
DEFAULT_CLASS = CLASSES[0]
_CLASS_RANK = {c: i for i, c in enumerate(CLASSES)}

# per-class wait budget as a multiple of max_wait when no explicit
# class_max_wait_ms map is given: interactive keeps the legacy flush
# deadline; batch and scavenger trade latency for riding backfill slack
_DEFAULT_WAIT_MULT = {"interactive": 1.0, "batch": 4.0, "scavenger": 16.0}


def parse_kv_spec(spec: str) -> dict[str, float]:
    """Parse a ``"key=float,key=float"`` spec string (class waits, class
    SLOs, tenant weights — the shared flag grammar of serve.py /
    fleet.py / the loadgen). Empty -> {}."""
    out: dict[str, float] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"malformed spec entry {part!r} (want key=value)")
        k, v = part.split("=", 1)
        out[k.strip()] = float(v)
    return out


class ServeRejection(RuntimeError):
    """A request the server declines to process; ``reason`` is one of the
    module-level rejection constants."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(detail or reason)


class RequestFuture:
    """One request's pending result (threading.Event + slot).

    ``add_done_callback`` exists for single-flight miss coalescing
    (server.py): follower requests for an in-flight fingerprint attach
    to the leader's future instead of entering the batcher, and are
    resolved on whichever thread completes the leader — success, error,
    or expiry all fire the callbacks exactly once."""

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._error: BaseException | None = None
        self._cb_lock = threading.Lock()
        self._callbacks: list = []

    def set_result(self, result) -> None:
        self._result = result
        self._done.set()
        self._fire_callbacks()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self._done.set()
        self._fire_callbacks()

    def add_done_callback(self, fn) -> None:
        """``fn(self)`` once this future resolves (immediately if it
        already has); callbacks run on the resolving thread."""
        with self._cb_lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _fire_callbacks(self) -> None:
        with self._cb_lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            fn(self)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None):
        if not self._done.wait(timeout):
            raise TimeoutError("request still pending")
        if self._error is not None:
            raise self._error
        return self._result


@dataclasses.dataclass
class Request:
    """A queued single-structure prediction request."""

    graph: CrystalGraph
    enqueued: float  # monotonic seconds
    deadline: float | None  # absolute monotonic; None = no deadline
    future: RequestFuture = dataclasses.field(default_factory=RequestFuture)
    fingerprint: str | None = None
    # slot budget under the shape set's layout, computed once at admission
    nodes: int = 0
    edges: int = 0
    # can this graph stage compactly (raw distances present + consistent,
    # atom rows in the vocabulary)? Decided ONCE at admission — a flush
    # whose requests are all compactable packs the raw CompactBatch form;
    # any non-compactable member demotes its flush to full-fidelity
    # packing (both programs are warmed, so neither path ever recompiles)
    compactable: bool = False
    # per-request trace identity (minted at admission; an inbound
    # X-Request-Id is honored) + the monotonic per-stage stamps
    # (SpanTracer.now_s clock): queued / packed / dispatched / fetched /
    # replied — the live-observability request journey
    trace_id: str = ""
    stamps: dict = dataclasses.field(default_factory=dict)
    # inbound cross-process span parent (observe/tracectx.py): the
    # upstream attempt span this request's serve.request span nests
    # under in a joined fleet trace; "" when the request arrived with
    # no X-Trace-Parent (this process roots its own tree)
    trace_parent: str = ""
    # precision tier (serve/quantize.py TIERS), validated at admission
    # against the server's warmed set: a flush runs ONE program, so
    # co-batched requests must share a tier — the batcher cuts a flush
    # at every tier boundary in the head prefix (see _take_locked)
    precision: str = "f32"
    # staging form (ISSUE 11): 'feat' = a featurized CrystalGraph (or a
    # wire-form structure the pack stage will featurize on the pool —
    # graph then holds the RawStructure until pack time), 'raw' = staged
    # as a RawBatch for the in-program neighbor search. Like precision,
    # a flush runs ONE program, so the head prefix cuts at form
    # boundaries — with the class, the full cut key is the
    # (class, tier, form) triple (ISSUE 19).
    form: str = "feat"
    # priority class (ISSUE 19, CLASSES): which per-class wait budget
    # and scheduling rank this request rides. The default keeps
    # single-class callers on the legacy FIFO behavior exactly.
    klass: str = DEFAULT_CLASS
    # fair-queuing tenant ("" = the shared anonymous tenant): WFQ
    # ordering within a class is by per-tenant virtual finish time
    tenant: str = ""
    # set by the batcher when this request rode a higher-class flush's
    # padding slack instead of waiting for its own class's cut — it is
    # still answered exactly once under its own trace id, never
    # downgraded (INVARIANTS.md)
    backfilled: bool = False
    # WFQ virtual finish time, stamped at offer() under the queue lock
    vft: float = 0.0


@dataclasses.dataclass
class Flush:
    """One batcher decision: requests to pack (into ``shape``) plus any
    requests whose deadline expired while queued."""

    requests: list
    shape: BatchShape | None
    expired: list
    reason: str = ""  # 'shape_full' | 'tier_boundary' | 'deadline' | 'drain' | ''
    # batch identity: co-batched requests carry DISTINCT trace ids but
    # share this flush id — the join key between a request's trace and
    # the flush-level pack/dispatch/fetch spans
    flush_id: str = ""
    # per-flush stage stamps (packed/dispatched/fetched), merged into
    # every member request's journey at reply time
    stamps: dict = dataclasses.field(default_factory=dict)
    # the tier every member shares (dispatch picks this tier's program
    # + param variant; serve/quantize.py)
    precision: str = "f32"
    # the staging form every member shares ('feat' | 'raw'; ISSUE 11)
    form: str = "feat"
    # the priority class this flush was CUT FOR (ISSUE 19): backfilled
    # lower-class members ride along without changing it — the flush's
    # timing contract belongs to the head class
    klass: str = DEFAULT_CLASS
    # backfill accounting: members that rode padding slack, and the
    # graph-slot slack the chosen rung had before backfill ran (the
    # serve_padding_fill_share numerator/denominator)
    n_backfilled: int = 0
    slack_slots: int = 0

    def __bool__(self) -> bool:
        return bool(self.requests or self.expired)

    def trace_ids(self) -> list:
        return [r.trace_id for r in self.requests]


class MicroBatcher:
    """Bounded priority queue + the flush policy described in the module
    docstring."""

    def __init__(
        self,
        shape_set: ShapeSet,
        *,
        max_queue: int = 256,
        max_wait_ms: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        queue_wait_hist=None,
        class_max_wait_ms: dict | None = None,
        backfill: bool = True,
        wfq_weights: dict | None = None,
    ):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.shape_set = shape_set
        self.max_queue = max_queue
        self.max_wait = max_wait_ms / 1000.0
        self._clock = clock
        # mergeable queue-wait histogram (observe/hist.py, ISSUE 16):
        # each fired request's enqueue->flush wait lands here at the
        # flush decision, the queueing truth independent of pack/dispatch
        # time downstream. None keeps the hot path untouched.
        self.queue_wait_hist = queue_wait_hist
        # per-class wait budget (seconds): explicit ms overrides, else
        # the default multiples of max_wait. An unknown class in the
        # override is a config error, not a silent default.
        self.class_wait = {
            c: self.max_wait * _DEFAULT_WAIT_MULT[c] for c in CLASSES
        }
        for c, ms in (class_max_wait_ms or {}).items():
            if c not in _CLASS_RANK:
                raise ValueError(
                    f"unknown priority class {c!r} in class_max_wait_ms "
                    f"(have: {list(CLASSES)})")
            self.class_wait[c] = float(ms) / 1000.0
        # padding-slack backfill switch (the bench.py --ab backfill leg
        # turns it off for the baseline)
        self.backfill = bool(backfill)
        # WFQ tenant weights (share of service per unit weight); tenants
        # absent from the map get weight 1.0
        self.wfq_weights: dict[str, float] = {}
        for t, w in (wfq_weights or {}).items():
            if float(w) <= 0:
                raise ValueError(
                    f"wfq weight for tenant {t!r} must be > 0, got {w}")
            self.wfq_weights[str(t)] = float(w)
        self._queue: list[Request] = []
        # a plain Condition normally; instrumented (lock-order + held-by
        # tracking) under CGNN_TPU_RACECHECK=1 — racecheck.make_condition
        # returns threading.Condition() when the gate is off
        self._cond = racecheck.make_condition("serve.batcher")
        self._closed = False
        self._flush_seq = 0
        # WFQ virtual time: advances to the largest served finish time;
        # a newly-arriving tenant starts HERE, so idling never banks
        # credit. All mutated under self._cond (GC-LOCKSHARE).
        self._vtime = 0.0
        self._tenant_vft: dict[str, float] = {}
        # lifetime backfill accounting (the serve_padding_fill_share
        # feed): requests that rode slack / graph-slot slack offered
        self._backfilled_total = 0
        self._slack_total = 0

    # ---- admission ----

    def offer(self, request: Request) -> None:
        """Admit or reject (raises ServeRejection; never blocks)."""
        if request.klass not in _CLASS_RANK:
            raise ServeRejection(
                MALFORMED,
                f"unknown priority class {request.klass!r} "
                f"(have: {list(CLASSES)})",
            )
        n, e = self.shape_set.graph_counts(request.graph)
        request.nodes, request.edges = n, e
        if not self.shape_set.largest.fits(1, n, e):
            raise ServeRejection(
                OVERSIZE, self.shape_set.oversize_detail(request.graph)
            )
        with self._cond:
            if self._closed:
                raise ServeRejection(SHUTDOWN, "server is draining")
            if len(self._queue) >= self.max_queue:
                raise ServeRejection(
                    QUEUE_FULL,
                    f"request queue at capacity ({self.max_queue})",
                )
            # WFQ stamp: finish time = max(global vtime, the tenant's
            # last finish) + cost/weight (cost 1 per request — service
            # share is in requests). Same-tenant arrivals chain, so a
            # single tenant degenerates to strict FIFO.
            w = self.wfq_weights.get(request.tenant, 1.0)
            base = max(self._vtime,
                       self._tenant_vft.get(request.tenant, 0.0))
            request.vft = base + 1.0 / w
            self._tenant_vft[request.tenant] = request.vft
            self._queue.append(request)
            self._cond.notify_all()

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def backfilled_total(self) -> int:
        """Requests that rode a higher-class flush's padding slack."""
        with self._cond:
            return self._backfilled_total

    @property
    def slack_total(self) -> int:
        """Graph-slot slack offered to backfill across all flushes."""
        with self._cond:
            return self._slack_total

    # ---- flush policy ----

    def _head_class_locked(self, live: list, now: float) -> str:
        """The class the next flush is cut for: the highest-priority
        class present — unless some class has AGED past its own wait
        budget, in which case the most-overdue class wins (starvation
        freedom: sustained interactive load cannot pin a scavenger
        request forever; once overdue it gets its own flush)."""
        oldest: dict[str, float] = {}
        for r in live:
            if r.klass not in oldest or r.enqueued < oldest[r.klass]:
                oldest[r.klass] = r.enqueued

        def urgency(c: str) -> float:
            return (now - oldest[c]) / max(self.class_wait[c], 1e-9)

        overdue = [c for c in oldest if urgency(c) >= 1.0]
        if overdue:
            # most overdue first; ties break toward the higher class
            return max(overdue,
                       key=lambda c: (urgency(c), -_CLASS_RANK[c]))
        return min(oldest, key=lambda c: _CLASS_RANK[c])

    def _take_locked(self, now: float) -> tuple[list, list, bool, bool]:
        """(head-class batch prefix, expired, shape-full, hit-boundary).
        The _locked suffix is the graftcheck GC-LOCKSHARE contract:
        callers hold self._cond.

        The cut key is the (class, tier, form) TRIPLE (ISSUE 19): the
        head class is chosen first (_head_class_locked), then within it
        requests are walked in WFQ order and a precision-tier or
        staging-form change is a batch boundary exactly like shape-full
        — the head (tier, form) prefix fires NOW (one program per
        flush), the rest starts the next batch. A mixed queue degrades
        to smaller flushes, never to head-of-line blocking; single-class
        single-tenant traffic walks in strict FIFO order, preserving the
        legacy behavior exactly."""
        big = self.shape_set.largest
        expired = [r for r in self._queue
                   if r.deadline is not None and now >= r.deadline]
        dead = set(map(id, expired))
        live = [r for r in self._queue if id(r) not in dead]
        if not live:
            return [], expired, False, False
        head = self._head_class_locked(live, now)
        # WFQ order within the head class (stable sort: equal finish
        # times keep arrival order)
        cand = sorted((r for r in live if r.klass == head),
                      key=lambda r: r.vft)
        take: list[Request] = []
        n_nodes = n_edges = 0
        full = False
        boundary = False
        key: tuple | None = None
        for req in cand:
            if key is None:
                key = (req.precision, req.form)
            elif (req.precision, req.form) != key:
                boundary = True  # tier/form cut: fire the head prefix now
                break
            if not big.fits(len(take) + 1, n_nodes + req.nodes,
                            n_edges + req.edges):
                full = True
                break
            take.append(req)
            n_nodes += req.nodes
            n_edges += req.edges
        # graph slots saturated = full even with nothing else queued (a
        # later arrival could never join this batch anyway)
        return (take, expired, full or len(take) >= big.graph_cap,
                boundary)

    def _backfill_locked(self, fired: list, shape: BatchShape,
                         now: float) -> tuple[int, int]:
        """Fill the chosen rung's remaining graph/node/edge slack with
        LOWER-class queued requests sharing the head's (tier, form)
        (ISSUE 19). The rung was already chosen for the head prefix and
        the flush fires NOW either way, so backfill can only convert
        padding into goodput — never delay the head class, never change
        the shape, never leave the warm set. A candidate that does not
        fit the remaining slack stays queued (a later, smaller one may
        still fit). -> (backfilled count, graph-slot slack offered)."""
        head = fired[0]
        head_rank = _CLASS_RANK[head.klass]
        key = (head.precision, head.form)
        n = len(fired)
        slack = shape.graph_cap - n
        if slack <= 0:
            return 0, 0
        n_nodes = sum(r.nodes for r in fired)
        n_edges = sum(r.edges for r in fired)
        taken = set(map(id, fired))
        cand = [r for r in self._queue
                if id(r) not in taken
                and _CLASS_RANK[r.klass] > head_rank
                and (r.precision, r.form) == key
                and not (r.deadline is not None and now >= r.deadline)]
        # highest class first among the lower ones, WFQ order within
        cand.sort(key=lambda r: (_CLASS_RANK[r.klass], r.vft))
        backfilled = 0
        for r in cand:
            if not shape.fits(n + 1, n_nodes + r.nodes,
                              n_edges + r.edges):
                continue
            r.backfilled = True
            fired.append(r)
            n += 1
            n_nodes += r.nodes
            n_edges += r.edges
            backfilled += 1
            if n >= shape.graph_cap:
                break
        return backfilled, slack

    def poll(self, now: float | None = None) -> Flush | None:
        """Non-blocking flush decision at time ``now``.

        Returns a Flush when the policy says fire (shape-full, head
        class's oldest waited past its class budget, tier/form boundary,
        draining, or deadline expiries need delivering), else None. Pure
        given the clock — the unit-testable core of the batcher."""
        now = self._clock() if now is None else now
        with self._cond:
            take, expired, full, boundary = self._take_locked(now)
            head_wait = (self.class_wait[take[0].klass] if take
                         else self.max_wait)
            waited = (
                take and now - min(r.enqueued for r in take) >= head_wait
            )
            if full or boundary or waited or (self._closed and take):
                # tier_boundary gets its own reason: conflating it with
                # shape_full would inflate the ladder-tuning signal with
                # tier-fragmentation flushes (they can be nearly empty)
                reason = ("shape_full" if full
                          else "tier_boundary" if boundary
                          else "deadline" if waited else "drain")
                fired = take
            elif expired:
                # nothing to pack yet, but expiries must not sit until
                # the next natural flush — deliver them now
                reason, fired = "", []
            else:
                return None
            shape = None
            n_back = slack = 0
            if fired:
                # the rung is chosen for the HEAD prefix; backfill then
                # packs lower-class work into its remaining slack
                # without ever upgrading the rung
                shape = self.shape_set.shape_for(
                    len(fired),
                    sum(r.nodes for r in fired),
                    sum(r.edges for r in fired),
                )
                if self.backfill and shape is not None:
                    n_back, slack = self._backfill_locked(
                        fired, shape, now)
                    self._backfilled_total += n_back
                    self._slack_total += slack
            drop = set(map(id, fired)) | set(map(id, expired))
            self._queue = [r for r in self._queue if id(r) not in drop]
            if self.queue_wait_hist is not None:
                for r in fired:
                    self.queue_wait_hist.observe((now - r.enqueued) * 1e3)
            if fired:
                # advance WFQ virtual time to the largest served finish
                # tag — late-arriving tenants start from here
                self._vtime = max(self._vtime,
                                  max(r.vft for r in fired))
            self._flush_seq += 1
            return Flush(fired, shape, expired, reason,
                         flush_id=f"flush-{self._flush_seq:06d}",
                         precision=(fired[0].precision if fired
                                    else "f32"),
                         form=(fired[0].form if fired else "feat"),
                         klass=(fired[0].klass if fired
                                else DEFAULT_CLASS),
                         n_backfilled=n_back, slack_slots=slack)

    def next_flush(self) -> Flush | None:
        """Block until the policy fires (worker-thread API).

        Returns None exactly once the batcher is closed AND empty — the
        worker's signal to exit after the drain is complete."""
        while True:
            # ticks every <= max_wait even when idle, so the racecheck
            # deadlock watchdog can tell 'no traffic' from 'wedged'
            racecheck.heartbeat()
            with self._cond:
                if self._closed and not self._queue:
                    return None
                if not self._queue:
                    self._cond.wait(timeout=self.max_wait)
                    continue
                # sleep until the soonest event that can fire a flush:
                # a class wait budget elapsing OR a per-request deadline
                # expiring (a lower-class-only queue may legitimately
                # sleep past max_wait; a new arrival that makes the
                # batch shape-full wakes us early via notify)
                next_at = min(
                    r.enqueued + self.class_wait[r.klass]
                    for r in self._queue
                )
                dl = min((r.deadline for r in self._queue
                          if r.deadline is not None), default=None)
                if dl is not None:
                    next_at = min(next_at, dl)
                remaining = next_at - self._clock()
                closed = self._closed  # read under the lock (GC-LOCKSHARE)
            if remaining > 0 and not closed:
                with self._cond:
                    self._cond.wait(timeout=remaining)
            flush = self.poll()
            if flush is not None:
                return flush

    # ---- drain ----

    def close(self) -> None:
        """Stop admitting; queued work still flushes (graceful drain)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed
