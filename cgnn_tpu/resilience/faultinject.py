"""Deterministic fault injection — the test substrate for resilience.

Faults are declared in the ``CGNN_TPU_FAULTS`` environment variable (or
programmatically via ``set_plan``) as ``;``-separated ``key=value``
pairs, and fire at exact, countable points, so every failure a test
provokes is reproducible:

- ``nan_batch=N``    — poison the N-th (0-based) training batch of the
  run with NaN targets (exercises the divergence guard, incl. inside
  the epoch scan);
- ``sigterm_epoch=N``— deliver SIGTERM to this process at the end of
  epoch N (exercises graceful preemption end to end);
- ``crash=POINT:N``  — raise ``InjectedCrash`` at the N-th (1-based)
  hit of the named checkpoint crash point (``after_write`` /
  ``before_commit`` / ``after_commit`` in the checkpoint finalizer);
  append ``:exit`` (``crash=POINT:N:exit``) to die with ``os._exit(137)``
  instead — indistinguishable from ``kill -9`` for the filesystem;
- ``loader_exc=N``   — raise ``InjectedLoaderError`` in place of the
  N-th training batch (exercises producer-thread shutdown).

Serve-side fault points (ISSUE 14 — the chaos substrate the fleet
harness drives; all counted over the SERVING dispatch stream):

- ``dispatch_exc=N[:COUNT]`` — raise ``InjectedDispatchError`` at the
  N-th (0-based) flush dispatch, and with ``:COUNT`` at every dispatch
  in ``[N, N+COUNT)`` (a sustained burst): each flush fails alone, its
  futures get the error, HTTP clients see a typed 500 — the fleet
  router's retry-on-5xx path, and (burst form) the error plateau that
  drives the SLO burn-rate alert end to end (ISSUE 16);
- ``wedge_flush=N[:SECS]`` — stall the N-th flush dispatch for SECS
  (default 600) seconds: the wedged-worker case the bounded
  ``--drain-timeout`` force-exit exists for;
- ``slow_dispatch=MS[:EVERY]`` — add MS milliseconds to every
  EVERY-th (default every) flush dispatch: the degraded-replica case
  the router's health scoring avoids and hedging races;
- ``drop_conn=N``          — close every N-th HTTP ``/predict``
  connection without a response: how a dying replica presents on the
  wire (the router's transport-error retry path).

Fleet autoscale/remediation fault points (ISSUE 17):

- ``boot_crash=N``   — die with ``os._exit(7)`` during warmup for the
  first N boots of this REPLICA, then boot clean: the crash-loop-guard
  pin (``fleet/spawn.py`` restart backoff + give-up cap). Boot counts
  persist across processes in the file named by the
  ``CGNN_TPU_FAULT_STATE`` env var (one appended byte per boot) — a
  crash leaves no in-process state, so the counter cannot;
- ``wedge_warm[=SECS]`` — hang in warm() for SECS (default 600)
  seconds: the listener is up but ``/healthz`` stays not-ready, the
  wedged-boot case ``wait_ready`` timeouts + restart backoff cover;
- ``exit75_at=N``    — deliver SIGTERM to ourselves at the N-th
  (0-based) flush dispatch and exit with the PR-2 resumable code 75
  after the drain: a mid-load preemption, which the fleet must record
  as a SCALE EVENT (breaker untripped, no incident bundle).

With the variable unset every hook is a cheap no-op: ``plan()`` is
``None`` and iterators are returned unwrapped.

``corrupt_checkpoint`` is the host-side half of the harness: it
truncates or bit-flips files of a *committed* save in place, the way
real disk faults present, to drive the restore fallback chain in tests.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Iterable, Iterator

import numpy as np

ENV_VAR = "CGNN_TPU_FAULTS"
# cross-process fault state (ISSUE 17): ``boot_crash`` counts BOOTS,
# and a boot that crashes takes its in-process counters with it — the
# harness points this at a file, each boot appends one byte, and the
# file size is the count that survives the crash
STATE_ENV = "CGNN_TPU_FAULT_STATE"

# serve-side ordinal counters are bumped from concurrent dispatch /
# HTTP-handler threads; the lock keeps "every N-th" exactly every N-th
# (the training-side counters run on one thread and stay lock-free)
_serve_lock = threading.Lock()


class InjectedCrash(RuntimeError):
    """A crash point fired (simulated mid-save process death)."""


class InjectedLoaderError(RuntimeError):
    """An injected data-loader failure."""


class InjectedDispatchError(RuntimeError):
    """An injected serving-dispatch failure (the flush fails alone)."""


@dataclasses.dataclass
class FaultPlan:
    nan_batch: int | None = None
    sigterm_epoch: int | None = None
    crash_point: str | None = None
    crash_hit: int = 1
    crash_exit: bool = False
    loader_exc: int | None = None
    # serve-side faults (ISSUE 14); dispatch_exc_count > 1 turns the
    # one-shot exception into a burst over [dispatch_exc,
    # dispatch_exc + count) — the SLO-alert driver (ISSUE 16)
    dispatch_exc: int | None = None
    dispatch_exc_count: int = 1
    wedge_flush: int | None = None
    wedge_secs: float = 600.0
    slow_dispatch_ms: float | None = None
    slow_every: int = 1
    drop_conn: int | None = None
    # fleet autoscale/remediation faults (ISSUE 17)
    boot_crash: int | None = None
    wedge_warm: float | None = None
    exit75_at: int | None = None
    # continual-learning fault (ISSUE 18): shift the labels of the
    # N-th fine-tune round (1-based) by a constant offset — the
    # deterministic way to make the trainer commit a REGRESSING
    # candidate the canary gate must catch
    label_noise_round: int | None = None
    label_noise_scale: float = 10.0
    # mutable hit counters (the determinism bookkeeping)
    _crash_hits: dict = dataclasses.field(default_factory=dict)
    _batches_seen: int = 0
    _sigterm_fired: bool = False
    _dispatches_seen: int = 0
    _conns_seen: int = 0
    _exit75_fired: bool = False

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        plan = cls()
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            key, _, value = part.partition("=")
            if key == "nan_batch":
                plan.nan_batch = int(value)
            elif key == "sigterm_epoch":
                plan.sigterm_epoch = int(value)
            elif key == "loader_exc":
                plan.loader_exc = int(value)
            elif key == "crash":
                fields = value.split(":")
                plan.crash_point = fields[0]
                if len(fields) > 1 and fields[1]:
                    plan.crash_hit = int(fields[1])
                plan.crash_exit = len(fields) > 2 and fields[2] == "exit"
            elif key == "dispatch_exc":
                fields = value.split(":")
                plan.dispatch_exc = int(fields[0])
                if len(fields) > 1 and fields[1]:
                    plan.dispatch_exc_count = max(1, int(fields[1]))
            elif key == "wedge_flush":
                fields = value.split(":")
                plan.wedge_flush = int(fields[0])
                if len(fields) > 1 and fields[1]:
                    plan.wedge_secs = float(fields[1])
            elif key == "slow_dispatch":
                fields = value.split(":")
                plan.slow_dispatch_ms = float(fields[0])
                if len(fields) > 1 and fields[1]:
                    plan.slow_every = max(1, int(fields[1]))
            elif key == "drop_conn":
                plan.drop_conn = int(value)
            elif key == "boot_crash":
                plan.boot_crash = int(value)
            elif key == "wedge_warm":
                plan.wedge_warm = float(value) if value else 600.0
            elif key == "exit75_at":
                plan.exit75_at = int(value)
            elif key == "label_noise":
                fields = value.split(":")
                plan.label_noise_round = int(fields[0])
                if len(fields) > 1 and fields[1]:
                    plan.label_noise_scale = float(fields[1])
            else:
                raise ValueError(
                    f"unknown fault key {key!r} in {ENV_VAR}={spec!r}"
                )
        return plan

    def describe(self) -> str:
        parts = []
        if self.nan_batch is not None:
            parts.append(f"NaN batch @{self.nan_batch}")
        if self.sigterm_epoch is not None:
            parts.append(f"SIGTERM @epoch {self.sigterm_epoch}")
        if self.crash_point is not None:
            how = "os._exit(137)" if self.crash_exit else "InjectedCrash"
            parts.append(
                f"{how} @{self.crash_point} hit {self.crash_hit}"
            )
        if self.loader_exc is not None:
            parts.append(f"loader exception @batch {self.loader_exc}")
        if self.dispatch_exc is not None:
            if self.dispatch_exc_count > 1:
                parts.append(
                    f"dispatch exceptions @flushes {self.dispatch_exc}.."
                    f"{self.dispatch_exc + self.dispatch_exc_count - 1}"
                )
            else:
                parts.append(
                    f"dispatch exception @flush {self.dispatch_exc}")
        if self.wedge_flush is not None:
            parts.append(
                f"wedge @flush {self.wedge_flush} ({self.wedge_secs:g} s)"
            )
        if self.slow_dispatch_ms is not None:
            parts.append(
                f"+{self.slow_dispatch_ms:g} ms every "
                f"{self.slow_every} dispatch(es)"
            )
        if self.drop_conn is not None:
            parts.append(f"drop every {self.drop_conn}th connection")
        if self.boot_crash is not None:
            parts.append(f"crash first {self.boot_crash} boot(s)")
        if self.wedge_warm is not None:
            parts.append(f"wedge warm() ({self.wedge_warm:g} s)")
        if self.exit75_at is not None:
            parts.append(f"preempt (exit 75) @flush {self.exit75_at}")
        if self.label_noise_round is not None:
            parts.append(
                f"label shift +{self.label_noise_scale:g} @fine-tune "
                f"round {self.label_noise_round}"
            )
        return ", ".join(parts) or "none"


_plan: FaultPlan | None = None
_parsed_env: str | None = None


def set_plan(plan: FaultPlan | None) -> None:
    """Install a plan programmatically (tests); None clears it AND
    re-enables environment-variable parsing (a sticky override would
    silently disable every later env-configured fault in the process)."""
    global _plan, _parsed_env
    _plan = plan
    _parsed_env = "<programmatic>" if plan is not None else None


def plan() -> FaultPlan | None:
    """The active plan (parsed from the environment once), or None."""
    global _plan, _parsed_env
    spec = os.environ.get(ENV_VAR, "")
    if _parsed_env == "<programmatic>":
        return _plan
    if spec != _parsed_env:
        _parsed_env = spec
        _plan = FaultPlan.parse(spec) if spec else None
    return _plan


# ---- hooks (each a no-op without an active plan) ----


def crash_point(name: str) -> None:
    """Die here if the plan says so (checkpoint finalizer instrumentation)."""
    p = plan()
    if p is None or p.crash_point != name:
        return
    hits = p._crash_hits.get(name, 0) + 1
    p._crash_hits[name] = hits
    if hits != p.crash_hit:
        return
    if p.crash_exit:
        os._exit(137)  # the kill -9 twin: no cleanup, no atexit, no flush
    raise InjectedCrash(f"injected crash at {name!r} (hit {hits})")


def maybe_sigterm(epoch: int) -> None:
    """Deliver SIGTERM to ourselves at the configured epoch boundary."""
    p = plan()
    if p is None or p.sigterm_epoch != epoch or p._sigterm_fired:
        return
    p._sigterm_fired = True
    os.kill(os.getpid(), signal.SIGTERM)


def poison_nan(batch):
    """The batch with NaN targets AND NaN node features.

    Targets alone would be a silent no-op for classification (labels go
    through ``astype(int32)``, turning NaN into the valid label 0); NaN
    node features propagate through the network to the loss on every
    task. Node poisoning is skipped for staged forms whose node leaf is
    integral (compact staging stores vocabulary indices) — their float
    targets still carry the fault for the regression tasks compact
    staging supports.
    """
    updates = {"targets": np.full_like(np.asarray(batch.targets), np.nan)}
    nodes = getattr(batch, "nodes", None)
    if nodes is not None and np.issubdtype(
        np.asarray(nodes).dtype, np.floating
    ):
        updates["nodes"] = np.full_like(np.asarray(nodes), np.nan)
    return dataclasses.replace(batch, **updates)


def poison_batches(batches: Iterable) -> Iterator:
    """Wrap a training-batch iterator with the plan's batch faults.

    Counts batches ACROSS epochs/iterators (one counter per run), so
    ``nan_batch=N`` lands mid-scan when the N-th batch falls in a later
    chunk. Returned unwrapped when no batch fault is configured.
    """
    p = plan()
    if p is None or (p.nan_batch is None and p.loader_exc is None):
        return iter(batches)

    def wrapped():
        for b in batches:
            i = p._batches_seen
            p._batches_seen += 1
            if p.loader_exc is not None and i == p.loader_exc:
                raise InjectedLoaderError(
                    f"injected loader failure at batch {i}"
                )
            yield poison_nan(b) if i == p.nan_batch else b

    return wrapped()


def boot_point() -> None:
    """Fleet boot fault point (ISSUE 17), called by serve.py right
    before warm(): the listener is already bound (so /healthz answers,
    not-ready), which is exactly when real warmup deaths happen.

    ``boot_crash=N`` appends one byte to the ``CGNN_TPU_FAULT_STATE``
    file and dies with ``os._exit(7)`` while the file holds <= N bytes
    — so the first N boots crash and the N+1st proceeds, across
    processes. Without a state file every boot crashes (the give-up
    pin). ``wedge_warm`` just hangs here."""
    p = plan()
    if p is None or (p.boot_crash is None and p.wedge_warm is None):
        return
    if p.boot_crash is not None:
        state = os.environ.get(STATE_ENV, "")
        boots = p.boot_crash + 1  # no state file: crash every boot
        if state:
            fd = os.open(state, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
            try:
                os.write(fd, b"b")
            finally:
                os.close(fd)
            boots = os.path.getsize(state)
        if boots <= p.boot_crash:
            os._exit(7)  # mid-warmup death: no cleanup, no drain
    if p.wedge_warm is not None:
        time.sleep(p.wedge_warm)


def exit75_requested() -> bool:
    """True once ``exit75_at`` has fired: serve.py's clean-drain path
    then exits with the PR-2 resumable code 75 instead of 0 — the
    preemption signature the fleet records as a scale event."""
    p = plan()
    return p is not None and p._exit75_fired


def label_noise_for_round(round_idx: int) -> float | None:
    """Label-shift offset for this fine-tune round (continual trainer,
    ISSUE 18), or None when the round is clean. 1-based."""
    p = plan()
    if p is None or p.label_noise_round is None:
        return None
    return (p.label_noise_scale if round_idx == p.label_noise_round
            else None)


def dispatch_point() -> None:
    """Serve-side fault point, called once per flush dispatch (ISSUE
    14). Counts dispatches across the run and fires the configured
    slow/wedge/exception faults at their exact ordinals — a no-op (one
    None check) without a plan."""
    p = plan()
    if p is None or (p.dispatch_exc is None and p.wedge_flush is None
                     and p.slow_dispatch_ms is None
                     and p.exit75_at is None):
        return
    with _serve_lock:  # concurrent per-device dispatch threads
        i = p._dispatches_seen
        p._dispatches_seen += 1
        fire75 = (p.exit75_at is not None and i >= p.exit75_at
                  and not p._exit75_fired)
        if fire75:
            p._exit75_fired = True
    if fire75:
        # a preemption notice mid-load (ISSUE 17): SIGTERM ourselves —
        # the normal graceful drain runs, then serve.py exits 75
        os.kill(os.getpid(), signal.SIGTERM)
    if p.slow_dispatch_ms is not None and i % p.slow_every == 0:
        time.sleep(p.slow_dispatch_ms / 1e3)
    if p.wedge_flush is not None and i == p.wedge_flush:
        time.sleep(p.wedge_secs)
    if (p.dispatch_exc is not None
            and p.dispatch_exc <= i < p.dispatch_exc + p.dispatch_exc_count):
        raise InjectedDispatchError(
            f"injected dispatch failure at flush {i}"
        )


def drop_connection() -> bool:
    """True when the plan says to kill this HTTP connection without a
    response (serve/http.py closes the socket) — every N-th /predict."""
    p = plan()
    if p is None or p.drop_conn is None or p.drop_conn < 1:
        return False
    with _serve_lock:  # concurrent HTTP handler threads
        i = p._conns_seen
        p._conns_seen += 1
    return i % p.drop_conn == p.drop_conn - 1


# ---- host-side corruption (test utility; no plan needed) ----


def corrupt_checkpoint(save_dir: str, mode: str = "garble") -> str:
    """Corrupt a committed save in place; returns the damaged file.

    ``garble`` bit-flips a span in the middle of the largest data file
    (caught by the manifest crc32 even when deserialization succeeds);
    ``truncate`` cuts the largest file in half (deserialization error);
    ``meta`` overwrites ``meta.json`` with non-JSON bytes.
    """
    if mode == "meta":
        path = os.path.join(save_dir, "meta.json")
        with open(path, "w") as f:
            f.write("{not json")
        return path
    largest, size = None, -1
    for root, _, files in os.walk(save_dir):
        for name in files:
            if name in ("meta.json", "MANIFEST.json"):
                continue
            p = os.path.join(root, name)
            s = os.path.getsize(p)
            if s > size:
                largest, size = p, s
    if largest is None:
        raise FileNotFoundError(f"no data files under {save_dir}")
    if mode == "truncate":
        with open(largest, "r+b") as f:
            f.truncate(max(size // 2, 1))
    elif mode == "garble":
        with open(largest, "r+b") as f:
            f.seek(size // 2)
            span = f.read(64) or b"\x00"
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in span))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return largest
