"""Preemption handling: SIGTERM/SIGINT -> graceful checkpoint + resume.

Production schedulers (k8s eviction, TPU preemption notices, slurm)
deliver SIGTERM with a grace window. The handler converts the signal
into a *request* flag that the training loops poll at safe points — the
epoch boundary in the per-step loops, the chunk boundary inside
``ScanEpochDriver._drive`` (a whole-epoch scan can run minutes; chunk
granularity keeps the grace window honored). The loop then saves a
resumable checkpoint, flushes telemetry, and ``train.py`` exits with
``RESUMABLE_EXIT_CODE`` so the scheduler can distinguish "requeue me
with --resume auto" from a real failure.

A second signal restores the default disposition and re-raises it — a
stuck save must not make the process unkillable (and a double Ctrl-C
still interrupts immediately).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable

# EX_TEMPFAIL: "temporary failure, retry" — the conventional sysexits
# code closest to "preempted; resume me", and distinct from both success
# (0) and the argument/data errors train.py already returns (2)
RESUMABLE_EXIT_CODE = 75

_DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptionHandler:
    """Latches termination signals into a pollable checkpoint request."""

    # what a first signal triggers — the log line's action clause;
    # trainers keep the default, the serving drain overrides it
    DEFAULT_ACTION = "checkpoint requested at the next epoch/chunk boundary"

    def __init__(self, log_fn: Callable = print, action: str | None = None):
        self._event = threading.Event()
        self._log = log_fn
        self._action = action or self.DEFAULT_ACTION
        self._installed: dict[int, object] = {}
        self._signal_no: int | None = None
        self._callbacks: list[Callable] = []
        self.requested_at: float | None = None

    # ---- the flag the training loops poll ----

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def add_callback(self, fn: Callable) -> None:
        """Run ``fn()`` once when a request latches — for consumers with
        no natural poll point (the serving drain kicks its batcher shut
        the moment SIGTERM lands instead of waiting out a poll interval).
        Callbacks fire from the latching thread (usually the signal
        handler on the main thread), so they must be quick and non-raising
        — set a flag, close a queue; never block on the work itself."""
        self._callbacks.append(fn)

    def request(self, signum: int | None = None) -> None:
        """Latch a checkpoint-and-exit request (signal handlers and the
        fault injector call this; tests may call it directly)."""
        if not self._event.is_set():
            self.requested_at = time.monotonic()
            self._signal_no = signum
            self._event.set()
            for fn in self._callbacks:
                try:
                    fn()
                except Exception as e:  # noqa: BLE001 — never mask the latch
                    self._log(f"preemption callback failed: {e!r}")

    # ---- signal plumbing ----

    def _on_signal(self, signum, frame):  # noqa: ARG002 — signal API
        if self._event.is_set():
            # second signal: stop being graceful — restore the default
            # disposition and re-deliver so the process dies now
            self._log(
                f"second signal {signal.Signals(signum).name}: exiting "
                f"immediately (graceful checkpoint abandoned)"
            )
            self.uninstall()
            signal.raise_signal(signum)
            return
        self._log(
            f"{signal.Signals(signum).name} received: {self._action} "
            f"(send again to exit now)"
        )
        self.request(signum)

    def install(self, signals=_DEFAULT_SIGNALS) -> "PreemptionHandler":
        """Install handlers (main thread only — signal module rule)."""
        for sig in signals:
            self._installed[sig] = signal.signal(sig, self._on_signal)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._installed.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # non-main thread / teardown
                pass
        self._installed.clear()

    @classmethod
    def installed(cls, log_fn: Callable = print) -> "PreemptionHandler":
        return cls(log_fn=log_fn).install()


def resumable_exit(log_fn: Callable = print) -> int:
    """Log the resume instructions and return the resumable exit code."""
    log_fn(
        f"preempted: resumable checkpoint saved — rerun with "
        f"--resume auto (exit code {RESUMABLE_EXIT_CODE}, pid {os.getpid()})"
    )
    return RESUMABLE_EXIT_CODE
