"""Fault-tolerant training subsystem (ISSUE 2; ROADMAP production scale).

PR 1 built the eyes (``cgnn_tpu.observe`` per-step NaN/grad-health
telemetry); this package is the reflexes — detect a fault, recover to a
known-good state, keep training without a human in the loop. Four
cooperating layers:

- **integrity**: per-leaf shape/dtype/checksum manifests — the commit
  marker and verification substrate for crash-safe checkpoints
  (``train.checkpoint.CheckpointManager`` writes/verifies them).
- **preempt**: SIGTERM/SIGINT -> checkpoint at the next epoch boundary
  (next chunk boundary inside the epoch scan), flush telemetry, exit
  with the distinct resumable code ``RESUMABLE_EXIT_CODE`` so schedulers
  can requeue with ``--resume auto``.
- **guard**: in-graph divergence guard — non-finite updates are skipped
  ON DEVICE (a ``jnp.where`` select of old-vs-new state, safe inside the
  donated-carry epoch scans; trajectory bit-identical when no fault
  fires), plus a host-side monitor that rolls back to the last good
  checkpoint with an LR cut after too many skipped steps.
- **faultinject**: deterministic, env-gated injection of the faults the
  layers above must survive — corrupted/truncated checkpoints, NaN
  batches, loader exceptions, mid-run SIGTERM, mid-save crashes. The
  test substrate for all of the above.
"""

from cgnn_tpu.resilience.guard import (
    DivergenceError,
    DivergenceMonitor,
    guard_step,
    scale_updates,
)
from cgnn_tpu.resilience.integrity import (
    IntegrityError,
    read_manifest,
    tree_manifest,
    verify_tree,
    write_manifest,
)
from cgnn_tpu.resilience.preempt import RESUMABLE_EXIT_CODE, PreemptionHandler

__all__ = [
    "DivergenceError",
    "DivergenceMonitor",
    "IntegrityError",
    "PreemptionHandler",
    "RESUMABLE_EXIT_CODE",
    "guard_step",
    "read_manifest",
    "scale_updates",
    "tree_manifest",
    "verify_tree",
    "write_manifest",
]
