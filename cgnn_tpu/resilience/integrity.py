"""Checkpoint integrity manifests: per-leaf shape/dtype/checksum.

A manifest is a small JSON sidecar describing every leaf of a saved
pytree. It serves two roles in ``train.checkpoint.CheckpointManager``:

- **commit marker**: the manifest is written LAST inside a save's temp
  directory, immediately before the atomic rename — a directory without
  one is an uncommitted (crashed) save and is never offered for restore;
- **verification**: on restore, the restored tree's leaves are checked
  against the manifest (shape, dtype, crc32 of the raw bytes), so silent
  on-disk corruption falls through to the next checkpoint in the
  fallback chain instead of resuming training from garbage.

Checksums are crc32 over the C-contiguous raw bytes — cheap relative to
the orbax (de)serialization either side of it, and enough to catch the
truncation/bit-rot class (this is corruption detection, not crypto).
"""

from __future__ import annotations

import json
import os
import zlib

import jax
import numpy as np

MANIFEST_NAME = "MANIFEST.json"
_FORMAT = 1


class IntegrityError(Exception):
    """A restored tree does not match its manifest."""


def _key_name(key) -> str:
    """Container-kind-agnostic key label: a typed optax/flax tree and its
    orbax raw-dict round trip must yield the SAME leaf paths (keystr
    renders a NamedTuple field as ``.trace`` but its deserialized dict
    twin as ``['trace']``, which would fail every structure-free
    verification)."""
    for attr in ("name", "key", "idx"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def _leaf_entries(tree) -> list[tuple[str, np.ndarray]]:
    """(path, host array) per leaf, in deterministic flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [
        ("/".join(_key_name(k) for k in path), np.asarray(leaf))
        for path, leaf in flat
    ]


def _checksum(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def tree_manifest(tree) -> dict:
    """Manifest dict for a (host-localized) pytree."""
    return {
        "format": _FORMAT,
        "leaves": {
            path: {
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "crc32": _checksum(arr),
            }
            for path, arr in _leaf_entries(tree)
        },
    }


def write_manifest(directory: str, manifest: dict) -> str:
    """Write ``MANIFEST.json`` into ``directory``, fsynced so a crash
    immediately after the enclosing atomic rename cannot leave a
    committed save with a torn manifest."""
    path = os.path.join(directory, MANIFEST_NAME)
    with open(path, "w") as f:
        # crc/shape/dtype entries are finite by construction — fail
        # LOUDLY on a NaN rather than commit an unparseable marker
        # (graftcheck GC-JSONFINITE)
        json.dump(manifest, f, indent=1, allow_nan=False)
        f.flush()
        os.fsync(f.fileno())
    return path


def read_manifest(directory: str) -> dict | None:
    """The directory's manifest, or None when absent/unparseable (an
    uncommitted or corrupted save — callers treat both the same)."""
    path = os.path.join(directory, MANIFEST_NAME)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(manifest, dict) or "leaves" not in manifest:
        return None
    return manifest


def verify_tree(tree, manifest: dict) -> None:
    """Raise IntegrityError unless every leaf matches the manifest.

    Checks leaf set, shapes, dtypes, and crc32 — the full end-to-end
    integrity of the restore (disk bytes AND the deserialization path).
    """
    entries = dict(_leaf_entries(tree))
    expected = manifest["leaves"]
    missing = sorted(set(expected) - set(entries))
    extra = sorted(set(entries) - set(expected))
    if missing or extra:
        raise IntegrityError(
            f"leaf set mismatch: missing={missing[:4]} extra={extra[:4]}"
        )
    for path, arr in entries.items():
        want = expected[path]
        if list(arr.shape) != list(want["shape"]):
            raise IntegrityError(
                f"{path}: shape {list(arr.shape)} != saved {want['shape']}"
            )
        if str(arr.dtype) != want["dtype"]:
            raise IntegrityError(
                f"{path}: dtype {arr.dtype} != saved {want['dtype']}"
            )
        crc = _checksum(arr)
        if crc != want["crc32"]:
            raise IntegrityError(
                f"{path}: crc32 {crc} != saved {want['crc32']} "
                f"(on-disk corruption)"
            )
