"""Divergence recovery: skip bad updates on device, roll back on blowup.

Two layers, built on the in-graph grad-health indicators of
``observe.health``:

**In-graph skip** (``guard_step``): wraps any ``(state, batch) ->
(state, metrics)`` train body. After the inner update it counts
non-finite elements across the new params/batch-stats (plus the step's
loss) and selects old-vs-new state with ``jnp.where`` — a pure in-graph
select, so it works inside the donated-carry whole-epoch scans and
under ``shard_map`` (the inputs to the check are replicated post-pmean
values, so every shard takes the same branch). When no fault fires the
select is the identity and the training trajectory is BIT-identical to
the unguarded body (pinned by tests/test_resilience.py, like the
telemetry tap). A skipped step leaves ``state.step`` unchanged and
zeroes its metric contributions (count included), and reports
``guard_skipped_sum``/``_count`` through the normal metric plumbing —
visible per-step at ``--telemetry step`` and in every epoch aggregate.

**Host rollback** (``DivergenceMonitor``): watches the per-epoch skip
count; when the guard keeps firing (K or more skipped steps in one
epoch — repeated divergence, not a transient bad batch) it restores the
last good checkpoint through the manager's fallback chain, cuts the
learning rate (``scale_updates`` — wraps ``tx.update`` without touching
the optimizer *state* structure, so checkpoints stay structurally
compatible across rollbacks at the cost of one retrace), and retries,
bounded by ``max_rollbacks``.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from cgnn_tpu.observe.health import nonfinite_count


class DivergenceError(RuntimeError):
    """Training diverged and the bounded rollback retries are exhausted."""


def guard_step(body: Callable) -> Callable:
    """Wrap a train body so non-finite updates are skipped on device."""

    def guarded(state, batch):
        new_state, metrics = body(state, batch)
        bad = nonfinite_count(new_state.params)
        bad = bad + nonfinite_count(new_state.batch_stats)
        if "loss_sum" in metrics:
            bad = bad + (
                ~jnp.isfinite(jnp.asarray(metrics["loss_sum"], jnp.float32))
            ).astype(jnp.float32)
        ok = bad == 0

        def keep(new, old):
            return jnp.where(ok, new, old)

        def select(new, old):
            return jax.tree_util.tree_map(keep, new, old)

        out_state = new_state.replace(
            # step stays put on a skip: the retried-batch rng fold_in and
            # the lr schedule see a trajectory without the bad step
            step=keep(new_state.step, state.step),
            params=select(new_state.params, state.params),
            batch_stats=select(new_state.batch_stats, state.batch_stats),
            opt_state=select(new_state.opt_state, state.opt_state),
        )
        okf = ok.astype(jnp.float32)
        # zero the skipped step's metric sums AND counts (a NaN loss must
        # not poison the epoch aggregate; where, not multiply — NaN*0=NaN)
        metrics = {
            k: jnp.where(ok, v, jnp.zeros_like(v)) for k, v in metrics.items()
        }
        metrics["guard_skipped_sum"] = 1.0 - okf
        metrics["guard_skipped_count"] = jnp.float32(1.0)
        return out_state, metrics

    return guarded


def scale_updates(tx: optax.GradientTransformation,
                  factor: float) -> optax.GradientTransformation:
    """``tx`` with its emitted updates scaled by ``factor``.

    Unlike ``optax.chain(tx, optax.scale(f))`` this leaves the optimizer
    STATE structure untouched — checkpoints saved before and after an LR
    cut stay mutually restorable (the fallback chain depends on that).
    The factor is baked into the closure: swapping it retraces the step,
    which is fine for an event as rare as a rollback.
    """

    def update(updates, opt_state, params=None):
        updates, opt_state = tx.update(updates, opt_state, params)
        return (
            jax.tree_util.tree_map(lambda u: u * factor, updates),
            opt_state,
        )

    return optax.GradientTransformation(tx.init, update)


class DivergenceMonitor:
    """Epoch-level watchdog: rollback-with-LR-cut on sustained divergence.

    ``observe(state, epoch, train_m) -> (state, rolled_back)`` is called
    once per epoch by the fit loops with the epoch's aggregated train
    metrics. An epoch is *bad* when its training loss is non-finite
    (guard off or overwhelmed) or when ``max_skips`` or more steps were
    skipped by the in-graph guard. ``post_restore`` re-places restored
    state for the caller's topology (data-parallel loops pass a
    replicate function).
    """

    def __init__(self, ckpt, max_skips: int = 3, lr_cut: float = 0.5,
                 max_rollbacks: int = 3, log_fn: Callable = print,
                 post_restore: Callable | None = None):
        if max_skips < 1:
            raise ValueError(f"max_skips must be >= 1, got {max_skips}")
        if not 0.0 < lr_cut < 1.0:
            raise ValueError(f"lr_cut must be in (0, 1), got {lr_cut}")
        self.ckpt = ckpt
        self.max_skips = max_skips
        self.lr_cut = lr_cut
        self.max_rollbacks = max_rollbacks
        self.rollbacks = 0
        self.lr_scale = 1.0
        self.post_restore = post_restore
        self._log = log_fn
        self._base_tx = None
        # incident hook (ISSUE 15): called with a reason string after
        # every rollback — wire a FlightRecorder.trigger here and the
        # postmortem bundle (metrics window + recent telemetry) dumps
        # at the exact epoch training went off the rails
        self.on_rollback: Callable | None = None

    def _is_bad(self, train_m: dict) -> tuple[bool, str]:
        loss = train_m.get("loss", float("nan"))
        if not math.isfinite(loss):
            return True, f"non-finite train loss {loss}"
        skipped = round(
            train_m.get("guard_skipped", 0.0) * train_m.get("steps", 0)
        )
        if skipped >= self.max_skips:
            return True, (
                f"{skipped} steps skipped by the divergence guard "
                f"(threshold {self.max_skips})"
            )
        return False, ""

    def meta(self) -> dict:
        """Progress to persist in every checkpoint meta: the LR cut and
        retry budget must survive a preemption requeue, or a resumed run
        restarts at the full-strength LR that caused the divergence and
        the rollback budget resets on every requeue (an unbounded
        diverge -> rollback -> preempt loop)."""
        return {
            "guard_lr_scale": self.lr_scale,
            "guard_rollbacks": self.rollbacks,
        }

    def resume_from_meta(self, state, meta: dict):
        """Reapply persisted rollback progress after a resume -> state
        (with the LR cut re-baked into ``state.tx`` when one was active).
        The inverse of ``meta()``; train.py calls this on --resume."""
        self.rollbacks = int(meta.get("guard_rollbacks", 0))
        scale = float(meta.get("guard_lr_scale", 1.0))
        if scale >= 1.0:
            return state
        self._base_tx = state.tx
        self.lr_scale = scale
        self._log(
            f"divergence guard: resumed with lr x{scale:g} and "
            f"{self.rollbacks}/{self.max_rollbacks} rollbacks spent"
        )
        return state.replace(tx=scale_updates(self._base_tx, scale))

    def observe(self, state, epoch: int, train_m: dict):
        bad, why = self._is_bad(train_m)
        if not bad:
            return state, False
        if self._base_tx is None:
            self._base_tx = state.tx
        if self.rollbacks >= self.max_rollbacks:
            raise DivergenceError(
                f"epoch {epoch}: {why}; {self.rollbacks} rollbacks already "
                f"spent (max {self.max_rollbacks}) — giving up"
            )
        if not self.ckpt.exists("latest"):
            self._log(
                f"divergence guard: epoch {epoch} diverged ({why}) but no "
                f"checkpoint exists yet to roll back to — continuing"
            )
            return state, False
        restored, meta = self.ckpt.restore(state)
        self.rollbacks += 1
        self.lr_scale *= self.lr_cut
        restored = restored.replace(
            tx=scale_updates(self._base_tx, self.lr_scale)
        )
        if self.post_restore is not None:
            restored = self.post_restore(restored)
        self._log(
            f"divergence guard: epoch {epoch} diverged ({why}) — rolled "
            f"back to checkpoint epoch {meta.get('epoch', '?')} with lr x"
            f"{self.lr_scale:g} (rollback {self.rollbacks}/"
            f"{self.max_rollbacks})"
        )
        if self.on_rollback is not None:
            try:
                self.on_rollback(f"epoch {epoch}: {why}")
            except Exception:  # noqa: BLE001 — an incident hook must
                pass           # never break the recovery it records
        return restored, True
