"""Consistent-hash ring mapping result-cache fingerprints to owner replicas.

The fleet's per-replica ``ResultCache`` LRUs (serve/cache.py) historically
replicated the same hot keyset N times: effective fleet capacity stayed at
1x no matter how far the autoscaler scaled out.  This ring partitions the
fingerprint keyspace across replicas so each key has exactly one *owner*
and the N LRUs compose into one fleet cache with ~Nx effective capacity
(ROADMAP open item 4).

Contract (also pinned in INVARIANTS.md):

- **Ownership is an optimization, never a correctness dependency.**  The
  router *prefers* the healthy owner; a breaker-open, draining, or dead
  owner falls back to the ordinary load-aware pick and the response stays
  bit-exact.  Nothing in the serving path may assume the owner answered.
- **Determinism across restarts.**  Virtual-node hash points derive only
  from the replica id and vnode index (``blake2b("rid:i")``), never from
  object identity, boot time, or randomness — a restarted process rebuilds
  the identical ring, so re-ownership after a crash is reproducible.
- **Incremental rebalance.**  ``add(rid)`` / ``remove(rid)`` insert or
  delete only that replica's vnode points; only keys on the moved arcs
  change owner.  Autoscale events therefore invalidate ~1/N of the
  keyspace, not all of it.

Stdlib-only; thread-safe via a single named lock (GC-LOCKSHARE).
"""

from __future__ import annotations

import bisect
import hashlib

from cgnn_tpu.analysis import racecheck

# 64 vnodes/replica keeps the max-arc imbalance under ~20% for small
# fleets (3-8 replicas) while the ring stays tiny (N*64 ints)
DEFAULT_VNODES = 64


def _point(data: str) -> int:
    """64-bit hash point for a vnode label or a fingerprint key."""
    return int.from_bytes(
        hashlib.blake2b(data.encode("utf-8"), digest_size=8).digest(), "big")


class CacheRing:
    """Consistent-hash ring: fingerprint -> owner replica id.

    All mutable state (``_points``, ``_rids``) is guarded by ``_lock``.
    """

    def __init__(self, rids=(), vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self._vnodes = int(vnodes)
        self._lock = racecheck.make_lock("fleet.cachering")
        self._points: list[tuple[int, int]] = []  # sorted (hash, rid)
        self._rids: set[int] = set()
        for rid in rids:
            self.add(rid)

    @staticmethod
    def _vnode_points(rid: int, vnodes: int) -> list[tuple[int, int]]:
        # label depends only on (rid, i): deterministic across restarts
        return [(_point(f"{rid}:{i}"), rid) for i in range(vnodes)]

    def add(self, rid: int) -> None:
        """Insert ``rid``'s vnodes; keys on the new arcs re-own to it."""
        rid = int(rid)
        with self._lock:
            if rid in self._rids:
                return
            self._rids.add(rid)
            for pt in self._vnode_points(rid, self._vnodes):
                bisect.insort(self._points, pt)

    def remove(self, rid: int) -> None:
        """Delete ``rid``'s vnodes; its arcs re-own to ring successors."""
        rid = int(rid)
        with self._lock:
            if rid not in self._rids:
                return
            self._rids.discard(rid)
            self._points = [p for p in self._points if p[1] != rid]

    def owner(self, key: str, alive=None):
        """Owner rid for a fingerprint key, or None on an empty ring.

        ``alive`` (an optional rid set) makes the walk health-aware: the
        first clockwise vnode whose replica is in ``alive`` owns the key
        — so a crashed owner's arcs re-own DETERMINISTICALLY to their
        ring successors while it is down, and revert (same determinism)
        the moment it probes healthy again. An empty intersection
        returns None (the caller falls back to ordinary routing)."""
        with self._lock:
            if not self._points:
                return None
            h = _point(key)
            # first point clockwise from h (wrap to points[0])
            i = bisect.bisect_right(self._points, (h, -1))
            n = len(self._points)
            for step in range(n):
                rid = self._points[(i + step) % n][1]
                if alive is None or rid in alive:
                    return rid
            return None

    def members(self) -> list[int]:
        with self._lock:
            return sorted(self._rids)

    def __contains__(self, rid: int) -> bool:
        with self._lock:
            return int(rid) in self._rids

    def __len__(self) -> int:
        with self._lock:
            return len(self._rids)

    def stats(self) -> dict:
        """Membership + per-replica arc share (fraction of hash space)."""
        with self._lock:
            points = list(self._points)
            rids = sorted(self._rids)
        share = {rid: 0.0 for rid in rids}
        if points:
            span = float(2 ** 64)
            for i, (h, rid) in enumerate(points):
                prev = points[i - 1][0] if i else points[-1][0] - 2 ** 64
                share[rid] += (h - prev) / span
        return {
            "replicas": rids,
            "vnodes": self._vnodes,
            "points": len(points),
            "arc_share": {str(r): round(s, 4) for r, s in share.items()},
        }
