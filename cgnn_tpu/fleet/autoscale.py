"""SLO-driven elastic autoscaling over the replica fleet (ISSUE 17).

The control loop ROADMAP item 5 asks for: the router's scraped signal
plane (fleet queue depth, rolling p99 vs the SLO objective, error-budget
burn rates, shed rate) already says when the fleet is too small or too
big — this module closes the loop and grows/shrinks the routed replica
set through ``fleet/spawn.py``.

Two layers, split exactly like the batcher (serve/batcher.py):

- :class:`AutoscalePolicy` — the PURE decision core. ``poll(now,
  signals)`` takes an explicit clock value and a :class:`ScaleSignals`
  snapshot and returns a :class:`ScaleDecision` (or None), with
  hysteresis built in: separate up/down thresholds (queue depth per
  ready replica must exceed ``up_queue_per_replica`` to grow but fall
  below the LOWER ``down_queue_per_replica`` to shrink), a cooldown
  between actions, a sustain window before any scale-down, and hard
  min/max bounds. A shed is the strongest signal there is — capacity
  was REFUSED — so a shed-rate increase bypasses the up-cooldown: the
  autoscaler must never sit out a cooldown while requests bounce.
  Deterministic and lock-free; tests drive it with a fake clock.

- :class:`Autoscaler` — the runtime. Owns the replica processes, keeps
  a **warm pool** of ``warm_target`` spares booted and warm()-compiled
  but NOT routed (serve.py binds its listener before warming, so a
  pool replica is fully compiled and /healthz-ready while invisible to
  the router) — scale-up is then a routing-table add that hides the
  multi-second warmup entirely. Scale-down picks the least-loaded
  routed replica (``pick_victim``), SIGTERM-drains it, and reaps it
  only after the drain answered everything; the router classifies the
  draining exit as a *scale event*, never an incident.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Sequence

from cgnn_tpu.analysis import racecheck


@dataclasses.dataclass(frozen=True)
class ScaleSignals:
    """One poll's snapshot of the router's signal plane.

    ``queue_depth`` is fleet-total pending work (router-view in-flight
    plus every replica's scraped serve_queue_depth); ``shed`` is the
    CUMULATIVE fleet_shed counter (the policy differentiates it);
    ``burn_fast``/``burn_slow`` are the worst burn rates across the
    router's SLO objectives (0 with the SLO layer off)."""

    replicas: int = 0          # routed replica count
    ready: int = 0             # of those, ready + admittable-ish
    draining: int = 0          # routed but draining (scale-down victims)
    warm_pool: int = 0         # booted + warmed, NOT routed
    queue_depth: float = 0.0
    p99_ms: float = 0.0        # router-measured fleet rolling p99
    shed: int = 0              # cumulative fleet_shed
    burn_fast: float = 0.0
    burn_slow: float = 0.0


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    action: str                # "up" | "down"
    reason: str
    urgent: bool = False       # True = the shed path (cooldown bypassed)


class AutoscalePolicy:
    """The pure decision core; see the module docstring.

    All state lives on this object and mutates only inside ``poll`` —
    callers serialize polls (the Autoscaler loop does; tests are
    single-threaded), so no lock is needed here."""

    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: int = 8,
        up_queue_per_replica: float = 2.0,
        down_queue_per_replica: float = 0.5,
        up_p99_ms: float = 0.0,        # 0 disables the latency trigger
        up_burn: float = 0.0,          # 0 disables the burn-rate trigger
        cooldown_up_s: float = 5.0,
        cooldown_down_s: float = 10.0,
        down_sustain_s: float = 10.0,
        warm_target: int = 1,
    ):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas {max_replicas} < min_replicas {min_replicas}")
        if down_queue_per_replica >= up_queue_per_replica:
            # the hysteresis band: equal thresholds would flap
            raise ValueError(
                f"down_queue_per_replica ({down_queue_per_replica}) must be "
                f"< up_queue_per_replica ({up_queue_per_replica})")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_queue_per_replica = float(up_queue_per_replica)
        self.down_queue_per_replica = float(down_queue_per_replica)
        self.up_p99_ms = float(up_p99_ms)
        self.up_burn = float(up_burn)
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)
        self.down_sustain_s = float(down_sustain_s)
        self.warm_target = int(warm_target)
        self._last_action_t: float | None = None
        self._last_shed: int | None = None
        self._quiet_since: float | None = None

    # ---- the decision ----

    def poll(self, now: float, signals: ScaleSignals) -> ScaleDecision | None:
        """One control tick: -> ScaleDecision or None (hold)."""
        s = signals
        # shed DELTA since the last poll: cumulative counters don't
        # re-trigger forever on one old incident
        if self._last_shed is None:
            self._last_shed = s.shed
        shed_delta = s.shed - self._last_shed
        self._last_shed = s.shed

        routed = s.replicas
        if routed < self.min_replicas:
            # bounds repair beats every cooldown: below min is broken
            self._note_action(now)
            return ScaleDecision("up", "below_min_replicas", urgent=True)

        reasons = []
        per_ready = s.queue_depth / max(s.ready, 1)
        if per_ready >= self.up_queue_per_replica:
            reasons.append(f"queue {per_ready:.1f}/replica")
        if self.up_p99_ms > 0 and s.p99_ms >= self.up_p99_ms:
            reasons.append(f"p99 {s.p99_ms:.0f}ms")
        if (self.up_burn > 0 and s.burn_fast >= self.up_burn
                and s.burn_slow >= self.up_burn):
            reasons.append(f"burn {s.burn_fast:.1f}/{s.burn_slow:.1f}")
        urgent = shed_delta > 0
        if urgent:
            reasons.append(f"shed +{shed_delta}")

        if reasons:
            self._quiet_since = None
            if routed >= self.max_replicas:
                return None  # at the bound: shedding is now legitimate
            if urgent or self._cooled(now, self.cooldown_up_s):
                self._note_action(now)
                return ScaleDecision("up", ", ".join(reasons),
                                     urgent=urgent)
            return None

        # ---- the calm path: consider shrinking ----
        calm = per_ready <= self.down_queue_per_replica
        if not calm or routed - s.draining <= self.min_replicas:
            self._quiet_since = None
            return None
        if self._quiet_since is None:
            self._quiet_since = now
            return None
        if (now - self._quiet_since >= self.down_sustain_s
                and self._cooled(now, self.cooldown_down_s)):
            self._note_action(now)
            self._quiet_since = None
            return ScaleDecision(
                "down", f"idle {per_ready:.2f}/replica for "
                        f"{self.down_sustain_s:g}s")
        return None

    def _cooled(self, now: float, cooldown_s: float) -> bool:
        return (self._last_action_t is None
                or now - self._last_action_t >= cooldown_s)

    def _note_action(self, now: float) -> None:
        self._last_action_t = now

    # ---- warm-pool accounting ----

    def pool_deficit(self, signals: ScaleSignals) -> int:
        """How many spares the warm pool is short. Bounded so pool +
        routed never exceeds max_replicas — spares that could never be
        routed are wasted compile time."""
        headroom = max(0, self.max_replicas - signals.replicas)
        return max(0, min(self.warm_target, headroom) - signals.warm_pool)

    # ---- victim selection ----

    @staticmethod
    def pick_victim(replicas: Sequence) -> int | None:
        """The least-loaded routed replica (by ReplicaState.score():
        in-flight + scraped queue depth, tie-broken by scraped p99 then
        rid); already-draining replicas are never re-picked, nor is a
        canary mid-evaluation (ISSUE 18: draining the canary would
        silently abort the candidate's gate window). None when nothing
        qualifies."""
        candidates = [r for r in replicas
                      if not r.stats()["draining"]
                      and not getattr(r, "canary", False)]
        if not candidates:
            return None
        return min(candidates, key=lambda r: r.score()).rid

    def stats(self) -> dict:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "warm_target": self.warm_target,
            "up_queue_per_replica": self.up_queue_per_replica,
            "down_queue_per_replica": self.down_queue_per_replica,
            "last_action_t": self._last_action_t,
        }


def signals_from_router(router, warm_pool: int = 0) -> ScaleSignals:
    """Snapshot the router's signal plane into a ScaleSignals — the
    production signal provider (tests inject fakes)."""
    replicas = router.replica_list()
    queue = 0.0
    ready = draining = 0
    for r in replicas:
        s = r.stats()
        queue += float(s["queue_depth"]) + float(s["inflight"])
        ready += bool(s["ready"] and not s["draining"])
        draining += bool(s["draining"])
    q = router.rolling_latency()
    burn_fast = burn_slow = 0.0
    if router.slo is not None:
        for obj in router.slo.state().get("objectives", {}).values():
            for rule in obj.get("rules", {}).values():
                burn_fast = max(burn_fast, float(rule.get("burn_fast", 0.0)))
                burn_slow = max(burn_slow, float(rule.get("burn_slow", 0.0)))
    return ScaleSignals(
        replicas=len(replicas),
        ready=ready,
        draining=draining,
        warm_pool=warm_pool,
        queue_depth=queue,
        p99_ms=float(q.get("p99", 0.0)) if q else 0.0,
        shed=router.count("fleet_shed"),
        burn_fast=burn_fast,
        burn_slow=burn_slow,
    )


class Autoscaler:
    """The runtime around the policy: warm pool, process lifecycle,
    routing-table adds/removes. See the module docstring.

    ``factory(rid) -> proc`` builds one replica process handle (the
    production factory wraps fleet.spawn.ReplicaProcess on the next
    free port); ``state_factory(rid, base_url) -> ReplicaState`` builds
    the router-side state for a newly routed replica. Both injectable —
    tests drive the whole runtime with fakes and a fake clock."""

    def __init__(
        self,
        router,
        policy: AutoscalePolicy,
        factory: Callable,
        state_factory: Callable,
        *,
        procs: dict | None = None,
        next_rid: int = 0,
        poll_interval_s: float = 1.0,
        boot_timeout_s: float = 300.0,
        drain_timeout_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        log_fn: Callable = print,
    ):
        self.router = router
        self.policy = policy
        self.factory = factory
        self.state_factory = state_factory
        self.poll_interval_s = float(poll_interval_s)
        self.boot_timeout_s = float(boot_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._clock = clock
        self._log = log_fn
        self._lock = racecheck.make_lock("fleet.autoscale")
        # all below mutated under self._lock (graftcheck GC-LOCKSHARE)
        self.procs: dict = dict(procs or {})   # rid -> proc (ever owned)
        self.pool: list = []                   # [(rid, proc)] warm spares
        self.events: list = []                 # the action journal
        self.counts = {"scale_ups": 0, "scale_downs": 0, "boots": 0,
                       "boot_failures": 0, "pool_refills": 0}
        self._next_rid = int(next_rid)
        self._downs_inflight: set = set()
        self._stop = threading.Event()
        self._loop_thread: threading.Thread | None = None
        self._refill_thread: threading.Thread | None = None
        self._down_threads: list = []
        self._t0 = clock()

    # ---- lifecycle ----

    def start(self) -> "Autoscaler":
        if self._loop_thread is None or not self._loop_thread.is_alive():
            self._stop.clear()
            self._loop_thread = threading.Thread(
                target=self._loop, daemon=True, name="fleet-autoscale")
            self._loop_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=30.0)
        with self._lock:
            down = list(self._down_threads)
            refill = self._refill_thread
        for t in down:
            t.join(timeout=self.drain_timeout_s + 30.0)
        if refill is not None:
            refill.join(timeout=self.boot_timeout_s + 30.0)

    def shutdown(self, drain_timeout_s: float | None = None) -> dict:
        """Stop the loop and SIGTERM-drain EVERYTHING this autoscaler
        owns (routed + pool); -> {rid: exit_code}."""
        self.stop()
        timeout = (self.drain_timeout_s if drain_timeout_s is None
                   else float(drain_timeout_s))
        with self._lock:
            procs = dict(self.procs)
        return {rid: p.terminate(timeout_s=timeout)
                for rid, p in procs.items()}

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            racecheck.heartbeat()
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self._log(f"autoscale: tick failed: {e!r}")

    # ---- one control tick ----

    def tick(self, now: float | None = None) -> ScaleDecision | None:
        now = self._clock() if now is None else now
        with self._lock:
            pool_n = len(self.pool)
        signals = signals_from_router(self.router, warm_pool=pool_n)
        self._replenish_pool(signals)
        decision = self.policy.poll(now, signals)
        if decision is None:
            return None
        if decision.action == "up":
            self.scale_up(decision.reason)
        elif decision.action == "down":
            self.scale_down(decision.reason)
        return decision

    # ---- warm pool ----

    def _boot_one(self) -> tuple | None:
        """Boot + warm one spare; -> (rid, proc) or None. The crash-loop
        guard lives in spawn.boot_with_retries — a replica that dies
        during boot retries with exponential backoff, bounded."""
        from cgnn_tpu.fleet.spawn import boot_with_retries

        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self.counts["boots"] += 1
        proc = self.factory(rid)
        if not boot_with_retries(proc, wait_ready_s=self.boot_timeout_s,
                                 log_fn=self._log):
            with self._lock:
                self.counts["boot_failures"] += 1
            self._event("boot_failed", rid, "gave up after restart backoff")
            return None
        with self._lock:
            self.procs[rid] = proc
        return rid, proc

    def _replenish_pool(self, signals: ScaleSignals) -> None:
        """Keep the warm pool at target, one boot in flight at a time
        (a pool refill must never become its own respawn storm)."""
        if self.policy.pool_deficit(signals) <= 0:
            return
        with self._lock:
            if (self._refill_thread is not None
                    and self._refill_thread.is_alive()):
                return
            t = threading.Thread(target=self._refill_one, daemon=True,
                                 name="fleet-autoscale-refill")
            self._refill_thread = t
        t.start()

    def _refill_one(self) -> bool:
        pair = self._boot_one()
        if pair is None:
            return False
        with self._lock:
            self.pool.append(pair)
            self.counts["pool_refills"] += 1
        self._event("pool_add", pair[0], "warm spare ready")
        return True

    def prewarm(self, count: int | None = None) -> int:
        """Synchronously fill the warm pool to ``count`` (default: the
        policy's warm_target) BEFORE load starts — the deterministic
        boot the smoke legs use so the first scale-up is a routing-table
        add, never a cold boot racing the ramp. Returns spares added;
        stops early on a boot failure."""
        want = self.policy.warm_target if count is None else int(count)
        added = 0
        while True:
            with self._lock:
                have = len(self.pool)
            if have >= want or not self._refill_one():
                break
            added += 1
        return added

    # ---- scale up: routing-table add ----

    def scale_up(self, reason: str = "") -> int | None:
        """Route one more replica; -> its rid (None on boot failure).
        Prefers a warm-pool spare (instant: it is already compiled and
        /healthz-ready) and falls back to a cold boot."""
        with self._lock:
            pair = self.pool.pop(0) if self.pool else None
        if pair is None:
            pair = self._boot_one()  # cold fallback: slower, still grows
            if pair is None:
                return None
        rid, proc = pair
        state = self.state_factory(rid, proc.base_url)
        try:
            state.probe(timeout_s=5.0)  # routed WITH a routing signal
        except Exception:  # noqa: BLE001 — the poller re-probes anyway
            pass
        self.router.add_replica(state)
        with self._lock:
            self.counts["scale_ups"] += 1
        self._event("scale_up", rid, reason)
        self._log(f"autoscale: scale UP -> replica{rid} routed "
                  f"({reason or 'manual'})")
        return rid

    # ---- scale down: drain, then reap ----

    def scale_down(self, reason: str = "") -> int | None:
        """Pick the least-loaded victim and drain it off the fleet; ->
        its rid (None when nothing qualifies). The drain runs on its
        own thread: SIGTERM -> the replica answers everything it
        accepted -> exit 0 -> the router logs a SCALE EVENT (the
        draining flag it advertised makes the disappearance
        classifiable), and only then is the process reaped."""
        with self._lock:
            exclude = set(self._downs_inflight)
        candidates = [r for r in self.router.replica_list()
                      if r.rid not in exclude]
        victim = self.policy.pick_victim(candidates)
        if victim is None:
            return None
        with self._lock:
            proc = self.procs.get(victim)
            if proc is None or victim in self._downs_inflight:
                return None
            self._downs_inflight.add(victim)
            t = threading.Thread(
                target=self._drain_victim, args=(victim, proc, reason),
                daemon=True, name=f"fleet-autoscale-drain-{victim}")
            self._down_threads.append(t)
        t.start()
        return victim

    def _drain_victim(self, rid: int, proc, reason: str) -> None:
        try:
            # mark intent router-side FIRST: even a drain that finishes
            # inside one probe interval is then classified a scale
            # event, never an incident
            self.router.begin_drain(rid)
            code = proc.terminate(timeout_s=self.drain_timeout_s)
            # idempotent: the health poller usually removed it already
            # when the draining replica stopped answering probes
            self.router.remove_replica(rid, reason="scale_down")
            with self._lock:
                self.counts["scale_downs"] += 1
            self._event("scale_down", rid,
                        f"{reason or 'manual'} (exit {code})")
            self._log(f"autoscale: scale DOWN -> replica{rid} drained "
                      f"(exit {code}; {reason or 'manual'})")
        finally:
            with self._lock:
                self._downs_inflight.discard(rid)

    def proc_for(self, rid: int):
        """The process handle this autoscaler owns for ``rid`` (None
        for externally-spawned replicas) — the remediator's reap path."""
        with self._lock:
            return self.procs.get(rid)

    # ---- bookkeeping ----

    def _event(self, action: str, rid: int, reason: str) -> None:
        with self._lock:
            self.events.append({
                "t_s": round(self._clock() - self._t0, 3),
                "action": action, "replica": rid, "reason": reason,
            })

    def stats(self) -> dict:
        with self._lock:
            return {
                "policy": self.policy.stats(),
                "counts": dict(self.counts),
                "warm_pool": [rid for rid, _ in self.pool],
                "owned": sorted(self.procs),
                "events": list(self.events),
            }
