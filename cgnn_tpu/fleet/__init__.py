"""Fleet-scale resilient serving (ISSUE 14; ROADMAP item 4b).

A thin router process fronting N independent ``InferenceServer``
replicas — the layer where the per-process reflexes PRs 2/6/7 built
(graceful drain, hot reload, the live /healthz + /metrics plane)
compose into a system that stays up when a replica dies:

- :mod:`breaker`  — per-replica circuit breaker (eject after K
  consecutive failures, half-open probe re-admission);
- :mod:`replica`  — one replica's client-side state: health snapshot
  scraped from ITS /healthz + /metrics, in-flight depth, rolling
  latency, the transport that actually carries a request;
- :mod:`router`   — health/load-aware dispatch with bounded retries
  (exponential backoff + jitter), deadline-aware hedging, an
  idempotency key (the PR-6 trace id) shared by every attempt so a
  retried/hedged request is answered exactly once, and graceful
  degradation (503 + Retry-After) when nothing is admittable;
- :mod:`http`     — the stdlib HTTP front-end over the router;
- :mod:`spawn`    — replica subprocess lifecycle (the serve.py boot),
  incl. the kill -9 / restart legs the chaos harness drives, plus the
  crash-loop-guarded supervised boot (``boot_with_retries``);
- :mod:`autoscale` — the self-driving control loop (ISSUE 17): an
  SLO-signal-driven decision core grows/shrinks the routed set with
  hysteresis, a warm pool hides warmup latency, and drained exits are
  scale events, never incidents;
- :mod:`remediate` — flight-recorder-driven auto-remediation:
  replace-and-drain on wedge evidence, every action journaled with
  the bundle that justified it, rate-limited against respawn storms.
"""

from cgnn_tpu.fleet.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    ScaleDecision,
    ScaleSignals,
    signals_from_router,
)
from cgnn_tpu.fleet.breaker import CircuitBreaker
from cgnn_tpu.fleet.remediate import RemediationPolicy, Remediator
from cgnn_tpu.fleet.replica import (
    FleetTransportError,
    ReplicaState,
    http_transport,
)
from cgnn_tpu.fleet.router import FleetRouter
from cgnn_tpu.fleet.spawn import (
    ReplicaProcess,
    RestartBackoff,
    boot_with_retries,
    spawn_fleet,
)

__all__ = [
    "Autoscaler",
    "AutoscalePolicy",
    "CircuitBreaker",
    "FleetRouter",
    "FleetTransportError",
    "RemediationPolicy",
    "Remediator",
    "ReplicaProcess",
    "ReplicaState",
    "RestartBackoff",
    "ScaleDecision",
    "ScaleSignals",
    "boot_with_retries",
    "http_transport",
    "signals_from_router",
    "spawn_fleet",
]
