"""Fleet-scale resilient serving (ISSUE 14; ROADMAP item 4b).

A thin router process fronting N independent ``InferenceServer``
replicas — the layer where the per-process reflexes PRs 2/6/7 built
(graceful drain, hot reload, the live /healthz + /metrics plane)
compose into a system that stays up when a replica dies:

- :mod:`breaker`  — per-replica circuit breaker (eject after K
  consecutive failures, half-open probe re-admission);
- :mod:`replica`  — one replica's client-side state: health snapshot
  scraped from ITS /healthz + /metrics, in-flight depth, rolling
  latency, the transport that actually carries a request;
- :mod:`router`   — health/load-aware dispatch with bounded retries
  (exponential backoff + jitter), deadline-aware hedging, an
  idempotency key (the PR-6 trace id) shared by every attempt so a
  retried/hedged request is answered exactly once, and graceful
  degradation (503 + Retry-After) when nothing is admittable;
- :mod:`http`     — the stdlib HTTP front-end over the router;
- :mod:`spawn`    — replica subprocess lifecycle (the serve.py boot),
  incl. the kill -9 / restart legs the chaos harness drives.
"""

from cgnn_tpu.fleet.breaker import CircuitBreaker
from cgnn_tpu.fleet.replica import (
    FleetTransportError,
    ReplicaState,
    http_transport,
)
from cgnn_tpu.fleet.router import FleetRouter
from cgnn_tpu.fleet.spawn import ReplicaProcess, spawn_fleet

__all__ = [
    "CircuitBreaker",
    "FleetRouter",
    "FleetTransportError",
    "ReplicaProcess",
    "ReplicaState",
    "http_transport",
    "spawn_fleet",
]
