"""One replica, as the router sees it.

``ReplicaState`` is pure client-side bookkeeping: the health snapshot
the poller scraped from the replica's OWN ``/healthz`` + ``/metrics``
plane (readiness, draining, param version, queue depth, rolling p99 —
the PR-6 surfaces, reused as the routing signal), the router-local
in-flight depth (requests this router has outstanding there), the
breaker, and a rolling latency window of what this router measured.

The transport is injectable: production uses :func:`http_transport`
(urllib against ``POST /predict``); unit tests inject fakes that fail,
stall, or refuse deterministically. A transport returns
``(status, payload_dict)`` for anything that produced an HTTP response
(including 4xx/5xx) and raises :class:`FleetTransportError` when the
wire itself failed (refused/reset/timeout) — the distinction the retry
policy keys on.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Callable

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.fleet.breaker import CircuitBreaker
from cgnn_tpu.observe.export import RollingSeries, parse_prometheus_text


class FleetTransportError(RuntimeError):
    """The wire failed before an HTTP response existed (connection
    refused/reset, socket timeout) — the retryable-by-definition case:
    a dead or mid-restart replica presents exactly like this."""


def http_transport(replica: "ReplicaState", body: dict,
                   timeout_s: float) -> tuple[int, dict]:
    """POST ``body`` to the replica's /predict; -> (status, payload).

    HTTP error statuses are RETURNED (the payload carries the replica's
    typed rejection reason); only wire-level failures raise."""
    data = json.dumps(body, allow_nan=False).encode()
    headers = {"Content-Type": "application/json",
               "X-Request-Id": str(body.get("trace_id", ""))}
    if body.get("trace_parent"):
        # cross-process span nesting (ISSUE 15): the router's attempt
        # span id rides to the replica, whose serve.request span
        # records it as its parent — the joined-trace tree edge
        headers["X-Trace-Parent"] = str(body["trace_parent"])
    if body.get("fingerprint"):
        # edge-computed content hash (ISSUE 20): hashed ONCE at the
        # router; the replica qualifies this key instead of re-hashing
        headers["X-Fingerprint"] = str(body["fingerprint"])
    req = urllib.request.Request(
        replica.base_url + "/predict", data=data, headers=headers,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read())
        except Exception:  # noqa: BLE001 — non-JSON error body
            payload = {"error": str(e)}
        return e.code, payload
    except (urllib.error.URLError, ConnectionError, OSError,
            TimeoutError) as e:
        raise FleetTransportError(
            f"{replica.name}: {e!r}"
        ) from None


def http_get_json(url: str, timeout_s: float = 2.0) -> tuple[int, dict]:
    """GET a JSON endpoint (the /healthz probe); raises
    FleetTransportError on wire failure."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:  # noqa: BLE001 — non-JSON error body
            return e.code, {}
    except (urllib.error.URLError, ConnectionError, OSError,
            TimeoutError) as e:
        raise FleetTransportError(f"{url}: {e!r}") from None


def http_post_json(url: str, body: dict,
                   timeout_s: float = 5.0) -> tuple[int, dict]:
    """POST a JSON body to a control endpoint (the canary plane's
    /reload-control and /label); -> (status, payload). HTTP error
    statuses are returned; wire failures raise FleetTransportError."""
    data = json.dumps(body, allow_nan=False).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:  # noqa: BLE001 — non-JSON error body
            return e.code, {}
    except (urllib.error.URLError, ConnectionError, OSError,
            TimeoutError) as e:
        raise FleetTransportError(f"{url}: {e!r}") from None


def http_get_text(url: str, timeout_s: float = 2.0) -> str:
    """GET a text endpoint (the /metrics scrape)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.read().decode()
    except (urllib.error.URLError, ConnectionError, OSError,
            TimeoutError) as e:
        raise FleetTransportError(f"{url}: {e!r}") from None


class ReplicaState:
    """Router-side state for one replica endpoint."""

    def __init__(
        self,
        rid: int,
        base_url: str,
        *,
        breaker: CircuitBreaker | None = None,
        breaker_k: int = 3,
        breaker_cooldown_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        rolling_window_s: float = 60.0,
        probe_backoff_base_s: float = 1.0,
        probe_backoff_max_s: float = 30.0,
    ):
        self.rid = int(rid)
        self.base_url = base_url.rstrip("/")
        self.name = f"replica{self.rid}"
        self.breaker = breaker or CircuitBreaker(
            k=breaker_k, cooldown_s=breaker_cooldown_s, clock=clock,
            name=f"fleet.breaker.{self.rid}",
        )
        self._clock = clock
        self._lock = racecheck.make_lock(f"fleet.replica.{self.rid}")
        # router-measured success latencies (ms); own internal lock
        self.rolling = RollingSeries(window_s=rolling_window_s,
                                     clock=clock)
        # all below mutated under self._lock (graftcheck GC-LOCKSHARE)
        self._inflight = 0
        self._ready = False          # last probed readiness
        # the HEALTH plane's own readiness, untouched by the dispatch
        # path: note_result clears _ready on a transport timeout (the
        # replica must stop looking pickable NOW), which means at
        # breaker-trip time _ready is always False — so the wedge
        # signature (health plane fine, dispatch plane failing) keys
        # on THIS flag, which only probes write (ISSUE 17)
        self._probe_ready = False
        self._draining = False
        self._drain_intent = False   # router-side, sticky (ISSUE 17)
        # canary pin (ISSUE 18): this replica is evaluating a candidate
        # version — out of the client-traffic rotation (shadow traffic
        # only), but NOT a drain: the poller must keep classifying it
        # healthy and the autoscaler must not pick it as a victim
        self._canary = False
        self._version = ""           # last probed param_version
        self._queue_depth = 0.0      # scraped serve_queue_depth
        self._scraped_p99_ms = 0.0   # scraped rolling p99
        self._probe_ok = False       # last probe reached the replica
        self._probes = 0
        # health-poller backoff (ISSUE 17): an unreachable replica's
        # probe interval doubles up to the bound and resets on first
        # success, so a dead replica costs one probe timeout at a
        # widening cadence instead of one per poll round
        self._probe_backoff_base_s = float(probe_backoff_base_s)
        self._probe_backoff_max_s = float(probe_backoff_max_s)
        self._probe_backoff_s = 0.0  # 0 = no backoff (reachable)
        self._next_probe_at = 0.0    # clock time the next probe is due
        self.counts: dict[str, int] = {
            "sent": 0, "answered": 0, "transport_errors": 0,
            "server_errors": 0, "rejections": 0,
        }

    # ---- health (the poller writes, the picker reads) ----

    def note_probe(self, *, ready: bool, draining: bool = False,
                   version: str = "", queue_depth: float | None = None,
                   p99_ms: float | None = None) -> None:
        with self._lock:
            self._probe_ok = True
            self._probes += 1
            self._ready = bool(ready)
            self._probe_ready = bool(ready)
            self._draining = bool(draining)
            if version:
                self._version = str(version)
            if queue_depth is not None:
                self._queue_depth = float(queue_depth)
            if p99_ms is not None:
                self._scraped_p99_ms = float(p99_ms)
            self._probe_backoff_s = 0.0
            self._next_probe_at = 0.0
        if ready and not draining:
            # half-open probe re-admission: a restarted replica that
            # reports ready is probed back into rotation
            self.breaker.record_probe_success()

    def note_unreachable(self) -> None:
        with self._lock:
            self._probe_ok = False
            self._probes += 1
            self._ready = False
            self._probe_ready = False
            # NOTE: _draining survives unreachability on purpose — a
            # drained replica's final disappearance must still read as
            # planned (the router's scale-event classification)
            self._probe_backoff_s = (
                self._probe_backoff_base_s if self._probe_backoff_s <= 0
                else min(self._probe_backoff_s * 2.0,
                         self._probe_backoff_max_s))
            self._next_probe_at = self._clock() + self._probe_backoff_s

    def note_draining(self) -> None:
        """Router-side drain intent (ISSUE 17): the autoscaler marks
        its victim BEFORE the SIGTERM goes out, so the poller
        classifies the eventual disappearance as a scale event even
        when the drain finishes inside one probe interval. Sticky: a
        probe landing before the SIGTERM (the replica not yet aware it
        is draining) must not clear the intent."""
        with self._lock:
            self._drain_intent = True

    def note_canary(self, on: bool) -> None:
        """Mark/unmark this replica as the canary under evaluation
        (ISSUE 18). Separate from drain intent on purpose: a canary is
        healthy and stays probed — it just takes no client traffic."""
        with self._lock:
            self._canary = bool(on)

    @property
    def canary(self) -> bool:
        with self._lock:
            return self._canary

    def probe_due(self) -> bool:
        """Whether the health poller should spend a probe on this
        replica this round (always true while reachable; on unreachable
        replicas, only once per backoff interval)."""
        with self._lock:
            if self._probe_backoff_s <= 0:
                return True
            return self._clock() >= self._next_probe_at

    def probe(self, timeout_s: float = 2.0) -> bool:
        """One health round against the live replica: GET /healthz
        (readiness, draining, version) + GET /metrics (queue depth,
        rolling p99 — the PR-6 plane as the routing signal). Returns
        readiness; an unreachable replica is marked not ready."""
        try:
            status, health = http_get_json(self.base_url + "/healthz",
                                           timeout_s)
        except FleetTransportError:
            self.note_unreachable()
            return False
        queue_depth = p99 = None
        try:
            fams = parse_prometheus_text(
                http_get_text(self.base_url + "/metrics", timeout_s))
            for labels, value in fams.get(
                    "cgnn_serve_queue_depth", {}).get("samples", []):
                queue_depth = value
            for labels, value in fams.get(
                    "cgnn_serve_latency_ms", {}).get("samples", []):
                if 'quantile="0.99"' in labels:
                    p99 = value
        except (FleetTransportError, ValueError):
            pass  # health alone still counts; the signal just goes stale
        ready = bool(health.get("ready", status == 200))
        self.note_probe(
            ready=ready and status == 200,
            draining=bool(health.get("draining", False)),
            version=str(health.get("param_version", "")),
            queue_depth=queue_depth, p99_ms=p99,
        )
        return ready

    # ---- the request path ----

    def note_sent(self) -> None:
        with self._lock:
            self._inflight += 1
            self.counts["sent"] += 1

    def note_result(self, outcome: str, latency_ms: float | None = None,
                    version: str = "") -> None:
        """``outcome``: 'answered' | 'rejections' | 'server_errors' |
        'transport_errors'. Releases the in-flight slot and feeds the
        breaker (server/transport errors are failures; an answered OR
        typed-rejected request proves the replica alive)."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self.counts[outcome] = self.counts.get(outcome, 0) + 1
            if version:
                self._version = str(version)
            if outcome == "transport_errors":
                # a dead replica must stop looking pickable before the
                # next poll round gets around to probing it
                self._ready = False
        if outcome in ("transport_errors", "server_errors"):
            self.breaker.record_failure()
        else:
            self.breaker.record_success()
        if outcome == "answered" and latency_ms is not None:
            self.rolling.add(latency_ms)

    # ---- scoring ----

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def ready(self) -> bool:
        with self._lock:
            return (self._ready
                    and not (self._draining or self._drain_intent))

    @property
    def version(self) -> str:
        with self._lock:
            return self._version

    def pickable(self) -> bool:
        with self._lock:
            if self._canary:
                return False
        return self.ready and self.breaker.would_admit()

    def score(self) -> tuple:
        """Lower is better: router-view in-flight depth plus the
        replica's own scraped queue depth (load), tie-broken by the
        scraped rolling p99 (health), then rid (determinism)."""
        with self._lock:
            load = self._inflight + self._queue_depth
            p99 = self._scraped_p99_ms
        return (load, p99, self.rid)

    def local_p99_ms(self) -> float:
        q = self.rolling.quantiles()
        return float(q.get("p99", 0.0)) if q else 0.0

    def stats(self) -> dict:
        with self._lock:
            out = {
                "url": self.base_url,
                "ready": self._ready,
                "draining": self._draining or self._drain_intent,
                "param_version": self._version,
                "inflight": self._inflight,
                "queue_depth": self._queue_depth,
                "scraped_p99_ms": self._scraped_p99_ms,
                "probes": self._probes,
                "probe_ok": self._probe_ok,
                "probe_ready": self._probe_ready,
                "probe_backoff_s": self._probe_backoff_s,
                "canary": self._canary,
                "counts": dict(self.counts),
            }
        out["breaker"] = self.breaker.stats()
        out["router_p99_ms"] = self.local_p99_ms()
        return out
