"""Stdlib HTTP front-end over the FleetRouter (the thin router process).

Mirrors serve/http.py deliberately: a fleet client speaks the SAME wire
protocol as a single-replica client — ``POST /predict`` with a
``graph`` or ``structure`` body — and the router adds its resilience
headers to the response:

- ``X-Request-Id``     — the trace id every attempt carried (the
  idempotency key; inbound ids honored);
- ``X-Fleet-Replica``  — which replica answered;
- ``X-Fleet-Attempts`` — how many attempts it took (1 = first try).

``GET /healthz`` reports fleet readiness (200 when at least one replica
is admittable, 503 + Retry-After otherwise — same ready-vs-live split
the replicas expose). ``GET /stats`` and ``GET /metrics`` expose the
router's own counters, per-replica gauges, and rolling latency — the
fleet-level twin of the replica plane.

The metrics-truth surfaces (ISSUE 16): ``GET /metrics/fleet`` scrapes
every replica's ``/metrics`` and merges the mergeable ``*_hist``
histogram families into ONE fleet-wide exposition (bucket counts add
associatively; labels preserved) — the cross-process latency truth
per-replica quantile summaries cannot provide. ``GET /timeseries``
serves the router's embedded multi-resolution history
(``?name=&res=``), same shape as the replica endpoint.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from cgnn_tpu.fleet.router import FleetRouter
from cgnn_tpu.observe.metrics_io import jsonfinite


def make_fleet_handler(router: FleetRouter):
    class FleetHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: ARG002 — not operator signal
            pass

        def _reply(self, status: int, payload: dict,
                   headers: dict | None = None) -> None:
            try:
                body = json.dumps(payload, allow_nan=False).encode()
            except ValueError:
                body = json.dumps(jsonfinite(payload)).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path == "/healthz":
                ready = router.admittable()
                payload = {
                    "ok": True,
                    "ready": ready,
                    "replicas": len(router.replicas),
                    "replicas_ready": router.ready_count(),
                    "versions": {str(k): v
                                 for k, v in router.versions().items()},
                }
                if ready:
                    self._reply(200, payload)
                else:
                    self._reply(503, payload, headers={
                        "Retry-After": str(int(router._retry_after_s()))
                    })
            elif self.path == "/stats":
                out = router.stats()
                # the self-driving layers' state (ISSUE 17), when wired
                if router.autoscaler is not None:
                    out["autoscale"] = router.autoscaler.stats()
                if router.remediator is not None:
                    out["remediation"] = router.remediator.stats()
                self._reply(200, out)
            elif self.path == "/metrics":
                self._reply_text(router.registry.prometheus_text())
            elif self.path == "/metrics/fleet":
                # scrape-and-merge (ISSUE 16): one fleet-wide histogram
                # exposition, bit-identical in counts to pooling every
                # replica's raw observations
                self._reply_text(router.fleet_metrics_text())
            elif self.path.split("?", 1)[0] == "/timeseries":
                self._do_timeseries()
            elif self.path.split("?", 1)[0] in ("/trace", "/trace/joined"):
                self._do_trace()
            elif self.path == "/flightrec":
                if router.flightrec is None:
                    self._reply(501, {
                        "error": "flight recorder not configured "
                                 "(fleet.py --flightrec-dir)",
                    })
                else:
                    self._reply(200, router.flightrec.snapshot())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _reply_text(self, text: str) -> None:
            body = text.encode()
            self.send_response(200)
            self.send_header(
                "Content-Type",
                "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _do_timeseries(self) -> None:
            from urllib.parse import parse_qs, urlsplit

            if router.tsdb is None:
                self._reply(501, {
                    "error": "time-series store disabled "
                             "(fleet.py --no-slo)",
                })
                return
            q = parse_qs(urlsplit(self.path).query)
            name = (q.get("name") or [""])[0]
            res = (q.get("res") or ["10s"])[0]
            if not name:
                self._reply(200, {
                    "names": router.tsdb.names(),
                    "resolutions": router.tsdb.resolutions(),
                    "stats": router.tsdb.stats(),
                })
                return
            try:
                points = router.tsdb.query(name, res)
            except KeyError as e:
                self._reply(400, {"error": str(e)})
                return
            self._reply(200, {"name": name, "res": res,
                              "points": points})

        def _do_trace(self) -> None:
            """`/trace` = the router's own span window; `/trace/joined`
            = the on-demand fleet join (ISSUE 15): pull every replica's
            `/trace` window, merge with the router's, and return ONE
            Perfetto-openable document — a hedged request renders as
            one tree with both attempts. `?since=<unix-s>` bounds both
            forms to recent history."""
            from cgnn_tpu.observe import trace_join

            since, err = trace_join.parse_since_query(self.path)
            if err:
                self._reply(400, {"error": err})
                return
            window = router.trace_window(since_s=since)
            if window is None:
                self._reply(501, {
                    "error": "span ring disabled (fleet.py "
                             "--trace-ring 0)",
                })
                return
            if self.path.split("?", 1)[0] == "/trace":
                self._reply(200, window)
                return
            windows, errors = trace_join.collect_windows(
                router.replica_trace_urls(), since_s=since)
            doc = trace_join.join_windows([window, *windows])
            if errors:
                doc["collect_errors"] = errors
            self._reply(200, doc)

        def do_POST(self):  # noqa: N802
            if self.path not in ("/predict", "/label"):
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
            except ValueError as e:
                self._reply(400, {"error": f"malformed JSON body: {e}"})
                return
            if not isinstance(body, dict):
                self._reply(400, {"error": "body must be a JSON object"})
                return
            if self.path == "/label":
                self._do_label(body)
                return
            trace_id = (self.headers.get("X-Request-Id")
                        or body.get("trace_id"))
            status, payload, meta = router.dispatch(
                body, timeout_ms=body.get("timeout_ms"),
                trace_id=trace_id)
            headers = {
                "X-Request-Id": meta["trace_id"],
                "X-Fleet-Replica": str(meta["replica"]),
                "X-Fleet-Attempts": str(meta["attempts"]),
            }
            if "retry_after_s" in meta:
                headers["Retry-After"] = str(
                    int(max(meta["retry_after_s"], 1)))
            self._reply(status, payload, headers=headers)

        def _do_label(self, body: dict) -> None:
            # late ground truth -> the router's label journal, joined
            # by the trace id the /predict answer carried (ISSUE 18:
            # exactly once — a retransmitted label answers 'already')
            if router.journal is None:
                self._reply(501, {
                    "error": "label journal not configured "
                             "(fleet.py --journal)",
                })
                return
            try:
                label = float(body["label"])
            except (KeyError, TypeError, ValueError) as e:
                self._reply(400, {"error": f"malformed label: {e}"})
                return
            trace_id = body.get("trace_id")
            fingerprint = body.get("fingerprint")
            if trace_id is None and fingerprint is None:
                self._reply(400, {
                    "error": "label needs a 'trace_id' or a 'fingerprint'",
                })
                return
            status = router.journal.join(
                label, trace_id=trace_id, fingerprint=fingerprint)
            self._reply(200 if status != "unmatched" else 404,
                        {"status": status})

    return FleetHandler


class _FleetHTTPServer(ThreadingHTTPServer):
    # same rationale as serve/http.py: the stdlib backlog of 5 RSTs
    # bursty clients the router's own shedding should be refusing
    request_queue_size = 128


def make_fleet_http_server(router: FleetRouter, host: str = "127.0.0.1",
                           port: int = 8440) -> ThreadingHTTPServer:
    return _FleetHTTPServer((host, port), make_fleet_handler(router))
