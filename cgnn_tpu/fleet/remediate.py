"""Flight-recorder-driven auto-remediation (ISSUE 17).

PR 16's flight recorder turned every fleet incident into an evidence
bundle; until now a human read it. The remediator is the subscriber
that acts: it hooks ``FlightRecorder.on_trigger`` (breaker trips,
unreachable transitions, wedge watchdog dumps), matches the trigger
against the fleet's own state, and — when the evidence says a replica
is wedged, not merely loaded — runs **replace-and-drain**:

    spawn replacement -> wait ready -> route it -> unroute the victim
    -> SIGTERM-drain the victim -> force-reap past the bound

Two rules keep this from making outages worse:

- **every action names its evidence**: each entry appended to
  ``remediation.jsonl`` records the flight-recorder bundle (or the
  recorder's last bundle when the trigger itself was rate-limited)
  that justified it — the action chain is auditable end to end;
- **rate-limited**: a flapping replica cannot drive a respawn storm —
  a global minimum interval between actions, a per-replica interval,
  and a hard action cap; suppressed triggers are counted, not acted on.

Split like the autoscaler: :class:`RemediationPolicy` is the pure
decision core (``consider(now, reason, detail, replica_stats)``,
injectable clock in the caller); :class:`Remediator` is the runtime
that subscribes, queues triggers off the request path, and executes.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable

from cgnn_tpu.analysis import racecheck

# triggers a remediator reacts to; everything else (5xx bursts, SLO
# burns, drain force-exits) is evidence, not a replace signal
ACTIONABLE = ("breaker_trip", "replica_unreachable", "watchdog")


def rid_from_detail(reason: str, detail: str) -> int | None:
    """Extract the replica id a trigger is about from its detail line
    (the formats router.py emits): breaker trips name the breaker
    (``fleet.breaker.<rid>: open after ...``), unreachable transitions
    name the replica (``replica<rid> (url) stopped answering ...``)."""
    detail = str(detail)
    if reason == "breaker_trip" and detail.startswith("fleet.breaker."):
        head = detail.split(":", 1)[0]
        tail = head.rsplit(".", 1)[-1]
        return int(tail) if tail.isdigit() else None
    if detail.startswith("replica"):
        head = detail.split(" ", 1)[0][len("replica"):]
        return int(head) if head.isdigit() else None
    return None


class RemediationPolicy:
    """The pure decision core: one trigger in, one action (or None)
    out. State mutates only inside ``consider`` — callers serialize.

    The wedge signature it keys on: the replica's HEALTH plane still
    answers (``probe_ok`` and ``probe_ready`` True — the listener
    lives, the last probe said ready; NOT the dispatch-path ``ready``,
    which the k-th timeout clears in the same breath that trips the
    breaker) while the DISPATCH plane tripped (k consecutive
    failures/timeouts). A loaded replica rejects typed 429s (breaker
    records success); a dead one stops answering probes (the incident
    path); only a wedged flush presents healthy-but-failing — exactly
    what ``wedge_flush`` injects. An unreachable
    trigger on a NON-draining replica is the dead-replica case and is
    also actionable (replace): with spare capacity there is no reason
    to wait out a breaker cooldown hoping it returns."""

    def __init__(
        self,
        *,
        min_interval_s: float = 30.0,
        per_replica_interval_s: float = 120.0,
        max_actions: int = 8,
    ):
        self.min_interval_s = float(min_interval_s)
        self.per_replica_interval_s = float(per_replica_interval_s)
        self.max_actions = int(max_actions)
        self.actions_taken = 0
        self.suppressed = 0
        self._last_action_t: float | None = None
        self._last_by_rid: dict[int, float] = {}

    def consider(self, now: float, reason: str, detail: str,
                 replica_stats: dict | None) -> dict | None:
        """-> ``{"action": "replace_and_drain", "replica": rid,
        "why": ...}`` or None. ``replica_stats`` is the router's view
        of the implicated replica (None = not routed / unknown)."""
        if reason not in ACTIONABLE:
            return None
        rid = rid_from_detail(reason, detail)
        if rid is None:
            return None
        why = None
        if reason == "breaker_trip":
            s = replica_stats or {}
            if s.get("probe_ok") and s.get("probe_ready"):
                why = ("health plane answers while the dispatch plane "
                       "tripped the breaker (wedged-flush signature)")
        elif reason == "replica_unreachable":
            s = replica_stats or {}
            if not s.get("draining"):
                why = "stopped answering health probes (not draining)"
        elif reason == "watchdog":
            why = "racecheck watchdog stall report"
        if why is None:
            return None
        if self.actions_taken >= self.max_actions:
            self.suppressed += 1
            return None
        if (self._last_action_t is not None
                and now - self._last_action_t < self.min_interval_s):
            self.suppressed += 1
            return None
        last = self._last_by_rid.get(rid)
        if last is not None and now - last < self.per_replica_interval_s:
            self.suppressed += 1
            return None
        self.actions_taken += 1
        self._last_action_t = now
        self._last_by_rid[rid] = now
        return {"action": "replace_and_drain", "replica": rid,
                "why": why}

    def stats(self) -> dict:
        return {
            "actions_taken": self.actions_taken,
            "suppressed": self.suppressed,
            "min_interval_s": self.min_interval_s,
            "max_actions": self.max_actions,
        }


class Remediator:
    """The runtime: subscribes to a FlightRecorder, queues triggers off
    the request path, and executes replace-and-drain through the
    autoscaler's process machinery.

    ``autoscaler`` supplies the factory/state_factory/procs plumbing —
    the remediator replaces THROUGH it so ownership stays in one place
    (the replacement lands in ``autoscaler.procs`` and future scale
    decisions see it). Every executed action is appended to
    ``<out_dir>/remediation.jsonl`` naming the justifying bundle."""

    def __init__(
        self,
        router,
        autoscaler,
        policy: RemediationPolicy | None = None,
        *,
        out_dir: str = "",
        drain_timeout_s: float = 30.0,
        boot_timeout_s: float = 300.0,
        clock: Callable[[], float] = time.monotonic,
        log_fn: Callable = print,
    ):
        self.router = router
        self.autoscaler = autoscaler
        self.policy = policy or RemediationPolicy()
        self.out_dir = out_dir
        self.drain_timeout_s = float(drain_timeout_s)
        self.boot_timeout_s = float(boot_timeout_s)
        self._clock = clock
        self._log = log_fn
        self._lock = racecheck.make_lock("fleet.remediate")
        # mutated under self._lock (graftcheck GC-LOCKSHARE)
        self.actions: list = []
        import queue as _queue

        self._queue: _queue.Queue = _queue.Queue(maxsize=256)
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None

    # ---- wiring ----

    def attach(self, recorder) -> "Remediator":
        """Subscribe to the recorder's triggers and start the worker.
        The subscription callback only ENQUEUES — a breaker trip on the
        request path costs one queue put, never a process spawn."""
        self._recorder = recorder
        recorder.on_trigger = self._on_trigger
        if self._worker is None or not self._worker.is_alive():
            self._stop.clear()
            self._worker = threading.Thread(
                target=self._run, daemon=True, name="fleet-remediate")
            self._worker.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._worker is not None:
            self._worker.join(
                timeout=self.boot_timeout_s + self.drain_timeout_s + 30.0)

    def _on_trigger(self, reason: str, detail: str,
                    bundle: str | None) -> None:
        if reason not in ACTIONABLE:
            return
        try:
            self._queue.put_nowait((reason, detail, bundle))
        except Exception:  # noqa: BLE001 — full queue: drop, never block
            self._log("remediate: trigger queue full; dropping "
                      f"{reason!r}")

    # ---- the worker ----

    def _run(self) -> None:
        import queue as _queue

        while not self._stop.is_set():
            racecheck.heartbeat()
            try:
                reason, detail, bundle = self._queue.get(timeout=0.5)
            except _queue.Empty:
                continue
            try:
                self.handle(reason, detail, bundle)
            except Exception as e:  # noqa: BLE001 — keep consuming
                self._log(f"remediate: action for {reason!r} "
                          f"failed: {e!r}")

    def handle(self, reason: str, detail: str,
               bundle: str | None) -> dict | None:
        """Consider + execute one trigger synchronously (the worker's
        body; tests call it directly); -> the action record or None."""
        rid = rid_from_detail(reason, detail)
        replica = self.router._replica(rid) if rid is not None else None
        stats = replica.stats() if replica is not None else None
        action = self.policy.consider(self._clock(), reason, detail,
                                      stats)
        if action is None:
            return None
        # a suppressed trigger has no bundle of its own: fall back to
        # the recorder's last bundle so the chain still names evidence
        if not bundle:
            rec = getattr(self, "_recorder", None)
            bundle = rec.last_bundle if rec is not None else ""
        return self._replace_and_drain(action["replica"], reason,
                                       detail, bundle or "", action["why"])

    def _replace_and_drain(self, victim: int, reason: str, detail: str,
                           bundle: str, why: str) -> dict:
        """spawn replacement -> wait ready -> route it -> unroute +
        drain the victim -> force-reap past the bound."""
        self._log(f"remediate: replacing replica{victim} "
                  f"({reason}: {why})")
        replacement = self.autoscaler.scale_up(
            reason=f"remediation: replace replica{victim}")
        steps = [f"scale_up -> replica{replacement}"
                 if replacement is not None else "scale_up FAILED"]
        # unroute the victim FIRST (reason='remediation' counts an
        # incident — this is a failure response, not elastic sizing),
        # then drain what it accepted; terminate() force-kills past
        # the bound, so a fully wedged victim still dies
        self.router.remove_replica(victim, reason="remediation")
        proc = self.autoscaler.proc_for(victim)
        if proc is not None:
            code = proc.terminate(timeout_s=self.drain_timeout_s)
            steps.append(f"drain victim (exit {code})")
        else:
            steps.append("victim process unknown (external spawn)")
        record = {
            "t_unix": time.time(),
            "action": "replace_and_drain",
            "replica": victim,
            "replacement": replacement,
            "reason": reason,
            "detail": detail,
            "bundle": bundle,
            "why": why,
            "steps": steps,
        }
        with self._lock:
            self.actions.append(record)
        self._append_jsonl(record)
        self._log(f"remediate: replica{victim} replaced by "
                  f"replica{replacement} ({'; '.join(steps)}) "
                  f"[evidence: {bundle or 'no bundle'}]")
        return record

    def _append_jsonl(self, record: dict) -> None:
        if not self.out_dir:
            return
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir, "remediation.jsonl")
            with open(path, "a") as f:
                f.write(json.dumps(record, allow_nan=False) + "\n")
        except Exception as e:  # noqa: BLE001 — the journal is evidence,
            self._log(f"remediate: journal append failed: {e!r}")

    def stats(self) -> dict:
        with self._lock:
            actions = list(self.actions)
        return {"policy": self.policy.stats(), "actions": actions,
                "queued": self._queue.qsize()}
