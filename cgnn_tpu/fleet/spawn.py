"""Replica process lifecycle: boot serve.py subprocesses, wait for
readiness, kill them (the chaos harness's kill -9 leg), restart them.

Each replica is a REAL process running the existing serve.py entrypoint
against the SAME checkpoint directory — which is exactly what makes
rolling promotion work with no new machinery: every replica's own
CheckpointWatcher (PR 3) polls that directory, so one committed save
rolls across the fleet within a poll interval, each replica swapping
atomically mid-load like the single-process invariant always promised.

``wait_ready`` polls ``GET /healthz`` until it reports ``ready`` (the
ISSUE-14 readiness split: a warming replica answers 503, so the fleet
never routes traffic into cold-compile latency).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Callable

from cgnn_tpu.fleet.replica import FleetTransportError, http_get_json

_SERVE_PY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "serve.py")


class ReplicaProcess:
    """One serve.py subprocess bound to a fixed port (stable across
    restarts, so the router's endpoint list never changes)."""

    def __init__(
        self,
        rid: int,
        ckpt_dir: str,
        port: int,
        *,
        host: str = "127.0.0.1",
        log_path: str | None = None,
        serve_args: list | None = None,
        env: dict | None = None,
        serve_py: str = _SERVE_PY,
    ):
        self.rid = int(rid)
        self.ckpt_dir = ckpt_dir
        self.host = host
        self.port = int(port)
        self.base_url = f"http://{host}:{port}"
        self.log_path = log_path
        self.serve_args = list(serve_args or [])
        self.env = dict(env) if env is not None else None
        self.serve_py = serve_py
        self.proc: subprocess.Popen | None = None
        self.starts = 0
        self.kills = 0

    def start(self) -> "ReplicaProcess":
        if self.proc is not None and self.proc.poll() is None:
            return self
        cmd = [sys.executable, self.serve_py, self.ckpt_dir,
               "--host", self.host, "--port", str(self.port),
               *self.serve_args]
        env = dict(os.environ if self.env is None else self.env)
        env.setdefault("JAX_PLATFORMS", "cpu")
        log = (open(self.log_path, "ab")
               if self.log_path else subprocess.DEVNULL)
        try:
            self.proc = subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT, env=env)
        finally:
            if self.log_path:
                log.close()  # the child holds its own fd now
        self.starts += 1
        return self

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def wait_ready(self, timeout_s: float = 300.0,
                   poll_s: float = 0.25) -> bool:
        """Poll /healthz until ready (True) or the process dies / the
        timeout passes (False)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self.alive():
                return False
            try:
                status, payload = http_get_json(
                    self.base_url + "/healthz", timeout_s=2.0)
                if status == 200 and payload.get("ready", True):
                    return True
            except FleetTransportError:
                pass  # not listening yet
            time.sleep(poll_s)
        return False

    def kill9(self) -> None:
        """The chaos leg: SIGKILL, no drain, no cleanup — in-flight
        requests die with their sockets, exactly like a machine loss."""
        if self.proc is not None and self.proc.poll() is None:
            os.kill(self.proc.pid, signal.SIGKILL)
            self.proc.wait(timeout=30)
            self.kills += 1

    def terminate(self, timeout_s: float = 60.0) -> int | None:
        """SIGTERM -> the replica's graceful drain; returns its exit
        code (None if it had to be killed after the timeout)."""
        if self.proc is None:
            return None
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                return self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=30)
                return None
        return self.proc.poll()

    def restart(self) -> "ReplicaProcess":
        """Bring a (dead) replica back on its port."""
        if self.alive():
            self.kill9()
        return self.start()


class RestartBackoff:
    """The crash-loop guard (ISSUE 17): a replica that dies during
    boot/warmup waits exponentially longer before each retry and the
    supervisor GIVES UP after ``give_up`` attempts — a broken
    checkpoint or a poisoned flag must never hot-loop respawns.

    Pure arithmetic on an injectable clock; ``next_delay()`` returns
    the seconds to wait before the next attempt or None when the
    budget is spent. ``reset()`` on the first healthy boot restores
    the full budget (an occasional preemption is not a crash loop)."""

    def __init__(self, *, base_s: float = 0.5, mult: float = 2.0,
                 max_s: float = 30.0, give_up: int = 5,
                 clock: Callable[[], float] = time.monotonic):
        if give_up < 1:
            raise ValueError(f"give_up must be >= 1, got {give_up}")
        self.base_s = float(base_s)
        self.mult = float(mult)
        self.max_s = float(max_s)
        self.give_up = int(give_up)
        self._clock = clock
        self.failures = 0
        self.last_failure_t: float | None = None

    def next_delay(self) -> float | None:
        """Record one boot failure; -> seconds to back off before the
        next attempt, or None when the give-up cap is spent."""
        self.failures += 1
        self.last_failure_t = self._clock()
        if self.failures >= self.give_up:
            return None
        return min(self.base_s * self.mult ** (self.failures - 1),
                   self.max_s)

    def reset(self) -> None:
        self.failures = 0
        self.last_failure_t = None

    def stats(self) -> dict:
        return {"failures": self.failures, "give_up": self.give_up,
                "base_s": self.base_s, "max_s": self.max_s}


def boot_with_retries(
    proc: ReplicaProcess,
    *,
    wait_ready_s: float = 300.0,
    backoff: RestartBackoff | None = None,
    log_fn: Callable = print,
    sleep: Callable[[float], None] = time.sleep,
) -> bool:
    """Supervised boot: start ``proc`` and wait for readiness,
    restarting through ``backoff`` when it dies during boot/warmup;
    -> True once healthy, False when the backoff gives up (the proc is
    reaped). The ``boot_crash=N`` fault point pins this: N boots die
    during warmup, the N+1st succeeds — under the default budget the
    supervisor outlasts the fault without hot-looping."""
    backoff = backoff or RestartBackoff()
    while True:
        proc.start()
        if proc.wait_ready(wait_ready_s):
            backoff.reset()
            return True
        delay = backoff.next_delay()
        if proc.alive():
            # ready-timeout, not a crash: a wedged warmup retries too,
            # but the old process must die first
            proc.kill9()
        if delay is None:
            log_fn(f"fleet: replica{proc.rid} crash-looped "
                   f"{backoff.failures}x during boot; giving up")
            proc.terminate(timeout_s=5.0)
            return False
        log_fn(f"fleet: replica{proc.rid} died during boot "
               f"(attempt {backoff.failures}); retrying in {delay:.2f}s")
        sleep(delay)


def spawn_fleet(
    ckpt_dir: str,
    n: int,
    *,
    base_port: int = 8441,
    host: str = "127.0.0.1",
    log_dir: str | None = None,
    serve_args: list | None = None,
    wait_ready_s: float = 300.0,
) -> list:
    """Boot ``n`` replicas on consecutive ports and wait until every
    one reports ready. Raises RuntimeError (after terminating the
    stragglers) when any replica fails to come up."""
    procs = []
    for i in range(n):
        log_path = (os.path.join(log_dir, f"replica-{i}.log")
                    if log_dir else None)
        procs.append(ReplicaProcess(
            i, ckpt_dir, base_port + i, host=host, log_path=log_path,
            serve_args=serve_args,
        ).start())
    failed = [p.rid for p in procs if not p.wait_ready(wait_ready_s)]
    if failed:
        for p in procs:
            p.terminate(timeout_s=5.0)
        raise RuntimeError(
            f"replicas {failed} never became ready within "
            f"{wait_ready_s:.0f} s (logs: {log_dir or 'discarded'})"
        )
    return procs
