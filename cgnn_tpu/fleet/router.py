"""Health/load-aware dispatch over a replica fleet, with per-request
resilience.

``FleetRouter.dispatch`` is the whole request policy, synchronously on
the caller's thread (the HTTP front-end calls it per connection; the
fleet loadgen calls it directly):

- **pick** — among replicas that are ready (scraped ``/healthz``), not
  draining, and whose breaker admits: least (router-view in-flight +
  scraped queue depth), tie-broken by scraped rolling p99 — the PR-6
  observability plane reused as the routing signal;
- **retry** — a transport failure (refused/reset/timeout: how a
  kill -9'd replica presents) or a retryable upstream status (429
  queue-full, 500 dispatch failure, 502, 503 draining) costs the
  replica a breaker failure and the request retries on the next-best
  replica after exponential backoff with jitter, bounded by
  ``max_attempts`` and the request deadline;
- **hedge** — with one attempt in flight past its hedge point (fixed
  ``hedge_ms``, or auto: 2x the replica's router-measured p99) and
  deadline budget left, a second attempt fires on a DIFFERENT replica;
  the first success wins;
- **exactly once** — every attempt of a request carries the SAME trace
  id (the PR-6 idempotency key, forwarded as ``X-Request-Id``), and the
  single coordinator is the only consumer of attempt results: the
  client gets exactly one answer no matter how many attempts resolve
  (a straggler's success is counted as ``fleet_hedge_waste``, never
  delivered);
- **shed** — when NO replica is admittable (all ejected or draining)
  the router degrades gracefully: 503 with a Retry-After derived from
  the soonest breaker cooldown, instead of queueing unboundedly;
- **pass through** — non-retryable upstream rejections (400 malformed,
  413 oversize, 504 deadline) return to the client as-is: retrying a
  malformed request burns fleet capacity to fail again.

All policy state is host-side; the router never touches jax.
"""

from __future__ import annotations

import collections
import itertools
import os
import queue
import random
import threading
import time
from typing import Callable, Sequence

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.fleet.cachering import CacheRing
from cgnn_tpu.fleet.replica import (
    FleetTransportError,
    ReplicaState,
    http_transport,
)
from cgnn_tpu.observe.export import MetricsRegistry, RollingSeries
from cgnn_tpu.observe.log import bind_trace
from cgnn_tpu.observe.spans import SpanTracer
from cgnn_tpu.observe.tracectx import format_parent, mint_span_id

# upstream statuses worth another replica (the replica is loaded,
# draining, or failed — a sibling may well answer)
RETRYABLE_STATUS = frozenset((429, 500, 502, 503))
# upstream rejections that are about the REQUEST, not the replica:
# retrying elsewhere would just fail again
PASSTHROUGH_STATUS = frozenset((400, 404, 413, 501, 504))


def edge_fingerprint(body: dict) -> str | None:
    """Content hash of a dispatch body's wire arrays, computed ONCE at
    the fleet edge (ISSUE 20): featurized ``graph`` payloads hash to the
    bare digest ``serve.cache.structure_fingerprint`` would produce,
    wire-form ``structure`` payloads to the ``'raw:'``-prefixed
    ``data.rawbatch.raw_fingerprint``. The hash rides to the replica as
    X-Fingerprint, which then only QUALIFIES the key (fs:/tier
    prefixes) instead of re-hashing the arrays. None on a body this
    router cannot hash (malformed or fingerprint-free) — affinity and
    coalescing simply disengage, routing is unchanged."""
    try:
        if "graph" in body:
            from cgnn_tpu.serve.cache import structure_fingerprint
            from cgnn_tpu.serve.http import graph_from_json

            return structure_fingerprint(graph_from_json(body["graph"]))
        if "structure" in body:
            from cgnn_tpu.data.rawbatch import raw_fingerprint
            from cgnn_tpu.serve.http import structure_from_json

            return raw_fingerprint(structure_from_json(body["structure"]))
    except (KeyError, TypeError, ValueError):
        return None
    return None


class _Call:
    """Per-request coordination: the shared trace id and the delivered
    latch attempt threads consult before posting (a straggler success
    after delivery is wasted compute, counted, never a second answer).
    ``span_id`` is the request's ROOT span in the router's trace ring —
    every attempt span parents to it (observe/tracectx.py)."""

    def __init__(self, tid: str, span_id: str = ""):
        self.tid = tid
        self.span_id = span_id
        self.done = threading.Event()


class FleetRouter:
    def __init__(
        self,
        replicas: Sequence[ReplicaState],
        *,
        transport: Callable | None = None,
        max_attempts: int = 4,
        backoff_ms: float = 25.0,
        backoff_mult: float = 2.0,
        max_backoff_ms: float = 1000.0,
        jitter: float = 0.5,
        hedge_ms: float | None = None,
        default_timeout_ms: float = 30000.0,
        feasibility: bool = True,
        feasibility_margin: float = 1.0,
        health_interval_s: float = 1.0,
        trace_ring: int = 65536,
        slo_layer: bool = True,
        slo_objectives=None,
        slo_rules=None,
        tsdb_interval_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        rng: random.Random | None = None,
        log_fn: Callable = print,
        cache_affinity: bool = True,
        coalesce_wait_ms: float = 1000.0,
        peer_fill: bool = True,
        ring_vnodes: int = 64,
    ):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        # the routing table is MUTABLE now (ISSUE 17: the autoscaler
        # adds/removes replicas at runtime) — storage lives behind
        # self._lock, reads go through replica_list()/_replica()
        self._replicas = list(replicas)
        self._by_rid = {r.rid: r for r in self._replicas}
        if len(self._by_rid) != len(self._replicas):
            raise ValueError("replica ids must be unique")
        self._transport = transport or http_transport
        self.max_attempts = int(max_attempts)
        self.backoff_s = backoff_ms / 1e3
        self.backoff_mult = float(backoff_mult)
        self.max_backoff_s = max_backoff_ms / 1e3
        self.jitter = float(jitter)
        # None = auto (2x the picked replica's router-measured p99,
        # floored); <= 0 disables hedging entirely
        self.hedge_ms = hedge_ms
        self.default_timeout_ms = float(default_timeout_ms)
        # deadline-feasibility admission (ISSUE 19): when on, a request
        # whose deadline cannot plausibly be met — judged from the
        # scraped per-replica rolling p99 + queue depth the health
        # prober already holds (ISSUE 16) — is rejected BEFORE any
        # attempt crosses a process boundary. ``feasibility_margin``
        # scales the estimate (2.0 = only shed when the predicted
        # completion exceeds twice the deadline; headroom for noisy p99)
        self.feasibility = bool(feasibility)
        if feasibility_margin <= 0:
            raise ValueError(
                f"feasibility_margin must be > 0, got {feasibility_margin}")
        self.feasibility_margin = float(feasibility_margin)
        self.health_interval_s = float(health_interval_s)
        self._clock = clock
        self._rng = rng or random.Random(0x5EED)
        self._log = log_fn
        self._lock = racecheck.make_lock("fleet.router")
        # mutated under self._lock (graftcheck GC-LOCKSHARE)
        self.counts: dict[str, int] = {
            "fleet_requests": 0, "fleet_answered": 0, "fleet_retries": 0,
            "fleet_hedges": 0, "fleet_hedge_wins": 0,
            "fleet_hedge_waste": 0, "fleet_shed": 0,
            "fleet_exhausted": 0, "fleet_deadline_exceeded": 0,
            "fleet_transport_errors": 0, "fleet_passthrough_rejects": 0,
            "fleet_duplicate_answers": 0,
            # ISSUE 19: deadline-feasibility sheds, split by cause —
            # queue congestion (retry helps) vs a p99 floor above the
            # deadline (retry cannot help; ask for a longer deadline)
            "fleet_infeasible_queue": 0, "fleet_infeasible_deadline": 0,
            # ISSUE 17: the capacity ledger. A planned disappearance
            # (drained scale-down, exit-75 preemption) is a SCALE
            # EVENT; an unplanned one (kill -9, crash) an INCIDENT
            "fleet_scale_events": 0, "fleet_incidents": 0,
            # ISSUE 20: cache partitioning. owner_routed/owner_fallback
            # split every fingerprinted pick by whether the ring owner
            # took it; coalesced = follower answers served off a
            # leader's in-flight dispatch; peer_fills = owner-miss rows
            # shipped back to the owner's cache
            "fleet_fingerprinted": 0, "fleet_owner_routed": 0,
            "fleet_owner_fallback": 0, "fleet_coalesced": 0,
            "fleet_coalesce_timeouts": 0, "fleet_peer_fills": 0,
            "fleet_peer_fill_stale": 0, "fleet_peer_fill_errors": 0,
        }
        # ---- one fleet cache (ISSUE 20) ----
        # owner-affinity is an OPTIMIZATION, never a correctness
        # dependency (INVARIANTS.md): a dead/ejected owner falls back
        # to the ordinary load-aware pick, responses stay bit-exact
        self.cache_ring = (CacheRing((r.rid for r in self._replicas),
                                     vnodes=ring_vnodes)
                          if cache_affinity else None)
        self.peer_fill = bool(peer_fill)
        # router-side single-flight: identical fingerprints dispatched
        # concurrently collapse onto one upstream leader; followers
        # wait BOUNDED (never past their own deadline) then dispatch
        # themselves — coalescing may only ever remove upstream work
        self._coalesce_wait_s = max(float(coalesce_wait_ms), 0.0) / 1e3
        self._sf_lock = racecheck.make_lock("fleet.singleflight")
        self._sf: dict[str, dict] = {}
        # replica lifecycle journal (add/remove/incident), mutated
        # under self._lock like counts
        self.lifecycle: collections.deque = collections.deque(maxlen=256)
        self._trace_prefix = os.urandom(3).hex()
        self._trace_seq = itertools.count(1)
        self._stop = threading.Event()
        self._health_thread: threading.Thread | None = None
        self._lat_rolling = RollingSeries(window_s=60.0, clock=clock)
        self.registry = MetricsRegistry(window_s=60.0)
        self.registry.add_provider("fleet", self._registry_snapshot)
        # the router's own span ring (ISSUE 15): one fleet.request root
        # per dispatch, one fleet.attempt per try/hedge — the spans a
        # joined fleet trace nests every replica's stage spans under.
        # Bounded, always-on by default, host-side only; 0 disables
        # (the propagation/recorder A/B baseline, PERF.md §18)
        self.tracer = (SpanTracer(
            process_name=f"fleet-router-{os.getpid()}",
            max_events=int(trace_ring)) if trace_ring else None)
        # incident flight recorder (observe/flightrec.py), attached by
        # the entrypoint; breaker trips + 5xx bursts dump bundles
        self.flightrec = None
        # the self-driving layers (ISSUE 17), attached by the
        # entrypoint; /stats folds their state in when present
        self.autoscaler = None
        self.remediator = None
        # continual-learning plane (ISSUE 18), attached by the
        # entrypoint: the label journal every 200 dispatch lands in
        # (POST /label joins late ground truth) and the canary
        # controller whose per-version histograms ride this registry
        self.journal = None
        self.canary = None
        # ---- fleet SLO engine + metrics truth (ISSUE 16) ----
        # the router's latency histogram is MERGEABLE (observe/hist.py)
        # where the rolling quantiles above are local color; the SLO
        # ledger is fed at ATTEMPT level (_attempt) — retries hide
        # errors from clients, and they must NOT hide them from the
        # error budget, or a fleet silently burning capacity on retried
        # 500s looks healthy right up to exhaustion
        from cgnn_tpu.observe.hist import LATENCY_MS_BOUNDS, Histogram
        from cgnn_tpu.observe.slo import SLOEngine, SLOObjective
        from cgnn_tpu.observe.tsdb import TimeSeriesStore, TsdbCollector

        self.hists: dict[str, Histogram] = {}
        self.slo = None
        self.tsdb = None
        self._tsdb_collector = None
        if slo_layer:
            self.hists = {
                "fleet_latency_ms_hist": Histogram(LATENCY_MS_BOUNDS),
                "fleet_attempt_latency_ms_hist": Histogram(
                    LATENCY_MS_BOUNDS),
                # ISSUE 20: wall time of each peer-fill hop (the price
                # of keeping the owner's cache warm off-path)
                "fleet_owner_hop_ms_hist": Histogram(LATENCY_MS_BOUNDS),
            }
            objectives = (tuple(slo_objectives) if slo_objectives else (
                SLOObjective("fleet_availability", target=0.999,
                             window_s=300.0),
                SLOObjective("fleet_latency", target=0.95,
                             latency_threshold_ms=2000.0, window_s=300.0),
            ))
            self.slo = SLOEngine(
                objectives, rules=slo_rules, clock=clock,
                on_fire=self._on_slo_fire, on_resolve=self._on_slo_resolve,
            )
            self.tsdb = TimeSeriesStore()
            self._tsdb_collector = TsdbCollector(
                self.registry, self.tsdb, interval_s=tsdb_interval_s,
            )
            self._tsdb_collector.add_on_tick(self._slo_tick)

    # ---- lifecycle ----

    def start(self, probe_now: bool = True) -> "FleetRouter":
        """Arm the health poller (one synchronous probe round first so
        the first dispatch already has a routing signal)."""
        if probe_now:
            self.probe_all()
        if self._health_thread is None or not self._health_thread.is_alive():
            self._stop.clear()
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True, name="fleet-health"
            )
            self._health_thread.start()
        if self._tsdb_collector is not None:
            self._tsdb_collector.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=10.0)
        if self._tsdb_collector is not None:
            self._tsdb_collector.stop()

    # ---- routing-table membership (ISSUE 17) ----

    @property
    def replicas(self) -> list:
        """A snapshot copy of the routed set — membership can change
        under the autoscaler, so no caller may hold the live list."""
        with self._lock:
            return list(self._replicas)

    def replica_list(self) -> list:
        with self._lock:
            return list(self._replicas)

    def _replica(self, rid: int):
        with self._lock:
            return self._by_rid.get(rid)

    def count(self, key: str) -> int:
        with self._lock:
            return int(self.counts.get(key, 0))

    def rolling_latency(self) -> dict:
        return self._lat_rolling.quantiles()

    def lifecycle_events(self) -> list:
        with self._lock:
            return list(self.lifecycle)

    def add_replica(self, state: ReplicaState) -> None:
        """Route a new replica (the autoscaler's scale-up add). The
        warm-pool contract makes this cheap: the replica is already
        booted, warm()-compiled, and /healthz-ready when it lands here,
        so adding it is one routing-table entry, not a warmup wait."""
        with self._lock:
            if state.rid in self._by_rid:
                raise ValueError(f"replica id {state.rid} already routed")
            self._replicas.append(state)
            self._by_rid[state.rid] = state
            self.lifecycle.append({
                "t": self._clock(), "event": "add",
                "replica": state.rid, "reason": "scale_up",
            })
        if self.cache_ring is not None:
            # incremental rebalance: only the new arcs re-own
            self.cache_ring.add(state.rid)
        if self.flightrec is not None:
            state.breaker.on_trip = self._on_breaker_trip

    def remove_replica(self, rid: int,
                       reason: str = "scale_down") -> ReplicaState | None:
        """Unroute a replica; -> its state (None if already gone —
        idempotent, because the health poller and the autoscaler's
        drain thread can both notice the same disappearance).

        ``reason`` decides the ledger: ``scale_down`` / ``preempt`` /
        ``drained`` are SCALE EVENTS (planned capacity change — no
        breaker trip, no incident bundle); ``incident`` /
        ``remediation`` count as fleet incidents."""
        with self._lock:
            r = self._by_rid.pop(rid, None)
            if r is None:
                return None
            self._replicas = [x for x in self._replicas if x.rid != rid]
            if reason in ("scale_down", "preempt", "drained"):
                self.counts["fleet_scale_events"] += 1
            elif reason in ("incident", "remediation"):
                self.counts["fleet_incidents"] += 1
            self.lifecycle.append({
                "t": self._clock(), "event": "remove",
                "replica": rid, "reason": reason,
            })
        if self.cache_ring is not None:
            self.cache_ring.remove(rid)
        self._log(f"fleet: replica{rid} unrouted ({reason})")
        return r

    def begin_drain(self, rid: int) -> None:
        """Mark a replica draining ROUTER-SIDE before its SIGTERM goes
        out: even a drain that completes inside one probe interval is
        then classified a scale event when it stops answering — the
        poller never sees an un-flagged disappearance."""
        r = self._replica(rid)
        if r is not None:
            r.note_draining()
            if self.cache_ring is not None:
                # re-own its arcs NOW — new keys go to successors while
                # the drain finishes in-flight work, so the successor
                # caches are already warming when the replica leaves
                self.cache_ring.remove(rid)

    # ---- the canary plane (ISSUE 18) ----
    # The fleet-adapter protocol continual/canary.py drives: pin one
    # replica to a candidate version (out of client rotation, shadow
    # traffic only), and promote fleet-wide by raising every reload
    # watcher's gate — the watchers then swap independently, rolling,
    # with zero dropped requests (in-flight work finishes on the params
    # it started with; serve/reload.py).

    def attach_journal(self, journal) -> None:
        """Wire the label journal: every 200 dispatch appends a served
        record; POST /label (fleet/http.py) joins late ground truth."""
        self.journal = journal

    def attach_canary(self, controller) -> None:
        """Wire the canary controller: its per-version MAE/latency
        histograms join this registry's scrape and /stats folds its
        state machine in."""
        self.canary = controller

    def _reload_control(self, r, body: dict) -> bool:
        from cgnn_tpu.fleet.replica import http_post_json

        try:
            status, _ = http_post_json(
                r.base_url + "/reload-control", body, timeout_s=5.0)
        except FleetTransportError as e:
            self._log(f"fleet: reload-control {r.name} failed: {e!r}")
            return False
        if status != 200:
            self._log(f"fleet: reload-control {r.name} -> HTTP {status}")
        return status == 200

    def fleet_version(self) -> str | None:
        """The version the routed (non-canary) fleet serves — the
        promotion baseline. With replicas mid-swap, the most common
        probed version wins; None before any probe landed."""
        versions = [r.version for r in self.replicas
                    if not r.canary and r.version]
        if not versions:
            return None
        return collections.Counter(versions).most_common(1)[0][0]

    def begin_canary(self, version: str) -> int | None:
        """Take one ready replica out of rotation and pin its reload
        watcher to ``version``; -> rid, or None when no replica can be
        spared this tick (a one-replica fleet never gives one up)."""
        pool = sorted((r for r in self.replicas if r.pickable()),
                      key=lambda r: r.score())
        if len(pool) < 2:
            return None
        r = pool[0]
        r.note_canary(True)
        if not self._reload_control(r, {"pin": version}):
            r.note_canary(False)
            return None
        with self._lock:
            self.counts["fleet_canaries"] = (
                self.counts.get("fleet_canaries", 0) + 1)
            self.lifecycle.append({
                "t": self._clock(), "event": "canary_begin",
                "replica": r.rid, "reason": version,
            })
        return r.rid

    def canary_version(self, rid: int) -> str | None:
        """What the pinned replica serves right now (the convergence
        probe); None when unreachable or unrouted."""
        from cgnn_tpu.fleet.replica import http_get_json

        r = self._replica(rid)
        if r is None:
            return None
        try:
            _, health = http_get_json(r.base_url + "/healthz",
                                      timeout_s=5.0)
        except FleetTransportError:
            return None
        v = str(health.get("param_version", ""))
        return v or None

    def shadow_predict(self, rid: int, payload: dict,
                       timeout_s: float) -> tuple[float, float]:
        """One mirrored request straight to the canary, bypassing
        routing, breakers, and the journal — the shadow answer never
        counts toward any client response or routing signal. Raises on
        any failure; -> (prediction, latency_ms)."""
        r = self._replica(rid)
        if r is None:
            raise FleetTransportError(f"replica{rid} is not routed")
        body = dict(payload)
        body["timeout_ms"] = timeout_s * 1e3
        t0 = time.perf_counter()
        status, resp = self._transport(r, body, timeout_s)
        lat_ms = (time.perf_counter() - t0) * 1e3
        if status != 200:
            raise RuntimeError(
                f"shadow predict -> HTTP {status}: "
                f"{(resp or {}).get('error', '')}")
        self._count("fleet_shadow_mirrors")
        return float(resp["prediction"][0]), lat_ms

    def promote(self, rid: int, version: str) -> None:
        """Broadcast the gate fleet-wide: every replica's reload
        watcher ceiling rises to ``version`` and each swaps
        independently when it next polls — the rolling, zero-downtime
        promotion. The canary un-pins and returns to rotation already
        serving the promoted version."""
        self._log(f"fleet: promoting {version} fleet-wide")
        for r in self.replicas:
            body = {"gate": version}
            if r.rid == rid:
                body["pin"] = None
            self._reload_control(r, body)
        r = self._replica(rid)
        if r is not None:
            r.note_canary(False)
        with self._lock:
            self.counts["fleet_promotions"] = (
                self.counts.get("fleet_promotions", 0) + 1)
            self.lifecycle.append({
                "t": self._clock(), "event": "promote",
                "replica": rid, "reason": version,
            })

    def abort_canary(self, rid: int, to_version: str | None) -> None:
        """Pin the canary back to the fleet version (the rollback);
        the controller calls end_canary once it converged."""
        r = self._replica(rid)
        if r is not None and to_version:
            self._reload_control(r, {"pin": to_version})
        with self._lock:
            self.counts["fleet_canary_rollbacks"] = (
                self.counts.get("fleet_canary_rollbacks", 0) + 1)
            self.lifecycle.append({
                "t": self._clock(), "event": "canary_rollback",
                "replica": rid, "reason": to_version or "",
            })

    def end_canary(self, rid: int) -> None:
        """Clear the pin and return the replica to rotation (its gate
        stays wherever the last promotion left it)."""
        r = self._replica(rid)
        if r is None:
            return
        self._reload_control(r, {"pin": None})
        r.note_canary(False)
        with self._lock:
            self.lifecycle.append({
                "t": self._clock(), "event": "canary_end",
                "replica": rid, "reason": "",
            })

    # ---- fleet SLO hooks (ISSUE 16) ----

    def _slo_tick(self) -> None:
        """Collector heartbeat: advance the alert state machines so
        firing/resolved transitions happen on the clock, not only when
        traffic arrives."""
        if self.slo is not None:
            self.slo.evaluate()

    def _note_slo_attempt(self, ok: bool, lat_ms: float) -> None:
        """One ATTEMPT into the error budget + the attempt histogram.
        Attempt level is deliberate: the retry/hedge machinery above
        turns upstream 500s into client 200s, and an error budget fed
        at client level would sleep through exactly the incidents it
        exists to catch."""
        if self.slo is not None:
            self.slo.record(ok, lat_ms)
        h = self.hists.get("fleet_attempt_latency_ms_hist")
        if h is not None:
            h.observe(lat_ms)

    def _on_slo_fire(self, tr: dict) -> None:
        """Fleet burn-rate alert FIRING -> incident bundle whose
        manifest names the alert (``slo_burn_<objective>``) — the
        fleet_smoke pin."""
        self._log(
            f"fleet: SLO ALERT firing: objective={tr['objective']} "
            f"rule={tr['rule']} burn_fast={tr['burn_fast']:.2f} "
            f"burn_slow={tr['burn_slow']:.2f} (factor {tr['factor']:g})"
        )
        fr = self.flightrec
        if fr is not None:
            fr.trigger(
                f"slo_burn_{tr['objective']}",
                detail=(f"rule={tr['rule']} "
                        f"burn_fast={tr['burn_fast']:.3f} "
                        f"burn_slow={tr['burn_slow']:.3f} "
                        f"factor={tr['factor']:g}"),
            )

    def _on_slo_resolve(self, tr: dict) -> None:
        self._log(
            f"fleet: SLO alert resolved: objective={tr['objective']} "
            f"rule={tr['rule']}"
        )

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            racecheck.heartbeat()
            self.probe_all()

    def probe_all(self, timeout_s: float = 2.0) -> int:
        """Probe every replica once; returns how many are ready.

        A reachable->unreachable TRANSITION (the wire died — not a
        draining/warming 503, which still answers the probe) is
        classified by intent (ISSUE 17): a replica that advertised
        ``draining`` before vanishing finished a planned drain (scale-
        down SIGTERM or an exit-75 preemption) and is removed as a
        SCALE EVENT — no breaker trip, no incident bundle. An
        un-flagged disappearance (kill -9, a machine loss) is an
        INCIDENT: it stays routed (its breaker ejects it; a restart
        re-admits it) and fires the flight recorder — the next poll
        round after a replica vanishes is the deterministic moment to
        bundle the fleet's last minutes, whether or not enough
        in-flight requests happened to trip its breaker first.

        Unreachable replicas back off their own probe cadence
        (replica.probe_due): a dead replica costs one probe timeout at
        a doubling interval, not one per poll round."""
        ready = 0
        for r in self.replica_list():
            if not r.probe_due():
                continue
            was_reachable = r.stats()["probe_ok"]
            try:
                ready += bool(r.probe(timeout_s))
            except Exception as e:  # noqa: BLE001 — the poller must survive
                self._log(f"fleet: health probe {r.name} failed: {e!r}")
            st = r.stats()
            if not was_reachable or st["probe_ok"]:
                continue
            if st["draining"]:
                # planned exit completed: a scale event, never an
                # incident (counted inside remove_replica)
                self.remove_replica(r.rid, reason="preempt")
                continue
            self._count("fleet_incidents")
            with self._lock:
                self.lifecycle.append({
                    "t": self._clock(), "event": "incident",
                    "replica": r.rid,
                    "reason": "unreachable (not draining)",
                })
            fr = self.flightrec
            if fr is not None:
                fr.trigger("replica_unreachable",
                           f"{r.name} ({r.base_url}) stopped answering "
                           f"health probes")
        return ready

    # ---- dispatch ----

    def _mint(self, requested: str | None) -> str:
        if requested:
            rid = "".join(c if c.isprintable() and c not in '\\"' else "_"
                          for c in str(requested).strip())
            if rid:
                return rid[:128]
        return f"flt-{self._trace_prefix}-{next(self._trace_seq):06x}"

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counts[key] = self.counts.get(key, 0) + n

    def _pick(self, exclude=(), hard_exclude=(),
              owner=None) -> ReplicaState | None:
        """Best admittable replica, preferring ones this request has
        not failed on; falls back to retrying a previously-failed (but
        still admittable) replica over shedding. ``hard_exclude`` is
        never relaxed — the hedge path passes its live attempt's
        replica there, so a hedge can NEVER land on the replica it is
        racing (the fallback would otherwise double down on the slow
        one and corrupt the live-attempt bookkeeping).
        ``breaker.admit()`` is called only on the chosen candidate —
        scoring uses the non-mutating check so an unchosen half-open
        replica keeps its trial slot.

        ``owner`` (ISSUE 20) is the cache-ring owner rid: preferred
        over the load score when it is healthy, admittable, and this
        request has not already failed on it — a PREFERENCE inside the
        same admittance rules, never an override of them, so a dead or
        ejected owner degrades to exactly the pre-affinity pick."""
        pool = [r for r in self.replicas
                if r.rid not in hard_exclude and r.pickable()]
        fresh = [r for r in pool if r.rid not in exclude]
        if owner is not None:
            for r in fresh:
                if r.rid == owner:
                    if r.breaker.admit():
                        return r
                    break
        for r in sorted(fresh or pool, key=lambda r: r.score()):
            if r.breaker.admit():
                return r
        return None

    def _hedge_after_s(self, rid: int) -> float:
        if self.hedge_ms is not None:
            return max(self.hedge_ms, 0.0) / 1e3
        r = self._replica(rid)  # may be unrouted mid-flight (ISSUE 17)
        p99 = r.local_p99_ms() if r is not None else 0.0
        return max(0.1, 2.0 * p99 / 1e3)

    def _feasibility_ms(self) -> tuple[float | None, float | None]:
        """(predicted completion ms, p99 floor ms) on the BEST
        admittable replica, from the health prober's scraped signals
        (ISSUE 16): floor = the replica's rolling p99 alone (even an
        idle replica takes about that long); predicted adds queue
        pressure — each queued/in-flight request is assumed to ride a
        batch of ~8, so depth adds depth/8 p99-units of wait. A
        deliberately conservative model: it only has to separate
        "plausible" from "cannot happen", not predict latency.

        (None, None) when any admittable replica lacks a p99 sample
        yet — feasibility is an optimisation on a warmed-up fleet, not
        a gate that sheds traffic off a cold start."""
        best_est = best_floor = None
        for r in self.replicas:
            if not r.pickable():
                continue
            s = r.stats()
            p99 = float(s["scraped_p99_ms"])
            if p99 <= 0:
                return None, None
            depth = float(s["queue_depth"]) + float(s["inflight"])
            est = p99 * (1.0 + depth / 8.0)
            if best_est is None or est < best_est:
                best_est = est
            if best_floor is None or p99 < best_floor:
                best_floor = p99
        return best_est, best_floor

    def _retry_after_s(self) -> float:
        """The Retry-After hint when shedding: the LARGER of the
        soonest any breaker could re-admit and the queue-depth/p99
        drain estimate (bounded 1..30 s; 5 s when neither signal
        exists). The congestion term is the PR-12 bugfix: breaker
        cooldowns alone under-hint on a fleet that is admittable but
        saturated — shed clients came straight back into the same
        queue instead of backing off proportionally to the congestion
        actually measured."""
        waits = [b for b in
                 (r.breaker.retry_after_s() for r in self.replicas)
                 if b > 0]
        breaker_s = min(waits) if waits else 0.0
        est_ms, _ = self._feasibility_ms()
        congestion_s = (est_ms or 0.0) / 1e3
        base = max(breaker_s, congestion_s) or 5.0
        return min(max(base, 1.0), 30.0)

    def _launch(self, replica: ReplicaState, body: dict, timeout_s: float,
                q: queue.Queue, call: _Call, attempt_no: int) -> None:
        replica.note_sent()
        span_id = ""
        if self.tracer is not None:
            # per-attempt span id, propagated as X-Trace-Parent so the
            # replica's serve.request span nests under THIS attempt in
            # the joined trace (a hedge's two attempts are two distinct
            # parents — both subtrees render, winner and straggler)
            span_id = mint_span_id("att")
            body = dict(body)
            body["trace_parent"] = format_parent(call.tid, span_id)
        threading.Thread(
            target=self._attempt,
            args=(replica, body, timeout_s, q, call, span_id, attempt_no),
            daemon=True, name=f"fleet-try-{call.tid[-10:]}-{attempt_no}",
        ).start()

    def _attempt(self, replica: ReplicaState, body: dict, timeout_s: float,
                 q: queue.Queue, call: _Call, span_id: str = "",
                 attempt_no: int = 0) -> None:
        t0 = time.perf_counter()
        err: BaseException | None = None
        status, payload = 0, None
        try:
            # +2 s grace past the request deadline so a replica-side 504
            # arrives as a typed response instead of a socket timeout
            status, payload = self._transport(replica, body,
                                              timeout_s + 2.0)
        except FleetTransportError as e:
            err = e
        except Exception as e:  # noqa: BLE001 — a transport bug is a failed attempt
            err = e
        lat_ms = (time.perf_counter() - t0) * 1e3
        version = ""
        if err is not None:
            outcome = "transport_errors"
        elif status == 200:
            outcome = "answered"
            version = str((payload or {}).get("param_version", ""))
        elif status in (500, 502):
            outcome = "server_errors"
        else:
            outcome = "rejections"
        replica.note_result(outcome, lat_ms if status == 200 else None,
                            version=version)
        # attempt-level SLO feed (ISSUE 16): transport failures and 5xx
        # burn budget; 4xx/429 are the request's fault or backpressure.
        # Stragglers count too — the replica really did the work.
        self._note_slo_attempt(err is None and status < 500, lat_ms)
        straggler = call.done.is_set()
        if self.tracer is not None:
            # one span per attempt, win or lose: the joined trace shows
            # BOTH sides of a hedge (t0 is perf_counter — the
            # SpanTracer.now_s clock — so this lines up with the
            # replica-side stage spans)
            self.tracer.complete(
                "fleet.attempt", t0, time.perf_counter(),
                trace_id=call.tid, span_id=span_id,
                parent=call.span_id, replica=replica.rid,
                attempt=attempt_no, outcome=outcome,
                status=int(status), straggler=straggler)
        if straggler:
            # the request was already answered by another attempt: this
            # result is wasted compute, NEVER a second answer
            if outcome == "answered":
                self._count("fleet_hedge_waste")
            return
        q.put((replica.rid, status, payload, err, lat_ms))

    def dispatch(self, body: dict, *, timeout_ms: float | None = None,
                 trace_id: str | None = None) -> tuple[int, dict, dict]:
        """Route one request; -> (status, payload, meta).

        ``meta``: replica (the answering rid, or -1), attempts,
        retries, hedges, latency_ms, trace_id, span_id (the root span
        in the router's trace ring; "" with the ring off),
        retry_after_s (shed only). The payload of a 200 is the
        replica's own response (param_version, prediction, stamps, ...)
        untouched.

        This wrapper is the observability boundary (ISSUE 15): it
        mints the trace id, binds it as the logging context, emits the
        ``fleet.request`` root span, and feeds the flight recorder —
        the policy engine underneath (``_dispatch_inner``) is unchanged
        and its served bytes identical with the layer on or off."""
        tid = self._mint(trace_id)
        t0 = time.perf_counter()
        # content fingerprint, hashed ONCE here at the fleet edge
        # (ISSUE 20): it keys owner-affinity + router coalescing below
        # and rides to the replica as X-Fingerprint so nothing
        # downstream re-hashes the arrays
        fp = str(body.get("fingerprint") or "") or None
        if fp is None and self.cache_ring is not None:
            fp = edge_fingerprint(body)
            if fp:
                body = dict(body)
                body["fingerprint"] = fp
        if fp:
            self._count("fleet_fingerprinted")
        with bind_trace(tid):
            status, payload, meta = self._dispatch_coalesced(
                body, fp, timeout_ms=timeout_ms, trace_id=tid)
        if self.tracer is not None:
            self.tracer.complete(
                "fleet.request", t0, time.perf_counter(),
                trace_id=meta["trace_id"], span_id=meta["span_id"],
                status=int(status), replica=meta["replica"],
                attempts=meta["attempts"], retries=meta["retries"],
                hedges=meta["hedges"])
        fr = self.flightrec
        if fr is not None:
            fr.note_request({
                "trace_id": meta["trace_id"], "status": int(status),
                "replica": meta["replica"],
                "attempts": meta["attempts"],
                "retries": meta["retries"], "hedges": meta["hedges"],
                "latency_ms": meta["latency_ms"],
                "param_version": (payload or {}).get(
                    "param_version", ""),
                "reason": (payload or {}).get("reason", ""),
            })
            fr.note_status(int(status))
        j = self.journal
        if j is not None and status == 200:
            # journal the answered request (continual/journal.py): the
            # wire body is the replay payload, the trace id the join
            # key a late POST /label lands on. Hedged/retried attempts
            # shared this trace id, so the journal holds ONE record per
            # client answer whatever the attempt count was.
            pred = (payload or {}).get("prediction")
            try:
                pred = float(pred[0]) if pred is not None else None
            except (TypeError, ValueError, IndexError):
                pred = None
            wire_payload = {k: body[k] for k in ("graph", "structure")
                            if k in body}
            j.note_served(
                trace_id=meta["trace_id"],
                payload=wire_payload or None,
                prediction=pred,
                param_version=str((payload or {}).get(
                    "param_version", "")),
                ts=time.time(),
            )
        return status, payload, meta

    @staticmethod
    def _route_key(body: dict, fp: str) -> str:
        """The ring/coalesce key: the edge fingerprint, tier-qualified
        the same way the replica cache qualifies it — two requests for
        one structure at different precisions are different results and
        must neither share an owner arc by accident nor coalesce."""
        tier = str(body.get("precision") or "f32")
        return fp if tier == "f32" else f"{tier}:{fp}"

    def _dispatch_coalesced(self, body: dict, fp: str | None, *,
                            timeout_ms: float | None = None,
                            trace_id: str | None = None
                            ) -> tuple[int, dict, dict]:
        """Router-side single-flight (ISSUE 20): concurrent dispatches
        of the SAME fingerprint collapse onto one upstream leader;
        followers wait for its answer instead of stampeding the fleet.

        The wait is BOUNDED (``coalesce_wait_ms``, never past the
        follower's own deadline) and every non-200 outcome — leader
        error, leader timeout, wait timeout — falls through to a plain
        ``_dispatch_inner``: coalescing may only ever REMOVE upstream
        work, never add a failure mode (INVARIANTS.md). A follower's
        payload is the leader's bytes with only ``trace_id`` (its own)
        and ``coalesced: True`` swapped in."""
        if not fp or self._coalesce_wait_s <= 0:
            return self._dispatch_inner(
                body, timeout_ms=timeout_ms, trace_id=trace_id)
        key = self._route_key(body, fp)
        with self._sf_lock:
            entry = self._sf.get(key)
            leader = entry is None
            if leader:
                entry = {"event": threading.Event(), "result": None}
                self._sf[key] = entry
        if not leader:
            t0 = self._clock()
            budget_s = (self.default_timeout_ms if timeout_ms is None
                        else float(timeout_ms)) / 1e3
            if entry["event"].wait(min(self._coalesce_wait_s,
                                       max(budget_s, 0.0))):
                result = entry["result"]
                if result is not None and result[0] == 200:
                    _, payload0, meta0 = result
                    self._count("fleet_coalesced")
                    payload = dict(payload0 or {})
                    payload["trace_id"] = trace_id
                    payload["coalesced"] = True
                    meta = dict(meta0)
                    meta.update(
                        trace_id=trace_id, span_id="", coalesced=True,
                        latency_ms=(self._clock() - t0) * 1e3)
                    return 200, payload, meta
                # leader failed — dispatch ourselves, no second wait
            else:
                self._count("fleet_coalesce_timeouts")
            return self._dispatch_inner(
                body, timeout_ms=timeout_ms, trace_id=trace_id)
        result = None
        try:
            result = self._dispatch_inner(
                body, timeout_ms=timeout_ms, trace_id=trace_id)
            return result
        finally:
            # pop BEFORE set: a follower arriving after the pop becomes
            # the next leader instead of reading a finished entry
            with self._sf_lock:
                if self._sf.get(key) is entry:
                    del self._sf[key]
            entry["result"] = result
            entry["event"].set()

    def _dispatch_inner(self, body: dict, *,
                        timeout_ms: float | None = None,
                        trace_id: str | None = None
                        ) -> tuple[int, dict, dict]:
        timeout_ms = (self.default_timeout_ms if timeout_ms is None
                      else float(timeout_ms))
        t_start = self._clock()
        deadline = t_start + timeout_ms / 1e3
        tid = self._mint(trace_id)
        # the idempotency key: EVERY attempt of this request carries the
        # same trace id, so replica-side journals/caches and the
        # loadgen's exactly-once assertion can join duplicates
        body = dict(body)
        body["trace_id"] = tid
        body.setdefault("timeout_ms", timeout_ms)
        call = _Call(tid, mint_span_id("req")
                     if self.tracer is not None else "")
        results: queue.Queue = queue.Queue()
        self._count("fleet_requests")
        # per-class request accounting (ISSUE 19): the body's priority
        # class rides the transport verbatim; the router only counts it
        klass = str(body.get("class") or body.get("priority") or "")
        if klass:
            self._count(f"fleet_class_{klass}_requests")
        # owner-affinity (ISSUE 20): the ring owner of this body's
        # fingerprint is PREFERRED while healthy — its ResultCache holds
        # (or will hold) this key's row. Computed once per dispatch from
        # the live health view; a dead/ejected/draining owner leaves
        # owner_rid pointing at its deterministic ring successor or, on
        # an empty alive set, disengages affinity entirely
        owner_rid = None
        fp = str(body.get("fingerprint") or "") or None
        if self.cache_ring is not None and fp:
            alive = {r.rid for r in self.replicas if r.pickable()}
            owner_rid = self.cache_ring.owner(
                self._route_key(body, fp), alive=alive)
        live: dict[int, float] = {}  # rid -> launch time (hedge timer)
        tried_failed: set[int] = set()
        hedged_rids: set[int] = set()
        launched = retries = hedges = 0
        hedge_spent = False  # one hedge per request (budget, not a fan-out)
        backoff = self.backoff_s
        last_failure = ""

        def meta(replica_id: int = -1, **extra) -> dict:
            return {
                "replica": replica_id, "attempts": launched,
                "retries": retries, "hedges": hedges, "trace_id": tid,
                "span_id": call.span_id,
                "latency_ms": (self._clock() - t_start) * 1e3, **extra,
            }

        # deadline-feasibility admission (ISSUE 19): shed a request the
        # scraped signal plane says cannot complete by its deadline
        # BEFORE it crosses a process boundary — an infeasible request
        # still costs transport, a replica queue slot, and a batcher
        # expiry downstream, and the client learns nothing it couldn't
        # learn right here, cheaper and sooner. Rejection is load
        # shedding, not an error (INVARIANTS.md): 429 when queue
        # congestion is the cause (retry after the hinted backoff
        # helps), 504 when even an idle replica's p99 floor exceeds the
        # deadline (only a longer deadline helps).
        if self.feasibility:
            est_ms, floor_ms = self._feasibility_ms()
            budget_ms = timeout_ms * self.feasibility_margin
            if floor_ms is not None and floor_ms > budget_ms:
                call.done.set()
                retry_after = self._retry_after_s()
                self._count("fleet_infeasible_deadline")
                return 504, {
                    "error": (
                        f"deadline infeasible: every admittable "
                        f"replica's rolling p99 ({floor_ms:.0f} ms) "
                        f"exceeds the {timeout_ms:.0f} ms deadline"),
                    "reason": "infeasible_deadline", "trace_id": tid,
                    "retry_after_s": retry_after,
                }, meta(retry_after_s=retry_after)
            if est_ms is not None and est_ms > budget_ms:
                call.done.set()
                retry_after = self._retry_after_s()
                self._count("fleet_infeasible_queue")
                return 429, {
                    "error": (
                        f"deadline infeasible under current load: "
                        f"predicted completion {est_ms:.0f} ms vs the "
                        f"{timeout_ms:.0f} ms deadline; retry after "
                        f"{retry_after:.0f} s"),
                    "reason": "infeasible_queue", "trace_id": tid,
                    "retry_after_s": retry_after,
                }, meta(retry_after_s=retry_after)

        while True:
            now = self._clock()
            remaining = deadline - now
            if remaining <= 0:
                call.done.set()
                self._count("fleet_deadline_exceeded")
                return 504, {
                    "error": f"fleet deadline exceeded "
                             f"({timeout_ms:.0f} ms, {launched} attempts; "
                             f"last failure: {last_failure or 'none'})",
                    "reason": "timeout", "trace_id": tid,
                }, meta()
            if not live:
                if launched >= self.max_attempts:
                    call.done.set()
                    self._count("fleet_exhausted")
                    return 502, {
                        "error": f"all {launched} attempts failed "
                                 f"(last: {last_failure})",
                        "reason": "upstream_exhausted", "trace_id": tid,
                    }, meta()
                r = self._pick(exclude=tried_failed, owner=owner_rid)
                if r is None:
                    call.done.set()
                    retry_after = self._retry_after_s()
                    self._count("fleet_shed")
                    return 503, {
                        "error": "no replica admittable (all ejected, "
                                 "draining, or unready); load shed",
                        "reason": "no_replicas", "trace_id": tid,
                        "retry_after_s": retry_after,
                    }, meta(retry_after_s=retry_after)
                if owner_rid is not None:
                    self._count("fleet_owner_routed"
                                if r.rid == owner_rid
                                else "fleet_owner_fallback")
                if launched > 0:
                    retries += 1
                    self._count("fleet_retries")
                self._launch(r, body, remaining, results, call, launched)
                live[r.rid] = now
                launched += 1
            # wait for the next attempt result; with a single attempt in
            # flight and hedge budget left, wake at its hedge point
            wait_s = remaining
            hedge_at = None
            if (len(live) == 1 and launched < self.max_attempts
                    and not hedge_spent
                    and (self.hedge_ms is None or self.hedge_ms > 0)):
                rid0, t_launch = next(iter(live.items()))
                hedge_at = t_launch + self._hedge_after_s(rid0)
                wait_s = min(wait_s, max(hedge_at - now, 0.0))
            try:
                rid, status, payload, err, lat_ms = results.get(
                    timeout=max(wait_s, 0.005))
            except queue.Empty:
                now = self._clock()
                if (hedge_at is not None and now >= hedge_at
                        and now < deadline):
                    # deadline-aware hedge: a second attempt on a
                    # DIFFERENT replica races the slow first one. One
                    # hedge per request — spent whether or not a sibling
                    # was available, so an unhedgeable single-replica
                    # fleet waits quietly instead of re-polling
                    hedge_spent = True
                    r2 = self._pick(exclude=tried_failed,
                                    hard_exclude=set(live))
                    if r2 is not None:
                        self._count("fleet_hedges")
                        hedges += 1
                        hedged_rids.add(r2.rid)
                        self._launch(r2, body, deadline - now, results,
                                     call, launched)
                        live[r2.rid] = now
                        launched += 1
                continue
            live.pop(rid, None)
            if err is None and status == 200:
                if call.done.is_set():
                    # structurally unreachable (one coordinator, one
                    # consumer) — counted so the loadgen can assert it
                    self._count("fleet_duplicate_answers")
                call.done.set()
                self._count("fleet_answered")
                if klass:
                    self._count(f"fleet_class_{klass}_answered")
                if rid in hedged_rids:
                    self._count("fleet_hedge_wins")
                total_ms = (self._clock() - t_start) * 1e3
                self._lat_rolling.add(total_ms)
                h = self.hists.get("fleet_latency_ms_hist")
                if h is not None:
                    # client-perceived end-to-end latency (retries and
                    # hedges folded in) — the mergeable twin of the
                    # rolling quantiles above
                    h.observe(total_ms)
                if (fp and self.peer_fill and owner_rid is not None
                        and rid != owner_rid
                        and self._transport is http_transport
                        and (payload or {}).get("prediction")
                        is not None):
                    # owner-miss: a non-owner answered (fallback,
                    # retry, or hedge won). Ship the row back to the
                    # ring owner OFF-PATH so its cache still warms —
                    # the client's answer never waits on this hop
                    threading.Thread(
                        target=self._peer_fill,
                        args=(owner_rid, fp, payload, body),
                        daemon=True, name="fleet-peer-fill",
                    ).start()
                return 200, payload, meta(rid)
            if err is None and status in PASSTHROUGH_STATUS:
                # about the request, not the replica: hand it back
                call.done.set()
                self._count("fleet_passthrough_rejects")
                return status, payload or {}, meta(rid)
            # retryable: transport failure or 429/500/502/503
            tried_failed.add(rid)
            fr_ = self._replica(rid)
            rname = fr_.name if fr_ is not None else f"replica{rid}"
            if err is not None:
                self._count("fleet_transport_errors")
                last_failure = f"{rname}: {err!r}"
            else:
                self._count(f"fleet_upstream_{status}")
                detail = (payload or {}).get("error", "")
                last_failure = f"{rname}: HTTP {status} {detail}"
            if live:
                continue  # a hedge is still racing; let it win first
            if launched < self.max_attempts:
                # exponential backoff + jitter before the next attempt.
                # A plain sleep, NOT self._stop.wait: that event is the
                # health poller's shutdown latch, and a stop() landing
                # mid-drain would collapse every in-flight request's
                # backoff to zero (hot-looping retries at the draining
                # replicas). The sleep is bounded by the request
                # deadline, so it cannot outlive the drain by much.
                delay = backoff * (1.0 + self.jitter * self._rng.random())
                backoff = min(backoff * self.backoff_mult,
                              self.max_backoff_s)
                remaining = deadline - self._clock()
                if remaining > 0 and delay > 0:
                    time.sleep(min(delay, remaining))

    def _peer_fill(self, owner_rid: int, fp: str, payload: dict,
                   body: dict) -> None:
        """Ship an owner-miss answer to the ring owner's /cache-fill
        (daemon thread, off the request path). Best-effort by design:
        the owner re-qualifies the key and version-checks at fill time
        (serve/server.py cache_fill), so a stale or lost fill costs one
        future miss, never a wrong answer."""
        r = self._replica(owner_rid)
        if r is None:
            return
        from cgnn_tpu.fleet.replica import http_post_json

        t0 = time.perf_counter()
        try:
            status, resp = http_post_json(
                r.base_url + "/cache-fill",
                {
                    "fingerprint": fp,
                    "prediction": payload.get("prediction"),
                    "param_version": payload.get("param_version", ""),
                    "precision": (payload.get("precision")
                                  or body.get("precision")),
                    "wire": payload.get("wire", "featurized"),
                },
                timeout_s=5.0)
        except FleetTransportError:
            self._count("fleet_peer_fill_errors")
            return
        finally:
            h = self.hists.get("fleet_owner_hop_ms_hist")
            if h is not None:
                h.observe((time.perf_counter() - t0) * 1e3)
        if status == 200 and (resp or {}).get("filled"):
            self._count("fleet_peer_fills")
        elif status == 200:
            # owner declined: the fill raced a param swap (stale)
            self._count("fleet_peer_fill_stale")
        else:
            self._count("fleet_peer_fill_errors")

    # ---- observation ----

    def trace_window(self, since_s: float | None = None) -> dict | None:
        """The router's span ring as a joinable `/trace` window
        (observe/trace_join.py); None with the ring off."""
        if self.tracer is None:
            return None
        w = self.tracer.window(since_s=since_s)
        w["role"] = "router"
        return w

    def replica_trace_urls(self) -> list:
        """The fleet's `/trace`-capable endpoints (every replica's base
        url) — what a joined trace or incident bundle pulls."""
        return [r.base_url for r in self.replicas]

    def attach_flight_recorder(self, recorder) -> None:
        """Wire an observe.flightrec.FlightRecorder: every dispatch
        outcome lands in its ring, statuses feed the 5xx burst trigger,
        and every replica breaker's trip fires an incident dump — the
        bundle then holds the joined fleet trace of the minutes that
        led to the ejection."""
        self.flightrec = recorder
        for r in self.replicas:
            r.breaker.on_trip = self._on_breaker_trip

    def _on_breaker_trip(self, breaker) -> None:
        fr = self.flightrec
        if fr is not None:
            fr.trigger("breaker_trip",
                       f"{breaker.name}: open after "
                       f"{breaker.k} consecutive failures")

    def versions(self) -> dict:
        """param_version per replica (the rolling-promotion view)."""
        return {r.rid: r.version for r in self.replicas}

    def ready_count(self) -> int:
        return sum(1 for r in self.replicas if r.ready)

    def admittable(self) -> bool:
        return any(r.pickable() for r in self.replicas)

    def stats(self) -> dict:
        with self._lock:
            counts = dict(self.counts)
            lifecycle = list(self.lifecycle)
        out = {
            "counts": counts,
            "lifecycle": lifecycle,
            "replicas": {str(r.rid): r.stats() for r in self.replicas},
            "versions": {str(k): v for k, v in self.versions().items()},
            "ready": self.ready_count(),
            "rolling_latency_ms": self._lat_rolling.quantiles(),
        }
        # fleet SLO + embedded tsdb health (ISSUE 16)
        if self.slo is not None:
            out["slo"] = self.slo.state()
        if self.tsdb is not None:
            out["tsdb"] = self.tsdb.stats()
        # continual-learning plane (ISSUE 18)
        if self.journal is not None:
            out["journal"] = self.journal.stats()
        if self.canary is not None:
            out["canary"] = self.canary.stats()
        # one-fleet-cache plane (ISSUE 20)
        if self.cache_ring is not None:
            out["cache_ring"] = self.cache_ring.stats()
        return out

    def _registry_snapshot(self) -> dict:
        """The fleet provider behind GET /metrics: router counters,
        per-replica gauges (folded into ``replica``-labeled families by
        observe/export.py), and the rolling latency summaries."""
        with self._lock:
            counts = dict(self.counts)
        counters = {k: float(v) for k, v in counts.items()}
        gauges = {
            "fleet_replicas": float(len(self.replicas)),
            "fleet_replicas_ready": float(self.ready_count()),
            "fleet_replicas_admittable": float(
                sum(1 for r in self.replicas if r.pickable())),
            "fleet_trace_ring": float(self.tracer is not None),
        }
        if self.tracer is not None:
            gauges["fleet_trace_dropped"] = float(self.tracer.dropped)
        fr = self.flightrec
        if fr is not None:
            frs = fr.stats()
            gauges["fleet_flightrec_bundles"] = float(frs["bundles"])
            gauges["fleet_flightrec_suppressed"] = float(
                frs["suppressed"])
        series = {}
        q = self._lat_rolling.quantiles()
        if q:
            series["fleet_latency_ms"] = q
        breaker_num = {"closed": 0.0, "half_open": 0.5, "open": 1.0}
        for r in self.replicas:
            s = r.stats()
            i = r.rid
            gauges[f"replica{i}_inflight"] = float(s["inflight"])
            gauges[f"replica{i}_ready"] = float(s["ready"])
            gauges[f"replica{i}_queue_depth"] = float(s["queue_depth"])
            gauges[f"replica{i}_scraped_p99_ms"] = float(
                s["scraped_p99_ms"])
            gauges[f"replica{i}_breaker_open"] = breaker_num.get(
                s["breaker"]["state"], 1.0)
            gauges[f"replica{i}_answered"] = float(
                s["counts"]["answered"])
            rq = r.rolling.quantiles()
            if rq:
                series[f"replica{i}_latency_ms"] = rq
        out = {"counters": counters, "gauges": gauges, "series": series}
        # the metrics-truth layer (ISSUE 16): mergeable histograms under
        # distinct `_hist` names + SLO/tsdb health gauges
        if self.hists:
            out["histograms"] = {
                name: h.snapshot() for name, h in self.hists.items()
            }
        if self.canary is not None:
            # per-version shadow-vs-live MAE + shadow latency (ISSUE
            # 18): param_version-labeled families export.py renders
            out.setdefault("histograms", {}).update(
                self.canary.metrics_histograms())
        if self.journal is not None:
            js = self.journal.stats()
            for k in ("served", "joined", "duplicate_joins",
                      "unmatched_labels"):
                counters[f"fleet_journal_{k}"] = float(js[k])
        if self.slo is not None:
            gauges.update(self.slo.gauges())
        if self.tsdb is not None:
            ts = self.tsdb.stats()
            gauges["tsdb_series"] = float(ts["series"])
            gauges["tsdb_points"] = float(ts["points"])
            gauges["tsdb_dropped_series"] = float(ts["dropped_series"])
        # one-fleet-cache derived ratios (ISSUE 20)
        if self.cache_ring is not None:
            gauges["fleet_cache_ring_replicas"] = float(
                len(self.cache_ring))
        from cgnn_tpu.observe.gauges import cache_gauges

        gauges.update(cache_gauges(counters, gauges))
        return out

    def fleet_metrics_text(self, timeout_s: float = 2.0) -> str:
        """``GET /metrics/fleet``: scrape every replica's ``/metrics``,
        merge the histogram families label-set by label-set, and render
        ONE fleet-wide exposition.

        This is the payoff of mergeable histograms (observe/hist.py):
        per-replica quantile summaries cannot be combined, but bucket
        counts add — the merged family here is BIT-IDENTICAL in counts
        to a histogram of the pooled raw observations (pinned by
        tests/test_slo.py). Labels are preserved through the merge;
        scrape failures degrade (the family merges what answered, the
        ``cgnn_fleet_scrape_errors`` gauge says so) rather than 500ing
        the fleet view."""
        from cgnn_tpu.fleet.replica import http_get_text
        from cgnn_tpu.observe import hist as _hist
        from cgnn_tpu.observe.export import parse_prometheus_text

        per_family: dict = {}  # fullname -> [ {label_key: snapshot} ]
        scraped = errors = 0
        for r in self.replicas:
            try:
                text = http_get_text(r.base_url + "/metrics", timeout_s)
                fams = parse_prometheus_text(text)
            except Exception as e:  # noqa: BLE001 — degrade, don't 500
                errors += 1
                self._log(f"fleet: /metrics scrape {r.name} "
                          f"failed: {e!r}")
                continue
            scraped += 1
            for fname, fam in fams.items():
                hmap = fam.get("histogram")
                if fam.get("type") == "histogram" and hmap:
                    per_family.setdefault(fname, []).append(hmap)
        # fold the router's OWN mergeable families in (ISSUE 20): the
        # owner-hop and fleet-latency histograms live router-side, not
        # on any replica, and the fleet view should carry them; the
        # per-(tier,form) cache-lookup families arrive from the replica
        # scrapes above and merge label-set by label-set
        for name, h in self.hists.items():
            per_family.setdefault(f"cgnn_{name}", []).append(
                {"": h.snapshot()})
        lines = [
            "# TYPE cgnn_fleet_scrape_replicas gauge",
            f"cgnn_fleet_scrape_replicas {float(scraped)}",
            "# TYPE cgnn_fleet_scrape_errors gauge",
            f"cgnn_fleet_scrape_errors {float(errors)}",
        ]
        for fname in sorted(per_family):
            merged = _hist.merge_snapshot_maps(per_family[fname])
            lines.append(f"# TYPE {fname} histogram")
            for key in sorted(merged):
                lines.extend(_hist.snapshot_exposition_lines(
                    fname, merged[key], _hist.parse_labels(key)))
        return "\n".join(lines) + "\n"
