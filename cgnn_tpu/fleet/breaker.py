"""Per-replica circuit breaker: eject after K consecutive failures,
half-open probe re-admission.

The state machine (injectable clock, synchronously testable):

- ``closed``    — healthy; requests flow. ``k`` CONSECUTIVE failures
  (any success resets the streak) trip it open.
- ``open``      — ejected; ``admit()`` refuses everything until
  ``cooldown_s`` has passed. Each re-open without an intervening close
  doubles the cooldown (bounded by ``max_cooldown_s``) so a flapping
  replica backs itself off instead of absorbing a probe per tick.
- ``half_open`` — the cooldown expired; ``admit()`` grants exactly ONE
  in-flight trial request (concurrent callers are refused until it
  resolves). Trial success — or a successful health probe
  (``record_probe_success``, the router's poller seeing ``ready``) —
  closes the breaker; trial failure re-opens it with the doubled
  cooldown.

``admit()`` MUTATES (it claims the half-open trial), so callers score
candidates with ``would_admit()`` first and claim only the one they
picked — a scored-but-unchosen replica must not leak its trial slot.
"""

from __future__ import annotations

import time
from typing import Callable

from cgnn_tpu.analysis import racecheck

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(
        self,
        k: int = 3,
        cooldown_s: float = 2.0,
        max_cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        name: str = "fleet.breaker",
    ):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        self.k = int(k)
        self.base_cooldown = float(cooldown_s)
        self.max_cooldown = float(max_cooldown_s)
        self._clock = clock
        self._lock = racecheck.make_lock(name)
        # all mutated under self._lock (graftcheck GC-LOCKSHARE)
        self._state = CLOSED
        self._failures = 0          # consecutive-failure streak
        self._cooldown = float(cooldown_s)
        self._opened_at = 0.0
        self._trial_inflight = False
        self.opens = 0              # lifetime trips (telemetry)
        self.closes = 0
        self.name = name
        # incident hook (ISSUE 15): called AFTER a closed->open /
        # half_open->open transition, outside the lock (the flight
        # recorder's trigger spawns a dump — IO must never run under a
        # breaker lock the request path contends on; GC-BLOCKING).
        # Assigned post-construction by whoever owns the recorder.
        self.on_trip: Callable | None = None

    # ---- observation ----

    @property
    def state(self) -> str:
        """Current state; promotes open -> half_open on cooldown expiry
        (observation only — the trial slot is claimed by admit())."""
        with self._lock:
            return self._state_locked(self._clock())

    def _state_locked(self, now: float) -> str:
        if self._state == OPEN and now - self._opened_at >= self._cooldown:
            self._state = HALF_OPEN
            self._trial_inflight = False
        return self._state

    def would_admit(self) -> bool:
        """Non-mutating admission check (candidate scoring)."""
        with self._lock:
            s = self._state_locked(self._clock())
            if s == CLOSED:
                return True
            if s == HALF_OPEN:
                return not self._trial_inflight
            return False

    def retry_after_s(self) -> float:
        """How long until this breaker could admit again (0 = now) —
        the Retry-After hint when a whole tier is ejected."""
        with self._lock:
            s = self._state_locked(self._clock())
            if s != OPEN:
                return 0.0
            return max(
                0.0, self._cooldown - (self._clock() - self._opened_at)
            )

    # ---- the request path ----

    def admit(self) -> bool:
        """Claim admission for one request (the half-open TRIAL when
        half-open). The claimer MUST later call record_success or
        record_failure — that is what releases the trial slot."""
        with self._lock:
            s = self._state_locked(self._clock())
            if s == CLOSED:
                return True
            if s == HALF_OPEN and not self._trial_inflight:
                self._trial_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._trial_inflight = False
            if self._state != CLOSED:
                self._state = CLOSED
                self._cooldown = self.base_cooldown
                self.closes += 1

    def record_probe_success(self) -> None:
        """A health probe (not a served request) found the replica
        ready. Re-admits from HALF-OPEN only: while the cooldown is
        still running the breaker stays open even if /healthz looks
        fine — K consecutive DISPATCH failures on a ready-looking
        replica is exactly the wedged-server case the cooldown exists
        to keep traffic away from."""
        with self._lock:
            s = self._state_locked(self._clock())
            if s == HALF_OPEN:
                self._failures = 0
                self._trial_inflight = False
                self._state = CLOSED
                self._cooldown = self.base_cooldown
                self.closes += 1

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            now = self._clock()
            s = self._state_locked(now)
            self._failures += 1
            if s == HALF_OPEN:
                # failed trial: back off harder each consecutive trip
                self._trial_inflight = False
                self._cooldown = min(self._cooldown * 2.0,
                                     self.max_cooldown)
                self._state = OPEN
                self._opened_at = now
                self.opens += 1
                tripped = True
            elif s == CLOSED and self._failures >= self.k:
                self._state = OPEN
                self._opened_at = now
                self.opens += 1
                tripped = True
            # already OPEN: stragglers from in-flight attempts land here;
            # they neither extend nor restart the cooldown
        if tripped and self.on_trip is not None:
            try:
                self.on_trip(self)
            except Exception:  # noqa: BLE001 — an incident hook must
                pass           # never fail the request path it rides

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self._state_locked(self._clock()),
                "consecutive_failures": self._failures,
                "cooldown_s": self._cooldown,
                "opens": self.opens,
                "closes": self.closes,
            }
