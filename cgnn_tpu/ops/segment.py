"""Segment reductions and the edge-aggregation dispatch point.

The reference's hottest device loop is the per-edge gather + per-node
scatter-sum inside its conv layer (SURVEY.md §3.3): on GPU it is ATen
``index_select`` + ``sum(dim=1)``. The TPU-native equivalents (SURVEY.md §2
native table) are:

- ``xla``: `jax.ops.segment_sum` over a flat COO edge list. XLA lowers this
  to a sorted-scatter that fuses with the surrounding elementwise work and is
  deterministic per compilation (unlike CUDA atomicAdd scatter).
- ``pallas``: a hand-written gather-scatter kernel (cgnn_tpu.ops.pallas_scatter)
  for the cases where XLA's scatter is not bandwidth-optimal.

`aggregate_edge_messages` is the single dispatch point; the model layer never
calls a backend directly, so benchmarking/falling back is a one-flag change.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_DEFAULT_IMPL = "xla"
_VALID_IMPLS = ("xla", "pallas", "sort")

# gather_transpose differentiation mechanism. "linear_call" (default,
# round 4+) composes with repeated/forward-mode AD (the force task's
# grad-over-grad needs it); "custom_vjp" is the round-3 implementation,
# kept ONLY so the interleaved A/B harness (scripts/bench_ab.py) can
# measure both mechanisms in one process — it emits the same transpose
# math but rejects second-order AD.
_TRANSPOSE_IMPL = "linear_call"

# jax 0.4.37 (this container) ships linear_call WITHOUT a
# differentiation rule ("Differentiation rule for 'linear_call' not
# implemented") — the second half of the 43 pre-existing seed failures
# (the first was shard_map resolution, parallel/compat.py). Probed once,
# lazily; when the rule is missing, gather_transpose binds an equivalent
# custom primitive with the SAME transpose body registered directly
# (impl/abstract/jvp/transpose/lowering) — which, like linear_call,
# composes with repeated differentiation (grad-over-grad pins this in
# tests). CI's newer jax never takes this path.
_LINEAR_CALL_GRAD: bool | None = None


def _linear_call_differentiable() -> bool:
    global _LINEAR_CALL_GRAD
    if _LINEAR_CALL_GRAD is None:
        import numpy as np

        idx = jnp.asarray(np.zeros(1, np.int32))
        try:
            jax.grad(lambda n: jax.custom_derivatives.linear_call(
                lambda r, x: jnp.take(x, r[0], axis=0),
                lambda r, ct: jax.ops.segment_sum(ct, r[0], num_segments=1),
                (idx,), n).sum())(jnp.zeros((1, 1), jnp.float32))
            _LINEAR_CALL_GRAD = True
        except NotImplementedError:
            _LINEAR_CALL_GRAD = False
    return _LINEAR_CALL_GRAD


def _transpose_cotangent(ct, slots, msk, o_slots, o_nodes, o_mask,
                         num_nodes: int):
    """The shared cotangent transpose ([E, F] -> [N, F]) — ONE body for
    every AD mechanism (linear_call / custom_vjp / the compat primitive)
    so an A/B isolates the mechanism, never the math.

    in_slots arrives pre-flattened (pack_graphs): a device-side
    [N, In] -> [N*In] flatten is a tiled->linear relayout that measured
    0.75 ms/step under the epoch scan. Accumulation stays in the
    cotangent dtype: matches the scatter-add's accumulation precision,
    and an f32 upcast doubles the [N, In, F] intermediate's bytes for no
    measured accuracy gain (full-step bf16: 16.0 ms vs f32-acc 17.5 ms
    vs scatter 18.8 ms).
    """
    contrib = jnp.take(ct, slots, axis=0).reshape(*msk.shape, ct.shape[-1])
    grad = (contrib * msk[..., None].astype(ct.dtype)).sum(axis=1)
    if o_slots is not None:
        rows = jnp.take(ct, o_slots, axis=0)
        rows = rows * o_mask[:, None].astype(ct.dtype)
        grad = grad + jax.ops.segment_sum(
            rows, o_nodes, num_segments=num_nodes, indices_are_sorted=True,
        )
    return grad


_GATHER_TR_P = None


def _gather_transpose_primitive():
    """Build (once) the compat primitive for jax without the linear_call
    differentiation rule. Operands: (nodes, neighbors, in_slots, in_mask
    [, over_slots, over_nodes, over_mask]) with static ``has_over``;
    only ``nodes`` is linear."""
    global _GATHER_TR_P
    if _GATHER_TR_P is not None:
        return _GATHER_TR_P
    from jax import core
    from jax.interpreters import ad, mlir

    p = core.Primitive("cgnn_gather_transpose")

    def _impl(nodes, neighbors, *rest, has_over):
        return jnp.take(nodes, neighbors, axis=0)

    p.def_impl(_impl)

    def _abstract(nodes, neighbors, *rest, has_over):
        return core.ShapedArray(
            (neighbors.shape[0],) + tuple(nodes.shape[1:]), nodes.dtype
        )

    p.def_abstract_eval(_abstract)
    mlir.register_lowering(p, mlir.lower_fun(_impl, multiple_results=False))

    def _jvp(primals, tangents, *, has_over):
        out = p.bind(*primals, has_over=has_over)
        dn = tangents[0]
        if type(dn) is ad.Zero:
            return out, ad.Zero.from_value(out)
        return out, p.bind(dn, *primals[1:], has_over=has_over)

    ad.primitive_jvps[p] = _jvp

    def _transpose(ct, nodes, neighbors, in_slots, in_mask, *over,
                   has_over):
        assert ad.is_undefined_primal(nodes), (
            "gather_transpose is linear in nodes only"
        )
        o_slots, o_nodes, o_mask = over if has_over else (None, None, None)
        grad = _transpose_cotangent(
            ct, in_slots, in_mask, o_slots, o_nodes, o_mask,
            nodes.aval.shape[0],
        )
        return (grad,) + (None,) * (3 + len(over))

    ad.primitive_transposes[p] = _transpose
    _GATHER_TR_P = p
    return p


def set_transpose_impl(impl: str) -> None:
    global _TRANSPOSE_IMPL
    if impl not in ("linear_call", "custom_vjp"):
        raise ValueError(f"unknown transpose impl {impl!r}")
    _TRANSPOSE_IMPL = impl


def set_default_aggregation_impl(impl: str) -> None:
    """Select the global default edge-aggregation backend ('xla'|'pallas'|'sort')."""
    global _DEFAULT_IMPL
    if impl not in _VALID_IMPLS:
        raise ValueError(f"impl must be one of {_VALID_IMPLS}, got {impl!r}")
    if impl == "pallas":  # fail eagerly, not from inside a jitted trace
        import cgnn_tpu.ops.pallas_scatter  # noqa: F401
    _DEFAULT_IMPL = impl


def gather(values: jax.Array, indices: jax.Array) -> jax.Array:
    """values[indices] — the edge-endpoint gather ([N, F] + [E] -> [E, F])."""
    return jnp.take(values, indices, axis=0)


def gather_transpose(
    nodes: jax.Array,  # [N, F]
    neighbors: jax.Array,  # [E] i32
    in_slots: jax.Array,  # [N*In] i32 FLAT — edge slots grouped by neighbor
    in_mask: jax.Array,  # [N, In] — 1 where the slot entry is a real edge
    over_slots: jax.Array | None = None,  # [O] i32 overflow edge slots
    over_nodes: jax.Array | None = None,  # [O] i32 (non-decreasing)
    over_mask: jax.Array | None = None,  # [O]
) -> jax.Array:
    """``nodes[neighbors]`` with a SCATTER-FREE (or scatter-light) backward.

    The forward is the plain neighbor gather. Its autodiff backward is a
    scatter-add of the [E, F] cotangent into [N, F] — the same XLA scatter
    the dense edge-slot layout removed from the forward aggregation (it
    runs ~50x below HBM bandwidth on TPU). Given the host-precomputed
    transpose mapping ``in_slots`` (pack_graphs ``in_cap``/``over_cap``),
    the backward becomes gather(ct, in_slots) + masked sum over the
    in-degree axis — a row gather plus a dense reduction, both
    full-bandwidth ops.

    TWO-TIER mode (``over_*`` given; pack_graphs ``over_cap``): tier 1 is
    [N, M] (no in-degree padding — the [N, 2M] single-tier gather was the
    step's largest single op at mean in-degree M, half padding bytes), and
    the ~7% of edges with rank >= M arrive via a node-sorted segment-sum
    over the small overflow list — a scatter 15x smaller than the one this
    path replaces.

    Equivalence to the plain gather's VJP requires the cotangent to be
    zero on edge slots missing from the mapping (padding slots). CGConv
    guarantees this: messages are multiplied by ``edge_mask`` and masked
    BatchNorm statistics exclude padding, so no gradient path reaches a
    padded slot's ``v_j``.

    Implemented with ``jax.custom_derivatives.linear_call`` rather than
    ``custom_vjp``: the gather is linear in ``nodes``, and a linear op with
    a declared transpose composes with forward-mode AD and with REPEATED
    differentiation — which the force task needs (grad-over-grad: the
    outer params gradient linearizes the inner positions gradient, and
    ``custom_vjp`` rejects that jvp). The transpose body is the same
    gather + masked in-degree reduction as before.
    """
    num_nodes = nodes.shape[0]

    if _TRANSPOSE_IMPL == "custom_vjp":  # round-3 mechanism (A/B only)

        @jax.custom_vjp
        def g(n):
            return jnp.take(n, neighbors, axis=0)

        def g_fwd(n):
            return g(n), None

        def g_bwd(_, ct):
            return (_transpose_cotangent(ct, in_slots, in_mask, over_slots,
                                         over_nodes, over_mask, num_nodes),)

        g.defvjp(g_fwd, g_bwd)
        return g(nodes)

    if not _linear_call_differentiable():
        # jax without the linear_call diff rule (in-container 0.4.37):
        # same math, bound through the compat primitive above
        p = _gather_transpose_primitive()
        if over_slots is not None:
            return p.bind(nodes, neighbors, in_slots, in_mask,
                          over_slots, over_nodes, over_mask, has_over=True)
        return p.bind(nodes, neighbors, in_slots, in_mask, has_over=False)

    def fwd(res, n):
        nbrs = res[0]
        return jnp.take(n, nbrs, axis=0)

    def trans(res, ct):  # ct: [E, F] -> [N, F]
        _, slots, msk, o_slots, o_nodes, o_mask = res
        return _transpose_cotangent(ct, slots, msk, o_slots, o_nodes,
                                    o_mask, num_nodes)

    res = (neighbors, in_slots, in_mask, over_slots, over_nodes, over_mask)
    return jax.custom_derivatives.linear_call(fwd, trans, res, nodes)


def segment_sum(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    """Sum ``data`` rows into ``num_segments`` buckets (deterministic on TPU)."""
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_mean(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    weights: jax.Array | None = None,
) -> jax.Array:
    """Masked segment mean: sum(w*x)/sum(w); empty segments return 0.

    ``weights`` (e.g. a node mask) keeps padding rows out of both numerator
    and denominator — this is the masked pooling from SURVEY.md §7 "hard
    parts" #3.
    """
    if weights is not None:
        data = data * weights[..., None]
        denom = segment_sum(weights, segment_ids, num_segments)
    else:
        denom = segment_sum(jnp.ones(data.shape[0], data.dtype), segment_ids, num_segments)
    total = segment_sum(data, segment_ids, num_segments)
    return total / jnp.maximum(denom, 1.0)[..., None]


def _aggregate_sort(messages: jax.Array, centers: jax.Array, num_nodes: int) -> jax.Array:
    """Sort-based aggregation: sort edges by center then segment-sum.

    On TPU, scatter over a *sorted* index vector lowers to a cheaper
    monotonic-update pattern; useful when the batcher cannot pre-sort.
    """
    order = jnp.argsort(centers)
    return jax.ops.segment_sum(
        jnp.take(messages, order, axis=0),
        jnp.take(centers, order),
        num_segments=num_nodes,
        indices_are_sorted=True,
    )


def aggregate_edge_messages(
    messages: jax.Array,
    centers: jax.Array,
    num_nodes: int,
    impl: str | None = None,
    indices_are_sorted: bool = True,
) -> jax.Array:
    """Scatter-sum per-edge messages into per-node accumulators.

    The batcher (data/graph.py) emits edges sorted by center node, so the
    default path tells XLA ``indices_are_sorted`` and avoids a device sort.
    """
    impl = impl or _DEFAULT_IMPL
    if impl == "xla":
        return jax.ops.segment_sum(
            messages, centers, num_segments=num_nodes,
            indices_are_sorted=indices_are_sorted,
        )
    if impl == "sort":
        return _aggregate_sort(messages, centers, num_nodes)
    if impl == "pallas":
        from cgnn_tpu.ops.pallas_scatter import segment_sum_pallas

        return segment_sum_pallas(messages, centers, num_nodes)
    raise ValueError(f"unknown aggregation impl {impl!r}")
