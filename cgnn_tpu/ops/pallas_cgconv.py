"""Whole-conv fused CGConv: gather -> fc_full -> BN -> gate -> reduce in
one Pallas pass structure (ROADMAP item 2, the §3b/§6b successor).

PERF.md's post-r3 position: the flagship step is memory-bound, and the two
narrow kernel attempts (windowed one-hot gather, fused BN epilogue) both
measured NEGATIVE because any custom op cut at a sub-conv boundary forces
``z``/``dz`` through HBM and loses to XLA's producer/consumer fusion
(§6b). The remaining structural lever is to fuse the ENTIRE dense-branch
conv so no opaque boundary is left to pay: per 128-node block, DMA the
block's neighbor window + the conv parameters to VMEM once, run the
``fc_full`` contraction on the MXU in-kernel, apply the masked-BN
normalize + sigmoid*softplus gate, and reduce over the M edge slots
in-register — writing ONLY the aggregated ``[N, F]`` message sum back to
HBM. The ``v_j`` gather result and the ``z = fc_full(...)`` activation
never exist in HBM at all, in either direction:

- forward: two input passes (a stats pass for the masked BN moments — a
  global reduction that must complete before any element normalizes —
  and an apply pass), ZERO intermediate writes. Residuals are just
  ``(mean, rstd)``; versus the unfused path's staged ``v_j`` ([E, F])
  and partially-materialized ``z`` ([E, 2F]).
- backward: rematerialized — the custom VJP re-derives gradients through
  a structured jnp twin of the forward (the §6b-measured property that
  XLA fuses ``dz`` into the matmul backwards at near-roofline makes a
  hand-blocked backward a boundary loss, not a win), so the forward
  saves no activations.

Two implementations behind one flag (the §6b methodology):

- ``impl='xla'``: the structured jnp twin as the forward too — measures
  what the minimal-pass STRUCTURE + custom-VJP rematerialization buy
  before any hand scheduling;
- ``impl='pallas'``: the blocked TPU kernels described above.

Window contract (the in-kernel gather): the packer places each graph's
nodes contiguously and every edge's neighbor lies inside its own graph,
so the neighbors of a 128-row node block live in a bounded window around
the block (ops/pallas_gather.py proved the locality). ``window=0`` uses
the whole node range (always correct, O(E*N) one-hot work — tests);
``window=W`` with ``W >= window_width(max_graph_nodes)`` (pallas_gather)
bounds the per-block DMA; callers own the bound (train.py derives it
from the dataset). An out-of-window REAL neighbor would silently gather
zeros — the wrapper therefore only accepts ``window > 0`` together with
the caller's explicit bound.

Numerical contract: identical to the dense CGConv branch in
models/cgcnn.py — ``_SplitFcFull`` + one-pass-f32 MaskedBatchNorm + gate
+ edge mask + sum — to f32 roundoff (tests/test_ops.py
TestFusedCGConv). The kernel computes matmuls with f32 accumulation and
all BN/gate math in f32 regardless of the storage dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cgnn_tpu.ops.segment import gather, gather_transpose

_TN = 128  # node rows per block AND per window tile (lane width)

# interpret-mode escape hatch: newer jax has
# pltpu.force_tpu_interpret_mode(); this container's 0.4.37 does not
# (the reason the older pallas tests are among the pre-existing seed
# failures), but pallas_call(interpret=True) works everywhere — so this
# module threads an explicit flag and exposes a context manager that
# uses whichever mechanism the running jax supports.
_INTERPRET = False


class interpret_mode:
    """Run this module's kernels interpreted (CPU-testable) — the
    version-portable twin of ``pltpu.force_tpu_interpret_mode()``."""

    def __enter__(self):
        global _INTERPRET
        self._ctx = None
        force = getattr(pltpu, "force_tpu_interpret_mode", None)
        if force is not None:
            self._ctx = force()
            self._ctx.__enter__()
        self._prev = _INTERPRET
        _INTERPRET = True
        return self

    def __exit__(self, *exc):
        global _INTERPRET
        _INTERPRET = self._prev
        if self._ctx is not None:
            return self._ctx.__exit__(*exc)
        return False


def window_width(max_graph_nodes: int) -> int:
    """Static window bound for a dataset (see ops/pallas_gather.py)."""
    need = 2 * _TN + 2 * (int(max_graph_nodes) - 1)
    return max(_TN, -(-need // _TN) * _TN)


def _win_starts(n_blocks: int, n_pad: int, window: int):
    """[NB] i32 aligned window starts: block b's graphs' node span
    sits inside [ws[b], ws[b] + window) (coverage pinned by test)."""
    import numpy as np

    pad_left = max((window - 2 * _TN) // 2, 0)
    ws = np.arange(n_blocks, dtype=np.int64) * _TN - pad_left
    ws = (ws // _TN) * _TN
    ws = np.clip(ws, 0, max(n_pad - window, 0))
    return jnp.asarray(ws.astype(np.int32))


# ---------------------------------------------------------------------------
# structured jnp twin (impl='xla' forward; the rematerialized backward; and
# the numerics reference the Pallas kernels must match)
# ---------------------------------------------------------------------------


def _masked_stats(z, mask):
    """Shifted one-pass masked moments over (N, M) -> f32 (the exact
    ops/norm.py estimator, shared with ops/fused_epilogue.py)."""
    zf = z.astype(jnp.float32)
    shift = jax.lax.stop_gradient(zf[:1].mean(axis=(0, 1)))
    zs = zf - shift
    m = mask.astype(jnp.float32)
    n_real = m.sum()
    zm = zs * m[..., None]
    s1 = zm.sum(axis=(0, 1))
    s2 = (zm * zs).sum(axis=(0, 1))
    n = jnp.maximum(n_real, jnp.float32(1.0))
    mean_s = s1 / n
    var = jnp.maximum(s2 / n - mean_s * mean_s, jnp.float32(0.0))
    return mean_s + shift, var, n_real


def _gate_sum(y, mask):
    # where-select, not multiply: padding slots of the TAIL node block
    # read out-of-range garbage in the Pallas kernels (both interpret
    # and Mosaic pad with arbitrary bytes), and 0 * NaN would poison the
    # reduction that a 0-select cannot. f32 literal: a bare python
    # float under an x64 session lowers an f64 constant (GA-F64).
    f = y.shape[-1] // 2
    msg = jax.nn.sigmoid(y[..., :f]) * jax.nn.softplus(y[..., f:])
    keep = (mask > 0)[..., None]
    return jnp.where(keep, msg, jnp.float32(0.0)).sum(axis=1)


def _z_structured(nodes, edges, kernel, bias, neighbors, transpose_args,
                  dtype):
    """fc_full(v_i, v_j, e) without materializing the concat — the
    _SplitFcFull contraction, with the v_j gather routed through the
    scatter-free transpose mapping when the batch carries one."""
    n, m = edges.shape[0], edges.shape[1]
    f = nodes.shape[-1]
    k = kernel.astype(dtype)
    if transpose_args is not None and transpose_args[0] is not None:
        in_slots, in_mask, over_slots, over_nodes, over_mask = transpose_args
        v_j = gather_transpose(
            nodes, neighbors, in_slots, in_mask, over_slots=over_slots,
            over_nodes=over_nodes, over_mask=over_mask,
        ).reshape(n, m, f)
    else:
        v_j = gather(nodes, neighbors).reshape(n, m, f)
    z = (
        (nodes.astype(dtype) @ k[:f])[:, None, :]
        + v_j.astype(dtype) @ k[f: 2 * f]
        + edges.astype(dtype) @ k[2 * f:]
    )
    return z + bias.astype(dtype)


def _forward_structured(nodes, edges, kernel, bias, scale, bn_bias,
                        neighbors, edge_mask, transpose_args, eps, dtype):
    z = _z_structured(nodes, edges, kernel, bias, neighbors,
                      transpose_args, dtype)
    mean, var, n_real = _masked_stats(z, edge_mask)
    rstd = jax.lax.rsqrt(var + jnp.float32(eps))
    y = (z.astype(jnp.float32) - mean) * (rstd * scale) + bn_bias
    agg = _gate_sum(y, edge_mask.astype(jnp.float32))
    return agg, mean, var, n_real


def _apply_structured(nodes, edges, kernel, bias, scale, bn_bias, mean,
                      rstd, neighbors, edge_mask, transpose_args, dtype):
    z = _z_structured(nodes, edges, kernel, bias, neighbors,
                      transpose_args, dtype)
    y = (z.astype(jnp.float32) - mean) * (rstd * scale) + bn_bias
    return _gate_sum(y, edge_mask.astype(jnp.float32))


# ---------------------------------------------------------------------------
# Pallas kernels: per 128-node block, accumulate v_j over the window tiles
# (one-hot MXU contraction), then fc_full + BN + gate + reduce in-register
# ---------------------------------------------------------------------------


def _row_keep(b, bn, n):
    rows = jax.lax.broadcasted_iota(jnp.int32, (bn, 1), 0) + b * bn
    return (rows < n).astype(jnp.float32)


def _vj_accumulate(w, ws_ref, nbr_ref, ntile_ref, vj_scratch, n):
    """vj_scratch (+)= one_hot(local) @ node_tile for window tile w.

    Exact in any dtype: each neighbor index lies in exactly one tile, so
    every other tile contributes certified zeros. Tile rows past the
    real node count are zeroed first — they are out-of-range block reads
    (garbage, possibly NaN) and 0-one-hot times NaN is NaN."""
    b = pl.program_id(0)
    base = ws_ref[b] + w * _TN
    local = nbr_ref[...] - base  # [TN, M]
    tile_rows = jax.lax.broadcasted_iota(jnp.int32, (_TN, 1), 0) + base
    tile = jnp.where(tile_rows < n, ntile_ref[...].astype(jnp.float32),
                     jnp.float32(0.0))
    oh = (
        local[:, :, None]
        == jax.lax.broadcasted_iota(
            jnp.int32, (*local.shape, _TN), 2)
    )
    part = jax.lax.dot_general(
        oh.astype(jnp.float32), tile,
        (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )

    @pl.when(w == 0)
    def _init():
        vj_scratch[...] = part

    @pl.when(w > 0)
    def _acc():
        vj_scratch[...] += part


def _z_block(b, nodes_ref, edges_ref, cst_ref, vj, n, f, g):
    """fc_full for one block, f32: [TN, M, 2F] from VMEM-resident inputs.

    ``cst_ref`` rows: kernel [(2F+G), 2F] then bias/scale/bn_bias/extra
    rows appended by the callers (see _pack_cst). Tail-block rows past
    ``n`` are zeroed at the source (out-of-range reads are garbage) —
    their z values are then finite and the edge-mask selects drop them.
    """
    keep = _row_keep(b, _TN, n) > 0  # [TN, 1]
    k = cst_ref[: 2 * f + g, :]
    nodes_blk = jnp.where(keep, nodes_ref[...].astype(jnp.float32),
                          jnp.float32(0.0))
    edges_blk = jnp.where(keep[..., None],
                          edges_ref[...].astype(jnp.float32),
                          jnp.float32(0.0))
    vi_term = jnp.dot(nodes_blk, k[:f, :],
                      preferred_element_type=jnp.float32)
    vj_term = jax.lax.dot_general(
        vj, k[f: 2 * f, :], (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    e_term = jax.lax.dot_general(
        edges_blk, k[2 * f: 2 * f + g, :],
        (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    bias = cst_ref[2 * f + g, :]
    return vi_term[:, None, :] + vj_term + e_term + bias


def _blk_mask(b, mask_ref, n):
    """[TN, M] edge mask with tail-block garbage rows zeroed (where, not
    multiply — the source values may be NaN)."""
    return jnp.where(_row_keep(b, _TN, n) > 0, mask_ref[...],
                     jnp.float32(0.0))


def _stats_kernel(ws_ref, nbr_ref, ntile_ref, nodes_ref, edges_ref,
                  mask_ref, cst_ref, out_ref, vj_scratch, *, n, f, g):
    b = pl.program_id(0)
    w = pl.program_id(1)
    nw = pl.num_programs(1)
    _vj_accumulate(w, ws_ref, nbr_ref, ntile_ref, vj_scratch, n)

    @pl.when(w == nw - 1)
    def _finish():
        z = _z_block(b, nodes_ref, edges_ref, cst_ref, vj_scratch[...],
                     n, f, g)
        shift = cst_ref[2 * f + g + 1, :]
        mask = _blk_mask(b, mask_ref, n)
        # zm = mask * (z - shift); the second moment is zm*zm because the
        # mask is binary (mask^2 == mask) — one select covers both sums
        zm = jnp.where(mask[..., None] > 0, z - shift, jnp.float32(0.0))
        part = jnp.stack([
            zm.sum(axis=(0, 1)),
            (zm * zm).sum(axis=(0, 1)),
        ])

        @pl.when(b == 0)
        def _zero():
            out_ref[...] = jnp.zeros_like(out_ref)

        out_ref[...] += part


def _apply_kernel(ws_ref, nbr_ref, ntile_ref, nodes_ref, edges_ref,
                  mask_ref, cst_ref, agg_ref, vj_scratch, *, n, f, g):
    b = pl.program_id(0)
    w = pl.program_id(1)
    nw = pl.num_programs(1)
    _vj_accumulate(w, ws_ref, nbr_ref, ntile_ref, vj_scratch, n)

    @pl.when(w == nw - 1)
    def _finish():
        z = _z_block(b, nodes_ref, edges_ref, cst_ref, vj_scratch[...],
                     n, f, g)
        base = 2 * f + g
        mean = cst_ref[base + 1, :]
        rstd_scale = cst_ref[base + 2, :]
        bn_bias = cst_ref[base + 3, :]
        y = (z - mean) * rstd_scale + bn_bias
        agg_ref[...] = _gate_sum(y, _blk_mask(b, mask_ref, n))


def _pack_cst(kernel, bias, *rows):
    """[(2F+G) + 1 + len(rows), 2F] f32: kernel, bias, then extra rows —
    one VMEM-resident constant block per pallas_call."""
    parts = [kernel.astype(jnp.float32), bias[None].astype(jnp.float32)]
    parts += [r[None].astype(jnp.float32) for r in rows]
    return jnp.concatenate(parts, axis=0)


def _pallas_passes(nodes, edges, kernel, bias, neighbors, edge_mask,
                   window, mode_rows, kernel_fn, out_shape):
    """Shared pallas_call plumbing for the stats/apply passes."""
    n, f = nodes.shape
    m = edges.shape[1]
    g = edges.shape[2]
    nb = pl.cdiv(n, _TN)
    n_pad = nb * _TN
    win = n_pad if window <= 0 else min(window, n_pad)
    nw = win // _TN
    ws = _win_starts(nb, n_pad, win)
    cst = _pack_cst(kernel, bias, *mode_rows)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, nw),
        in_specs=[
            pl.BlockSpec((_TN, m), lambda b, w, ws: (b, 0)),  # neighbors
            pl.BlockSpec((_TN, f), lambda b, w, ws: (ws[b] // _TN + w, 0)),
            pl.BlockSpec((_TN, f), lambda b, w, ws: (b, 0)),  # nodes blk
            pl.BlockSpec((_TN, m, g), lambda b, w, ws: (b, 0, 0)),
            pl.BlockSpec((_TN, m), lambda b, w, ws: (b, 0)),  # edge mask
            pl.BlockSpec(cst.shape, lambda b, w, ws: (0, 0)),
        ],
        out_specs=out_shape[1],
        scratch_shapes=[pltpu.VMEM((_TN, m, f), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(kernel_fn, n=n, f=f, g=g),
        grid_spec=grid_spec,
        out_shape=out_shape[0],
        interpret=_INTERPRET,
    )(
        ws,
        neighbors.astype(jnp.int32).reshape(n, m),
        nodes,
        nodes,
        edges,
        edge_mask.astype(jnp.float32),
        cst,
    )


def _pallas_stats(nodes, edges, kernel, bias, neighbors, edge_mask, shift,
                  window):
    f = nodes.shape[-1]
    return _pallas_passes(
        nodes, edges, kernel, bias, neighbors, edge_mask, window,
        (shift,), _stats_kernel,
        (jax.ShapeDtypeStruct((2, 2 * f), jnp.float32),
         pl.BlockSpec((2, 2 * f), lambda b, w, ws: (0, 0))),
    )


def _pallas_apply(nodes, edges, kernel, bias, neighbors, edge_mask, mean,
                  rstd_scale, bn_bias, window):
    n, f = nodes.shape
    return _pallas_passes(
        nodes, edges, kernel, bias, neighbors, edge_mask, window,
        (mean, rstd_scale, bn_bias), _apply_kernel,
        (jax.ShapeDtypeStruct((n, f), jnp.float32),
         pl.BlockSpec((_TN, f), lambda b, w, ws: (b, 0))),
    )


def _shift_row0(nodes, edges, kernel, bias, neighbors, dtype):
    """The stats estimator's cancellation shift — z of node row 0,
    averaged over its M slots (ops/norm.py semantics), computed with a
    tiny jnp expression so the kernels can consume it as a constant."""
    m = edges.shape[1]
    f = nodes.shape[-1]
    k = kernel.astype(dtype)
    vj0 = jnp.take(nodes, neighbors[:m], axis=0).astype(dtype)
    z0 = (
        nodes[0].astype(dtype) @ k[:f]
        + vj0 @ k[f: 2 * f]
        + edges[0].astype(dtype) @ k[2 * f:]
        + bias.astype(dtype)
    )
    return jax.lax.stop_gradient(z0.astype(jnp.float32).mean(axis=0))


def _forward_pallas(nodes, edges, kernel, bias, scale, bn_bias, neighbors,
                    edge_mask, eps, window, dtype):
    shift = _shift_row0(nodes, edges, kernel, bias, neighbors, dtype)
    s = _pallas_stats(nodes, edges, kernel, bias, neighbors, edge_mask,
                      shift, window)
    n_real = edge_mask.astype(jnp.float32).sum()
    c = jnp.maximum(n_real, jnp.float32(1.0))
    mean_s = s[0] / c
    var = jnp.maximum(s[1] / c - mean_s * mean_s, jnp.float32(0.0))
    mean = mean_s + shift
    rstd = jax.lax.rsqrt(var + jnp.float32(eps))
    agg = _pallas_apply(
        nodes, edges, kernel, bias, neighbors, edge_mask,
        mean, rstd * scale, bn_bias, window,
    )
    return agg, mean, var, n_real


# ---------------------------------------------------------------------------
# the op: custom VJP with a rematerialized structured backward
# ---------------------------------------------------------------------------


def fused_cgconv(
    nodes: jax.Array,  # [N, F]
    edges: jax.Array,  # [N, M, G]
    kernel: jax.Array,  # [2F+G, 2F] (fc_full)
    bias: jax.Array,  # [2F]
    scale: jax.Array,  # [2F] (bn1)
    bn_bias: jax.Array,  # [2F]
    neighbors: jax.Array,  # [N*M] i32
    edge_mask: jax.Array,  # [N, M]
    transpose_args=None,  # (in_slots, in_mask, over_*) or None
    *,
    eps: float = 1e-5,
    impl: str = "pallas",
    window: int = 0,
    dtype=jnp.float32,
):
    """(agg [N, F] f32, mean [2F], var [2F], n_real) — training mode.

    Differentiable in (nodes, edges, kernel, bias, scale, bn_bias); the
    stats outputs feed the (stop-gradient) running-stat EMA. The
    backward REMATERIALIZES through the structured twin — residuals are
    the op's own inputs, nothing forward-computed is saved — and routes
    the v_j cotangent through ``gather_transpose`` when the batch
    carries a transpose mapping (the scatter-free dense backward).
    """
    if impl not in ("xla", "pallas"):
        raise ValueError(f"impl must be 'xla' or 'pallas', got {impl!r}")
    tr = transpose_args

    @jax.custom_vjp
    def op(nodes, edges, kernel, bias, scale, bn_bias):
        if impl == "pallas":
            return _forward_pallas(nodes, edges, kernel, bias, scale,
                                   bn_bias, neighbors, edge_mask, eps,
                                   window, dtype)
        return _forward_structured(nodes, edges, kernel, bias, scale,
                                   bn_bias, neighbors, edge_mask, tr, eps,
                                   dtype)

    def op_fwd(nodes, edges, kernel, bias, scale, bn_bias):
        out = op(nodes, edges, kernel, bias, scale, bn_bias)
        return out, (nodes, edges, kernel, bias, scale, bn_bias)

    def op_bwd(res, cts):
        # rematerialized: re-derive the structured forward's VJP from the
        # saved INPUTS (no activations were stored); the stats outputs'
        # cotangents are zero by construction (EMA is stop-gradient)
        _, vjp_fn = jax.vjp(
            lambda *a: _forward_structured(*a, neighbors, edge_mask, tr,
                                           eps, dtype),
            *res,
        )
        zeros = (jnp.zeros_like(cts[1]), jnp.zeros_like(cts[2]),
                 jnp.zeros_like(cts[3]))
        return vjp_fn((cts[0], *zeros))

    op.defvjp(op_fwd, op_bwd)
    return op(nodes, edges, kernel, bias, scale, bn_bias)


def fused_cgconv_eval(nodes, edges, kernel, bias, scale, bn_bias,
                      neighbors, edge_mask, mean, var, transpose_args=None,
                      *, eps: float = 1e-5, impl: str = "pallas",
                      window: int = 0, dtype=jnp.float32):
    """Eval/serving mode: normalize with running stats — ONE apply pass,
    the whole-conv serving fast path."""
    rstd = jax.lax.rsqrt(var.astype(jnp.float32) + jnp.float32(eps))
    m32 = mean.astype(jnp.float32)
    if impl == "pallas":
        return _pallas_apply(nodes, edges, kernel, bias, neighbors,
                             edge_mask, m32, rstd * scale, bn_bias, window)
    return _apply_structured(nodes, edges, kernel, bias, scale, bn_bias,
                             m32, rstd, neighbors, edge_mask,
                             transpose_args, dtype)


def fused_conv_hbm_bytes(n: int, m: int, g: int, f: int,
                         dtype_bytes: int = 4) -> dict:
    """The kernel's analytic HBM byte model (graftaudit roofline budget).

    Per training-mode forward: TWO passes read the block inputs (edges
    [N,M,G] dominate; nodes via bounded windows ~2x [N,F]; neighbors +
    mask), ONE [N,F] f32 write, ZERO intermediate tensors — the ~3
    round-trips the unfused path pays for v_j/z/staging collapse to one
    per edge block. The audit gates a lowered fused program's
    cost-analysis bytes against this model so a later change that
    silently rematerializes an [N,M,*] intermediate in HBM blocks CI.
    """
    edges_b = n * m * g * dtype_bytes
    nodes_b = 2 * n * f * dtype_bytes  # block rows + window tiles
    nbr_b = n * m * 4
    mask_b = n * m * 4
    params_b = (2 * f + g) * 2 * f * 4
    read_once = edges_b + nodes_b + nbr_b + mask_b + params_b
    write_b = n * f * 4
    return {
        "reads_per_pass": read_once,
        "passes": 2,
        "write_bytes": write_b,
        "model_bytes": 2 * read_once + write_b,
    }


class FcFullParams(nn.Module):
    """``_SplitFcFull``'s parameter tree (kernel/bias) without its
    compute — instantiated by CGConv with ``name='fc_full'`` so the
    fused path owns the EXACT checkpoint layout (and, with the same rng
    path, the bit-identical init) of the unfused branch."""

    features: int  # 2F

    @nn.compact
    def __call__(self, in_dim: int):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (in_dim, self.features), jnp.float32,
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,),
                          jnp.float32)
        return kernel, bias


class BN1Params(nn.Module):
    """MaskedBatchNorm's parameter/stat tree without its compute.

    Two-phase use by CGConv (``name='bn1'``): a first call declares and
    returns (scale, bias, running mean, running var); a second call with
    ``update=(mean, var, n_real)`` applies the momentum-0.1 EMA — the
    exact update MaskedBatchNorm/FusedBN1GateSum perform, including the
    all-padding-batch guard and the unbiased-variance correction.
    Compact modules may be called repeatedly; both calls declare the
    same tree, so the layout is identical either way.
    """

    momentum: float = 0.1

    @nn.compact
    def __call__(self, features: int, update=None):
        scale = self.param("scale", nn.initializers.ones, (features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (features,),
                          jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros(features, jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones(features, jnp.float32)
        )
        if update is not None and not self.is_initializing():
            mean, var, n_real = update
            has_rows = n_real > 0
            one = jnp.float32(1.0)
            unbiased = var * n_real / jnp.maximum(n_real - one, one)
            ra_mean.value = jnp.where(
                has_rows,
                (1.0 - self.momentum) * ra_mean.value
                + self.momentum * mean,
                ra_mean.value,
            )
            ra_var.value = jnp.where(
                has_rows,
                (1.0 - self.momentum) * ra_var.value
                + self.momentum * unbiased,
                ra_var.value,
            )
        return scale, bias, ra_mean.value, ra_var.value
