"""Device-side ops: segment reductions, masked normalization, Pallas kernels.

TPU-native replacement for the reference's native kernel surface
(SURVEY.md §2 "Native components" table): ATen gather + per-node reduction
become XLA segment ops (and optionally a Pallas gather-scatter kernel), and
cuDNN BatchNorm becomes an in-tree masked BatchNorm that keeps padding out of
the batch statistics.
"""

from cgnn_tpu.ops.segment import (
    segment_sum,
    segment_mean,
    gather,
    aggregate_edge_messages,
    set_default_aggregation_impl,
)
from cgnn_tpu.ops.norm import MaskedBatchNorm

__all__ = [
    "segment_sum",
    "segment_mean",
    "gather",
    "aggregate_edge_messages",
    "set_default_aggregation_impl",
    "MaskedBatchNorm",
]
