"""Windowed one-hot neighbor-gather kernel (SURVEY.md §7 phase 6).

The dense-layout conv's forward ``v_j = nodes[neighbors]`` is a row-granular
HBM gather: TPU has no data cache, so each node's 128-byte row is re-read
once per incident edge (~M times), and row-granular access itself tops out
~230 GB/s on v5e (measured, PERF.md). But the batcher packs each graph's
nodes contiguously and every edge's neighbor lies INSIDE its own graph, so
the gather has perfect block locality: the edges owned by a 128-slot node
block only reference a bounded node WINDOW (that block's graphs' spans,
<= 128 + 2*(max_graph_nodes-1) rows).

This kernel exploits that: per node block b, the grid's minor dimension w
walks the (few) 128-row node tiles of b's window — Pallas pipelines each
tile HBM->VMEM via a scalar-prefetch index_map (each node row read once
per block instead of M times, sequential DMA) — and the gather becomes an
MXU contraction ``one_hot(local_idx) @ node_tile`` accumulated over w.
The accumulation is EXACT in any dtype: each edge's index lies in exactly
one tile, so all other tiles contribute zeros.

STATUS (round 3, measured on the real v5e with value-fetch fencing): NOT
integrated — a tested negative result, like the interval-one-hot
segment-sum before it (ops/pallas_scatter.py). At the bench's MP shape
(N=15488, M=12, F=64, bf16), bit-exact vs ``jnp.take`` but SLOWER:
1.96 ms vs 1.31 ms (TN=128), 1.77 vs 1.47 (TN=256), 1.86 vs 1.53
(TN=512). Why: the one-hot materialization does E*W lane-compares
(~95M elements at W=512) — ~30x the E*F output volume — and that VPU
work exceeds what the M-fold redundant HBM reads cost the native
gather. The trade would flip for much larger F (one-hot cost is
F-independent) or much larger M; at this model's F=64/M=12 XLA's
row-granular gather is the right tool. Kept as a correct, tested
scaffold; the model path keeps jnp.take + the two-tier transpose
backward (ops/segment.py gather_transpose).

Correctness cases handled:
- window start clamped to [0, N-W]; clamping only extends coverage left.
- padding slots are self-loops whose nodes may fall outside a padding
  block's window: their one-hot rows are all-zero -> v_j = 0, identical
  to the plain gather of a zeroed padding node row.
- requires node_cap % 128 == 0 and edge_cap == node_cap * M (the dense
  layout); callers align capacities.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TN = 128  # node rows per tile (= lane width)


def _kernel(ws_ref, nbr_ref, ntile_ref, out_ref, *, tn, m):
    b = pl.program_id(0)
    w = pl.program_id(1)
    base = (ws_ref[b] // tn + w) * tn  # absolute first row of this tile
    local = nbr_ref[:] - base  # [tn, m]
    oh = (
        local[:, :, None]
        == jax.lax.broadcasted_iota(jnp.int32, (tn, m, tn), 2)
    )
    part = jax.lax.dot_general(
        oh.astype(ntile_ref.dtype),
        ntile_ref[:],
        (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        # HIGHEST: default MXU precision rounds f32 operands to bf16,
        # which would silently break the bit-exactness claim for f32
        precision=jax.lax.Precision.HIGHEST,
    ).astype(out_ref.dtype)

    @pl.when(w == 0)
    def _init():
        out_ref[:] = part

    @pl.when(w > 0)
    def _acc():
        out_ref[:] += part


@functools.partial(jax.jit, static_argnames=("window",))
def windowed_gather(
    nodes: jax.Array,  # [N, F], N % 128 == 0
    neighbors: jax.Array,  # [N*M] i32 (dense slot layout)
    win_starts: jax.Array,  # [N // 128] i32 first window row per block
    window: int,  # static width, multiple of 128 (see window_width)
) -> jax.Array:
    n, f = nodes.shape
    e = neighbors.shape[0]
    m = e // n
    assert n % _TN == 0, f"node capacity {n} not {_TN}-aligned"
    assert window % _TN == 0
    nb = n // _TN
    nw = window // _TN
    win_starts = jnp.minimum(
        win_starts.astype(jnp.int32), jnp.int32(max(n - window, 0))
    )
    win_starts = (win_starts // _TN) * _TN
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, nw),
        in_specs=[
            pl.BlockSpec((_TN, m), lambda b, w, ws: (b, 0)),
            pl.BlockSpec((_TN, f), lambda b, w, ws: (ws[b] // _TN + w, 0)),
        ],
        out_specs=pl.BlockSpec((_TN, m, f), lambda b, w, ws: (b, 0, 0)),
    )
    return pl.pallas_call(
        functools.partial(_kernel, tn=_TN, m=m),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, m, f), nodes.dtype),
    )(win_starts, neighbors.astype(jnp.int32).reshape(n, m), nodes)


def window_width(max_graph_nodes: int) -> int:
    """Static window for a dataset: a 128-slot block can straddle one
    graph cut at its start and another at its end, plus one extra tile
    for the 128-row alignment of the window start."""
    need = 2 * _TN + 2 * (int(max_graph_nodes) - 1)
    return max(_TN, -(-need // _TN) * _TN)
