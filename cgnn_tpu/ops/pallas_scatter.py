"""Pallas TPU kernel for the gather-scatter hot loop (SURVEY.md §7 phase 6).

The reference's hottest device op is the per-node reduction of edge messages
(CUDA: ATen scatter / atomicAdd). XLA lowers ``segment_sum`` to a scatter;
this kernel instead exploits the batcher's sorted-centers invariant
(data/graph.py) to turn the reduction into MXU matmuls with zero scatter:

- a device-side ``searchsorted`` over the sorted centers yields, for every
  node, its contiguous incident-edge range [start_n, end_n);
- grid over node tiles of TN=128 rows; per-node ranges arrive as an aligned
  [num_tiles, TN] block, tile-level ranges as scalar prefetch;
- each tile's edge span is streamed HBM -> VMEM in fixed TE-row chunks; a
  chunk is reduced in one shot via an interval one-hot matmul:
      oh[e, n]  = (start_n <= g_e) & (g_e < end_n),  g_e = global edge row
      acc[n, f] += oh^T @ msg_chunk                  (MXU contraction)
  Rows past the tile's span or past E fall outside every interval, so
  over-reads are self-masking. No atomics, deterministic, tolerant of
  arbitrary degree skew and empty nodes.

Backward: aggregation is linear, so d_messages = d_out[centers] — a plain
XLA gather (custom_vjp below). Exposed through
``aggregate_edge_messages(..., impl='pallas')`` (ops/segment.py).

STATUS (round 3, measured with honest value-fetch fencing — the round-2
numbers previously quoted here were polluted by ``block_until_ready``
returning early under the tunneled runtime, see bench.py): NOT the default,
and NOT the answer to the scatter problem. At E=567k/F=128/bf16 on the real
v5e chip: XLA segment_sum 10.1 ms, this kernel 17.3 ms, cumsum+boundary-
gather 21.3 ms — all ~50x below HBM bandwidth; scatter-shaped reductions
are simply slow on this hardware. The production fix is STRUCTURAL: the
dense edge-slot layout (data/graph.py pack_graphs dense_m) removes the
segment-sum from the model entirely (aggregation becomes a dense reduce,
measured 1.1 ms at the same shape, 2x faster end-to-end train step). The
kernel stays as a correct, tested, flag-selectable backend for the flat
layout and as the scaffold for a windowed one-hot GATHER kernel (the
remaining neighbor-gather backward is now the dominant step cost).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TN = 128  # node rows per grid step (= lane width)
_TE = 512  # edge rows per streamed chunk


def _kernel(tile_starts_ref, bounds_ref, msg_ref, out_ref, acc_ref,
            msg_vmem, sem):
    i = pl.program_id(0)
    start = tile_starts_ref[i]
    end = tile_starts_ref[i + 1]

    acc_ref[:] = jnp.zeros_like(acc_ref)
    # explicit int32: under jax_enable_x64 a Python-int operand would
    # promote the index math to int64, which SMEM scalars reject
    te = jnp.int32(_TE)
    # align the stream start down to the sublane tile (8 rows — required for
    # bf16 HBM slices); rows before `start` belong to the previous tile's
    # nodes and are self-masked by the interval one-hot
    astart = (start // 8) * 8
    num_chunks = pl.cdiv(end - astart, te)
    # bounds block is (8, TN) for sublane alignment; rows 2..7 are padding
    node_start = bounds_ref[0, :]  # [TN] first edge row of each node
    node_end = bounds_ref[1, :]  # [TN] one-past-last edge row

    def chunk_body(k, _):
        off = pl.multiple_of(astart + k * te, 8)
        dma = pltpu.make_async_copy(
            msg_ref.at[pl.ds(off, _TE), :], msg_vmem, sem
        )
        dma.start()
        dma.wait()
        # interval one-hot over global edge rows; self-masks over-read rows
        g = off + jax.lax.broadcasted_iota(jnp.int32, (_TE, _TN), 0)
        oh = jnp.logical_and(
            g >= node_start[None, :], g < node_end[None, :]
        ).astype(msg_vmem.dtype)
        # f32 operands need HIGHEST or the MXU rounds them through bf16
        # passes; bf16 operands are exact already (one-hot selection) and
        # only support the native bf16 x bf16 -> f32 path
        precision = (
            jax.lax.Precision.HIGHEST
            if msg_vmem.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT
        )
        acc_ref[:] += jax.lax.dot_general(
            oh,
            msg_vmem[:],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        return 0

    jax.lax.fori_loop(0, num_chunks, chunk_body, 0)
    out_ref[:] = acc_ref[:]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def segment_sum_pallas(
    messages: jax.Array, centers: jax.Array, num_nodes: int
) -> jax.Array:
    """Scatter-free segment sum over SORTED centers -> [num_nodes, F].

    Requires the pack_graphs sortedness invariant; messages for masked
    (padding) edges must already be zeroed, as in CGConv.
    """
    return _forward(messages, centers, num_nodes)


def _forward(messages, centers, num_nodes):
    e, f = messages.shape
    num_tiles = pl.cdiv(num_nodes, _TN)
    n_pad = num_tiles * _TN
    # pad edges so chunk DMAs past `end` stay in bounds, and features to the
    # 128-lane tile (Mosaic requires aligned DMA slices)
    f_pad = -f % 128
    fp = f + f_pad
    msg_p = jnp.pad(messages, ((0, _TE), (0, f_pad)))

    centers = centers.astype(jnp.int32)
    # per-node contiguous edge ranges from the global sort
    edge_bounds = jnp.searchsorted(
        centers, jnp.arange(n_pad + 1, dtype=jnp.int32), side="left"
    ).astype(jnp.int32)
    # (8, TN)-tiled bounds block per tile: row 0 = start, row 1 = end,
    # rows 2..7 sublane-alignment padding
    bounds = jnp.zeros((num_tiles, 8, _TN), jnp.int32)
    bounds = bounds.at[:, 0].set(edge_bounds[:-1].reshape(num_tiles, _TN))
    bounds = bounds.at[:, 1].set(edge_bounds[1:].reshape(num_tiles, _TN))
    bounds = bounds.reshape(num_tiles * 8, _TN)
    tile_starts = edge_bounds[:: _TN]  # [num_tiles + 1]

    out = pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(num_tiles,),
            in_specs=[
                pl.BlockSpec(
                    (8, _TN), lambda i, ts: (i, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(memory_space=pl.ANY),  # messages
            ],
            out_specs=pl.BlockSpec(
                (_TN, fp), lambda i, ts: (i, 0), memory_space=pltpu.VMEM
            ),
            scratch_shapes=[
                pltpu.VMEM((_TN, fp), jnp.float32),
                pltpu.VMEM((_TE, fp), messages.dtype),
                pltpu.SemaphoreType.DMA(()),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, fp), jnp.float32),
    )(tile_starts, bounds, msg_p)
    return out[:num_nodes, :f].astype(messages.dtype)


def _fwd(messages, centers, num_nodes):
    return _forward(messages, centers, num_nodes), centers


def _bwd(num_nodes, centers, g):
    # linear op: d_messages[e] = g[centers[e]]; centers get no gradient
    return jnp.take(g, centers, axis=0).astype(g.dtype), None


segment_sum_pallas.defvjp(_fwd, _bwd)
