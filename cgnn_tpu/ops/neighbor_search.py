"""In-program periodic neighbor search + featurization (ISSUE 11).

The front of the pipeline, compiled: given a staged :class:`RawBatch`
(positions, lattice, species — data/rawbatch.py), build the exact
dense-layout ``GraphBatch`` the models consume INSIDE the jitted
program. This is the host ``knn_neighbor_list`` + ``atom_features`` +
``GaussianDistance`` chain (data/neighbors.py, data/dataset.py), moved
on device under the padded-capacity discipline:

- per structure, every (atom j, periodic image k) pair is a CANDIDATE:
  a dense ``[S, S*K]`` f32 distance matrix over the rung's fixed image
  grid (``RawSpec.images``, lexicographic (ia, ib, ic) order). At
  serving-scale structures (S <= ~128 atoms, K <= ~100 images) this
  dense matrix IS the TPU-shaped form of a cell list — plain VPU
  elementwise work and one sort, no gather/scatter binning — and the
  fixed caps play the role the cell capacity plays in a binned search;
- selection is SORT-BASED: candidates sort by the canonical key
  (distance, then candidate index = source atom major, image minor) and
  the first ``dense_m`` in-radius survivors per center are the edges —
  exactly the host featurizer's ``max_num_nbr`` nearest truncation in
  exactly the host's canonical order (lexsort by (center, distance),
  ties by (source atom, image grid order));
- out-of-range slots are WHERE-masked, never multiplied: invalid
  candidates carry an ``inf`` sort key, masked edge slots emit the
  dense layout's self-loop neighbor and zero features (the same padding
  contract ``pack_graphs`` writes).

Two implementations behind one flag (the PR-9 §6b methodology):
``impl='xla'`` is the vectorized jnp/`lax.sort` form (the default —
XLA's sort and fusion are hard to beat until a chip A/B says
otherwise); ``impl='pallas'`` runs each structure as one kernel
invocation — candidate distances computed in VMEM and the top-M
selection as ``dense_m`` lexicographic argmin rounds (sort-free, the
shape a blocked TPU kernel wants) — auto-interpreted off-TPU so CPU CI
pins variant parity. The two variants select identical edges wherever
the f32 radius/tie decisions are exact (pinned by test).

Overflow contract (INVARIANTS.md "raw-wire overflow flag"): the program
re-derives each structure's needed image counts from its STAGED lattice
(plane-spacing formula, ``data.rawbatch.needed_images_f32``) and flags
any structure whose lattice needs more images than the rung provides —
the only way this fixed-cap search can miss a true edge, given exact
top-M selection over the full candidate set. Flagged structures must
never be answered from the truncated graph (serving routes them to the
host-featurized fallback); the flag is computed IN-PROGRAM, not at
admission, because relaxation/MD (ROADMAP item 2) updates positions
on device where no host pre-check exists.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from cgnn_tpu.data.elements import full_embedding_table
from cgnn_tpu.data.graph import GraphBatch
from cgnn_tpu.data.rawbatch import RawBatch, RawSpec


def _needed_images_jnp(lat, radius: float):
    """[3] f32 needed-image counts — the jnp twin of
    ``data.rawbatch.needed_images_f32`` (same formula, same 1e-4 slack)."""
    cross = jnp.stack([
        jnp.cross(lat[1], lat[2]),
        jnp.cross(lat[2], lat[0]),
        jnp.cross(lat[0], lat[1]),
    ])
    det = jnp.abs(jnp.dot(lat[0], cross[0]))
    norms = jnp.sqrt((cross * cross).sum(axis=1))
    return jnp.ceil(jnp.float32(radius) * norms / det - jnp.float32(1e-4))


def _candidate_distances(frac, lat, offsets_f32):
    """[S, S*K] candidate distances, candidate index c = j*K + k (source
    atom major, lexicographic image minor — the canonical tie order)."""
    s_cap = frac.shape[0]
    k = offsets_f32.shape[0]
    cart = frac @ lat  # [S, 3]
    shifts = offsets_f32 @ lat  # [K, 3]
    pos_j = cart[:, None, :] + shifts[None, :, :]  # [S, K, 3]
    diff = pos_j[None, :, :, :] - cart[:, None, None, :]  # [S, S, K, 3]
    d2 = (diff[..., 0] * diff[..., 0] + diff[..., 1] * diff[..., 1]
          + diff[..., 2] * diff[..., 2])
    return jnp.sqrt(d2).reshape(s_cap, s_cap * k)


def _candidate_valid(amask, spec: RawSpec):
    """[S, S*K] bool: both atoms real, home-image self pair excluded.
    (The radius test is applied by the caller — it depends on d.)"""
    s_cap = amask.shape[0]
    k = spec.n_images
    m_b = amask.astype(bool)
    valid = m_b[:, None, None] & m_b[None, :, None]
    valid = valid & jnp.ones((s_cap, s_cap, k), bool)
    self_home = (jnp.eye(s_cap, dtype=bool)[:, :, None]
                 & (jnp.arange(k) == spec.home_image)[None, None, :])
    return (valid & ~self_home).reshape(s_cap, s_cap * k)


def _search_one_xla(frac, lat, amask, spec: RawSpec, offsets_f32):
    """One structure's search (vmapped over the batch): ->
    (neighbors [S, M] i32 local, distances [S, M] f32,
    edge_mask [S, M] f32, n_edges i32, overflow bool)."""
    s_cap, m = spec.snode_cap, spec.dense_m
    k = spec.n_images
    d = _candidate_distances(frac, lat, offsets_f32)
    valid = _candidate_valid(amask, spec) & (d <= jnp.float32(spec.radius))
    key = jnp.where(valid, d, jnp.float32(jnp.inf))
    cand = jnp.broadcast_to(
        jnp.arange(s_cap * k, dtype=jnp.int32), (s_cap, s_cap * k)
    )
    # two-key lexicographic sort: distance, then candidate index — the
    # canonical order is exact even where the backend sort is unstable
    sk, sc = lax.sort((key, cand), dimension=1, num_keys=2)
    sk, sc = sk[:, :m], sc[:, :m]
    n_valid = valid.sum(axis=1)
    emask = jnp.arange(m)[None, :] < n_valid[:, None]
    nbr = jnp.where(emask, sc // k,
                    jnp.arange(s_cap, dtype=jnp.int32)[:, None])
    dist = jnp.where(emask, sk, jnp.float32(0.0))
    n_edges = jnp.minimum(n_valid, m).sum().astype(jnp.int32)
    need = _needed_images_jnp(lat, spec.radius)
    # padding structure slots (no real atoms; host-written identity
    # lattice) must never flag — there is no graph to truncate
    overflow = (jnp.any(need > jnp.asarray(spec.images, jnp.float32))
                & jnp.any(amask > 0))
    return nbr, dist, emask.astype(jnp.float32), n_edges, overflow


def _search_kernel(frac_ref, lat_ref, amask_ref, offs_ref, nbr_ref,
                   dist_ref, em_ref, ne_ref, *, spec: RawSpec):
    """Pallas kernel: ONE structure per grid step — candidate distances
    in VMEM, then ``dense_m`` lexicographic argmin rounds (sort-free
    top-M: each round takes the minimum (distance, candidate) pair per
    center and masks it out — the selection order is IDENTICAL to the
    sorted form because (d, c) keys are distinct by construction)."""
    s_cap, m = spec.snode_cap, spec.dense_m
    k = spec.n_images
    c = s_cap * k
    frac = frac_ref[0]
    lat = lat_ref[0]
    amask = amask_ref[0]
    d = _candidate_distances(frac, lat, offs_ref[...])
    valid = _candidate_valid(amask, spec) & (d <= jnp.float32(spec.radius))
    key = jnp.where(valid, d, jnp.float32(jnp.inf))
    cand = lax.broadcasted_iota(jnp.int32, (s_cap, c), 1)
    rows = lax.broadcasted_iota(jnp.int32, (s_cap, m), 0)
    nbr_cols, dist_cols, em_cols = [], [], []
    for _ in range(m):
        dmin = jnp.min(key, axis=1, keepdims=True)  # [S, 1]
        hit = jnp.isfinite(dmin[:, 0])
        tie = key == dmin
        cmin = jnp.min(jnp.where(tie, cand, c), axis=1)  # [S]
        nbr_cols.append(jnp.where(hit, cmin // k, 0))
        dist_cols.append(jnp.where(hit, dmin[:, 0], jnp.float32(0.0)))
        em_cols.append(hit.astype(jnp.float32))
        key = jnp.where(cand == cmin[:, None], jnp.float32(jnp.inf), key)
    em = jnp.stack(em_cols, axis=1)
    nbr = jnp.stack(nbr_cols, axis=1)
    nbr_ref[0] = jnp.where(em > 0, nbr, rows)
    dist_ref[0] = jnp.stack(dist_cols, axis=1)
    em_ref[0] = em
    ne_ref[0, 0] = em.sum().astype(jnp.int32)


def _search_pallas(frac, lats, amask, spec: RawSpec, offsets_f32,
                   interpret: bool):
    g_cap, s_cap = amask.shape
    m = spec.dense_m
    kern = functools.partial(_search_kernel, spec=spec)
    nbr, dist, em, ne = pl.pallas_call(
        kern,
        grid=(g_cap,),
        in_specs=[
            pl.BlockSpec((1, s_cap, 3), lambda g: (g, 0, 0)),
            pl.BlockSpec((1, 3, 3), lambda g: (g, 0, 0)),
            pl.BlockSpec((1, s_cap), lambda g: (g, 0)),
            pl.BlockSpec((spec.n_images, 3), lambda g: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, s_cap, m), lambda g: (g, 0, 0)),
            pl.BlockSpec((1, s_cap, m), lambda g: (g, 0, 0)),
            pl.BlockSpec((1, s_cap, m), lambda g: (g, 0, 0)),
            pl.BlockSpec((1, 1), lambda g: (g, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g_cap, s_cap, m), jnp.int32),
            jax.ShapeDtypeStruct((g_cap, s_cap, m), jnp.float32),
            jax.ShapeDtypeStruct((g_cap, s_cap, m), jnp.float32),
            jax.ShapeDtypeStruct((g_cap, 1), jnp.int32),
        ],
        interpret=interpret,
    )(frac, lats, amask.astype(jnp.float32), offsets_f32)
    # the overflow flag reads only the lattice: a tiny vectorized jnp
    # computation, shared verbatim with the XLA variant instead of
    # burning an image-cap constant into the kernel
    need = jax.vmap(
        lambda la: _needed_images_jnp(la, spec.radius)
    )(lats)
    # padding slots never flag (no real atoms — same rule as the XLA
    # variant)
    overflow = (jnp.any(need > jnp.asarray(spec.images, jnp.float32),
                        axis=1)
                & jnp.any(amask > 0, axis=1))
    return nbr, dist, em, ne[:, 0], overflow


def neighbor_search(frac, lats, amask, spec: RawSpec,
                    impl: str = "xla", interpret: bool | None = None):
    """Batched in-program search -> (neighbors [G, S, M] i32 local,
    distances [G, S, M] f32, edge_mask [G, S, M] f32, n_edges [G] i32,
    overflow [G] bool).

    ``interpret=None`` auto-interprets the Pallas variant off-TPU (the
    CPU-CI parity path; config.py backend rule)."""
    if impl not in ("xla", "pallas"):
        raise ValueError(f"impl must be 'xla' or 'pallas', got {impl!r}")
    offsets_f32 = jnp.asarray(
        spec.offsets_grid().astype(np.float32)
    )
    if impl == "pallas":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        return _search_pallas(frac, lats, amask, spec, offsets_f32,
                              interpret)
    one = functools.partial(_search_one_xla, spec=spec,
                            offsets_f32=offsets_f32)
    return jax.vmap(one)(frac, lats, amask)


def neighbor_search_hbm_bytes(g_cap: int, s_cap: int, k: int,
                              m: int) -> dict:
    """Analytic HBM byte model of one batched search — the GA-ROOFLINE
    budget (analysis/program_audit.py).

    The intended working set is the ``[S, S*K]`` candidate plane per
    structure, touched a bounded number of times: three per-axis
    position diffs, the squared-sum + sqrt, the validity/key masks, and
    the two-operand sort's read/write — ~16 f32 passes is a generous
    constant-factor bound. What the budget EXCLUDES (and therefore
    catches at ~G-fold = ~40x): a per-candidate FEATURE tensor
    ``[S, S*K, G]`` — featurization must happen after truncation to the
    ``[S, M]`` survivors, never on the full candidate set."""
    cand = g_cap * s_cap * s_cap * k
    passes = 16
    io = (g_cap * s_cap * 3 * 4 + g_cap * 9 * 4 + g_cap * s_cap * 5
          + g_cap * s_cap * m * 12 + g_cap * 8)
    return {
        "candidates": int(cand),
        "candidate_passes": passes,
        "io_bytes": int(io),
        "budget_bytes": int(cand * 4 * passes + io),
    }


def make_raw_expander(spec: RawSpec, edge_dtype=jnp.float32,
                      impl: str = "xla") -> Callable:
    """Jit-composable RawBatch -> (GraphBatch, overflow [G] bool,
    n_edges [G] i32) reconstruction — the raw-wire sibling of
    ``data.compact.make_expander``.

    The emitted GraphBatch uses the per-structure BLOCK layout:
    structure g owns node slots ``[g*S, (g+1)*S)``; every dense-layout
    invariant holds (centers = arange // M non-decreasing, masks zero
    on padding, padding edge slots self-loop their owning node).
    Geometry fields come back None like the compact expander — the
    energy-family models never read them.
    """
    table = full_embedding_table()
    mu = np.asarray(spec.gauss_filter, np.float32)
    var2 = np.float32(spec.gauss_var) ** 2
    m = spec.dense_m

    def expand(rb: RawBatch):
        g_cap, s_cap = rb.species.shape
        nbr, dist, emask, n_edges, overflow = neighbor_search(
            rb.frac, rb.lattices, rb.atom_mask, spec, impl=impl
        )
        node_mask = rb.atom_mask.reshape(-1).astype(jnp.float32)
        nodes = jnp.asarray(table)[rb.species.reshape(-1)] \
            * node_mask[:, None]
        # the one radial-basis formula, division form — matches
        # data.featurize.gaussian_expand exactly modulo jnp.exp's
        # <= 1 ulp (the compact-expander contract)
        efea = jnp.exp(-((dist[..., None] - jnp.asarray(mu)) ** 2) / var2)
        efea = (efea * emask[..., None]).astype(edge_dtype)
        edges = efea.reshape(g_cap * s_cap, m, efea.shape[-1])
        base = (jnp.arange(g_cap, dtype=jnp.int32) * s_cap)[:, None, None]
        neighbors = (nbr + base).reshape(-1)
        centers = jnp.arange(g_cap * s_cap * m, dtype=jnp.int32) // m
        node_graph = jnp.arange(g_cap * s_cap, dtype=jnp.int32) // s_cap
        gb = GraphBatch(
            nodes=nodes,
            edges=edges,
            centers=centers,
            neighbors=neighbors,
            node_graph=node_graph,
            node_mask=node_mask,
            edge_mask=emask.reshape(-1),
            graph_mask=rb.graph_mask,
            targets=rb.targets,
            target_mask=rb.target_mask,
            positions=None,
            lattices=None,
            edge_offsets=None,
            node_targets=None,
        )
        overflow = overflow & (rb.graph_mask > 0)
        return gb, overflow, n_edges

    return expand
