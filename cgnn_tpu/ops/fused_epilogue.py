"""Fused conv epilogue: masked-BN normalize + gate + edge-mask + sum-over-M.

PERF.md §4b scoped this as the top remaining structural lever: elementwise/
BN loop fusions are 3.12 ms of the 8.59 ms flagship step (36%), spread over
~6 passes of the [N, M, 2F] activation in forward + backward. This module
collapses the BN1-apply -> sigmoid*softplus gate -> edge-mask -> sum-over-M
chain of CGConv's dense branch (models/cgcnn.py) into a hand-scheduled
custom-VJP with a minimal-pass structure:

  forward:  stats (1 read of z)  +  apply (1 read of z, write [N, F])
  backward: reductions (1 read)  +  dz (1 read, write [N, M, 2F])

with residuals of only (mean, rstd) [2F] — the autodiff graph otherwise
saves or rematerializes the [N, M, *] intermediates (xhat, gate, msg) with
extra full passes.

Two implementations behind one flag:

- ``impl='xla'``: plain jnp with the same pass structure — measures how much
  of the win is STRUCTURE (fewer conceptual passes for XLA to fuse).
- ``impl='pallas'``: the apply/reduction/dz passes as Pallas TPU kernels
  with explicit [BN, M, 2F] VMEM blocking — measures what hand scheduling
  adds on top.

MEASURED VERDICT (round 4, real v5e, same-process interleaved rounds at
the bench workload — PERF.md §6b): BOTH impls are ~5-20% SLOWER than the
unfused chain (unfused 33.7-39.9k structs/s vs fused-xla 32.4-32.6k vs
fused-pallas 32.0-32.8k). The custom-VJP boundary forfeits XLA's
producer/consumer fusion: unfused, the normalize+gate+sum chain fuses
into the fc_full matmul epilogue and dz into the matmul backwards, so z
and dz never round-trip HBM as standalone tensors — exactly the passes
this op "saves" were not being paid. Same verdict class as the r3 gather
kernels (§3b). The module stays as a correct, tested scaffold behind
--fused-epilogue; the default path remains unfused.

Numerical contract: identical to MaskedBatchNorm(one-pass f32 stats) +
split + sigmoid*softplus + mask + sum, to f32 roundoff (tests/test_ops.py).
NOT used by the force task (its trunk is BatchNorm-free) — this custom_vjp
is first-order only, which regression/classification training is.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# apply/dz kernels block the node axis at this many rows; node capacities
# are 8-aligned, not 128-aligned, so kernels row-mask the tail block
_BLOCK_N = 256


def _masked_stats(z: jax.Array, mask: jax.Array):
    """Shifted one-pass masked moments over the (N, M) axes -> f32.

    Same estimator as ops/norm.py MaskedBatchNorm's f32 path (including the
    leading-row shift that kills E[x^2]-E[x]^2 cancellation); kept in jnp —
    a single fused multiply-reduce read of z is already roofline-bound.
    """
    zf = z.astype(jnp.float32)
    shift = jax.lax.stop_gradient(zf[:1].mean(axis=(0, 1)))
    zs = zf - shift
    m = mask.astype(jnp.float32)
    n_real = m.sum()
    zm = zs * m[..., None]
    s1 = zm.sum(axis=(0, 1))
    s2 = (zm * zs).sum(axis=(0, 1))
    n = jnp.maximum(n_real, 1.0)
    mean_s = s1 / n
    var = jnp.maximum(s2 / n - mean_s * mean_s, 0.0)
    return mean_s + shift, var, n_real


def _gate(y: jax.Array, mask: jax.Array):
    f = y.shape[-1] // 2
    sg = jax.nn.sigmoid(y[..., :f])
    sp = jax.nn.softplus(y[..., f:])
    return sg * sp * mask[..., None]


# ---------------------------------------------------------------------------
# impl='xla': hand-structured passes, XLA does the in-pass fusion
# ---------------------------------------------------------------------------


def _apply_xla(z, mask, mean, rstd, scale, bias):
    zf = z.astype(jnp.float32)
    y = (zf - mean) * (rstd * scale) + bias
    return _gate(y, mask.astype(jnp.float32)).sum(axis=1)


def _bwd_xla(z, mask, mean, rstd, scale, bias, n_real, ct_agg):
    zf = z.astype(jnp.float32)
    xhat = (zf - mean) * rstd
    # single definition of the gate gradient, shared with the Pallas
    # kernels (_gate_grad) so the two impls cannot silently diverge
    g = _gate_grad(
        xhat * scale + bias, mask.astype(jnp.float32), ct_agg
    )
    d_bias = g.sum(axis=(0, 1))
    d_scale = (g * xhat).sum(axis=(0, 1))
    dxhat = g * scale
    c = jnp.maximum(n_real, 1.0)
    mean_dxhat = dxhat.sum(axis=(0, 1)) / c
    mean_dxhat_xhat = (dxhat * xhat).sum(axis=(0, 1)) / c
    mf = mask.astype(jnp.float32)[..., None]
    dz = rstd * (dxhat - mf * (mean_dxhat + xhat * mean_dxhat_xhat))
    return dz.astype(z.dtype), d_scale, d_bias


# ---------------------------------------------------------------------------
# impl='pallas': explicit VMEM blocking over the node axis
# ---------------------------------------------------------------------------


def _row_keep(i, bn, n, m):
    """[bn, m] f32: 1 where global row i*bn+r < n (tail-block masking).

    ``n`` is the STATIC node capacity (baked at trace time); out-of-range
    rows of the final grid block read padded garbage that must not reach
    the masked sums."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (bn, m), 0) + i * bn
    return (rows < n).astype(jnp.float32)


def _gate_grad(y, mask, ct):
    """dL/dy [BN, M, 2F] from ct [BN, F] through sigmoid*softplus*mask."""
    f = y.shape[-1] // 2
    sg = jax.nn.sigmoid(y[..., :f])
    spg = jax.nn.sigmoid(y[..., f:])  # softplus' = sigmoid
    sp = jax.nn.softplus(y[..., f:])
    dmsg = ct[:, None, :] * mask[..., None]
    return jnp.concatenate(
        [dmsg * sg * (1.0 - sg) * sp, dmsg * sg * spg], axis=-1
    )


def _apply_kernel(z_ref, mask_ref, cst_ref, agg_ref, *, n):
    pid = pl.program_id(0)
    z = z_ref[...].astype(jnp.float32)  # [BN, M, 2F]
    mean, rstd, scale, bias = (cst_ref[k] for k in range(4))
    y = (z - mean) * (rstd * scale) + bias
    keep = _row_keep(pid, z.shape[0], n, z.shape[1])
    msg = _gate(y, mask_ref[...] * keep)
    agg_ref[...] = msg.sum(axis=1)


def _reduce_kernel(z_ref, mask_ref, cst_ref, ct_ref, out_ref, *, n):
    pid = pl.program_id(0)
    z = z_ref[...].astype(jnp.float32)
    mean, rstd, scale, bias = (cst_ref[k] for k in range(4))
    keep = _row_keep(pid, z.shape[0], n, z.shape[1])
    mask = mask_ref[...] * keep
    xhat = (z - mean) * rstd
    g = _gate_grad(xhat * scale + bias, mask, ct_ref[...])
    dxhat = g * scale
    part = jnp.stack([
        g.sum(axis=(0, 1)),               # d_bias
        (g * xhat).sum(axis=(0, 1)),      # d_scale
        dxhat.sum(axis=(0, 1)),           # sum dxhat
        (dxhat * xhat).sum(axis=(0, 1)),  # sum dxhat*xhat
    ])

    @pl.when(pid == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += part


def _dz_kernel(z_ref, mask_ref, cst_ref, red_ref, ct_ref, dz_ref, *, n):
    pid = pl.program_id(0)
    z = z_ref[...].astype(jnp.float32)
    mean, rstd, scale, bias = (cst_ref[k] for k in range(4))
    keep = _row_keep(pid, z.shape[0], n, z.shape[1])
    mask = mask_ref[...] * keep
    xhat = (z - mean) * rstd
    g = _gate_grad(xhat * scale + bias, mask, ct_ref[...])
    dxhat = g * scale
    mean_dxhat = red_ref[2] * red_ref[4, 0]       # x 1/C, precomputed
    mean_dxhat_xhat = red_ref[3] * red_ref[4, 0]
    dz = rstd * (
        dxhat - mask[..., None] * (mean_dxhat + xhat * mean_dxhat_xhat)
    )
    dz_ref[...] = dz.astype(dz_ref.dtype)


def _pallas_apply(z, mask, mean, rstd, scale, bias):
    n, m, c2 = z.shape
    bn = min(_BLOCK_N, n)
    grid = (pl.cdiv(n, bn),)
    cst = jnp.stack([mean, rstd, scale, bias])
    return pl.pallas_call(
        functools.partial(_apply_kernel, n=n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, m, c2), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, m), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, c2 // 2), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, c2 // 2), jnp.float32),
    )(z, mask.astype(jnp.float32), cst)


def _pallas_bwd(z, mask, mean, rstd, scale, bias, n_real, ct_agg):
    n, m, c2 = z.shape
    bn = min(_BLOCK_N, n)
    grid = (pl.cdiv(n, bn),)
    cst = jnp.stack([mean, rstd, scale, bias])
    mask_f = mask.astype(jnp.float32)
    ct = ct_agg.astype(jnp.float32)

    red = pl.pallas_call(
        functools.partial(_reduce_kernel, n=n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, m, c2), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, m), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, c2 // 2), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((4, c2), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((4, c2), jnp.float32),
    )(z, mask_f, cst, ct)

    d_bias, d_scale = red[0], red[1]
    inv_c = (1.0 / jnp.maximum(n_real, 1.0)) * jnp.ones((1, c2), jnp.float32)
    red5 = jnp.concatenate([red, inv_c], axis=0)  # row 4 = 1/C broadcast

    dz = pl.pallas_call(
        functools.partial(_dz_kernel, n=n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, m, c2), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, m), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, c2 // 2), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, m, c2), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, m, c2), z.dtype),
    )(z, mask_f, cst, red5, ct)
    return dz, d_scale, d_bias


# ---------------------------------------------------------------------------
# custom-VJP wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_epilogue(z, mask, scale, bias, eps: float, impl: str):
    """(agg [N, F] f32, mean [2F], var [2F], count) — training mode.

    Differentiable in (z, scale, bias); mask gets a zero cotangent; the
    stats outputs feed the (undifferentiated) running-stat EMA.
    """
    agg, mean, var, n_real, _, _ = _fwd_parts(z, mask, scale, bias, eps, impl)
    return agg, mean, var, n_real


def _fwd_parts(z, mask, scale, bias, eps, impl):
    mean, var, n_real = _masked_stats(z, mask)
    rstd = jax.lax.rsqrt(var + eps)
    if impl == "pallas":
        agg = _pallas_apply(z, mask, mean, rstd, scale, bias)
    else:
        agg = _apply_xla(z, mask, mean, rstd, scale, bias)
    return agg, mean, var, n_real, rstd, None


def _fused_fwd(z, mask, scale, bias, eps, impl):
    agg, mean, var, n_real, rstd, _ = _fwd_parts(z, mask, scale, bias, eps,
                                                 impl)
    return (agg, mean, var, n_real), (z, mask, mean, rstd, scale, bias,
                                      n_real)


def _fused_bwd(eps, impl, res, cts):
    z, mask, mean, rstd, scale, bias, n_real = res
    ct_agg = cts[0]  # stats outputs feed only the stop-gradient EMA
    if impl == "pallas":
        dz, d_scale, d_bias = _pallas_bwd(
            z, mask, mean, rstd, scale, bias, n_real, ct_agg
        )
    else:
        dz, d_scale, d_bias = _bwd_xla(
            z, mask, mean, rstd, scale, bias, n_real, ct_agg
        )
    return dz, jnp.zeros_like(mask), d_scale, d_bias


fused_epilogue.defvjp(_fused_fwd, _fused_bwd)


def fused_epilogue_eval(z, mask, scale, bias, mean, var, eps: float,
                        impl: str = "xla"):
    """Eval-mode epilogue: normalize with running stats, gate, mask, sum."""
    rstd = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    m32 = mean.astype(jnp.float32)
    if impl == "pallas":
        return _pallas_apply(z, mask, m32, rstd, scale, bias)
    return _apply_xla(z, mask, m32, rstd, scale, bias)


class FusedBN1GateSum(nn.Module):
    """Drop-in for CGConv's BN1 -> gate -> mask -> sum chain (dense layout).

    Owns the SAME parameter/collection names as ``MaskedBatchNorm(name=
    'bn1')`` — scale/bias params, mean/var batch_stats — so checkpoints
    trained either way restore interchangeably. Output is the aggregated
    [N, F] message sum in f32 (CGConv casts as needed).
    """

    momentum: float = 0.1
    epsilon: float = 1e-5
    impl: str = "xla"  # 'xla' (structured jnp) | 'pallas'

    @nn.compact
    def __call__(self, z, mask, use_running_average: bool = False):
        features = z.shape[-1]
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros(features, jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones(features, jnp.float32)
        )
        scale = self.param("scale", nn.initializers.ones, (features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (features,),
                          jnp.float32)
        if use_running_average:
            return fused_epilogue_eval(
                z, mask, scale, bias, ra_mean.value, ra_var.value,
                self.epsilon, self.impl,
            )
        agg, mean, var, n_real = fused_epilogue(
            z, mask, scale, bias, self.epsilon, self.impl
        )
        if not self.is_initializing():
            has_rows = n_real > 0
            unbiased = var * n_real / jnp.maximum(n_real - 1.0, 1.0)
            ra_mean.value = jnp.where(
                has_rows,
                (1.0 - self.momentum) * ra_mean.value + self.momentum * mean,
                ra_mean.value,
            )
            ra_var.value = jnp.where(
                has_rows,
                (1.0 - self.momentum) * ra_var.value
                + self.momentum * unbiased,
                ra_var.value,
            )
        return agg
