"""Masked BatchNorm — padding-aware batch normalization.

The reference normalizes over all N·M edge slots and all N node slots with
cuDNN/ATen BatchNorm1d (SURVEY.md §2 component 6). On TPU the batch is padded
to static capacity, and padding rows must not pollute the batch statistics
(SURVEY.md §7 "hard parts" #3) — this module computes masked moments.

Semantics mirror ``torch.nn.BatchNorm1d`` for the oracle parity harness
(SURVEY.md §4.3):

- normalization uses the *biased* batch variance (divide by n);
- running-variance updates use the *unbiased* estimate (divide by n-1);
- running stats update as ``running = (1-momentum)*running + momentum*batch``
  with torch's default momentum 0.1;
- eval mode normalizes with running stats.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

# Diagnostic/experiment knob: force the two-pass centered variance even for
# float32 statistics (the f64 oracle path always uses it). Costs one extra
# full read of the activation per BN; exists so accuracy A/Bs can isolate
# the one-pass estimator (scripts/mae_ab.py) and as an escape hatch.
_FORCE_TWO_PASS = False


def force_two_pass_stats(enabled: bool = True) -> None:
    global _FORCE_TWO_PASS
    _FORCE_TWO_PASS = enabled


class MaskedBatchNorm(nn.Module):
    """BatchNorm1d over rows [..., C] with an optional [...] validity mask.

    All leading axes are batch axes (statistics reduce over every axis but
    the last), so callers with a dense edge-slot layout can pass [N, M, C]
    + mask [N, M] directly — numerically identical to flattening to
    [N*M, C] first, but without the reshape, which on TPU is a real
    layout-change copy for (8,128)-tiled 3-D tensors (measured ~16% of
    step time as "data formatting" before this was removed).
    """

    momentum: float = 0.1
    epsilon: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True
    # output dtype; statistics follow promote_types(input, float32), so
    # float64 activations keep float64 running stats (oracle parity)
    dtype: jnp.dtype | None = None
    # when the row axis is sharded across a mesh axis (edge-sharded graph
    # parallelism), moments must be computed over ALL shards: f32-stat
    # mode psums (count, sum, sum-of-squares) once; f64-stat mode (the
    # oracle-parity path) psums count+mean first and the centered
    # variance second, keeping the single-device centered numerics
    axis_name: str | None = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        mask: jax.Array | None = None,
        use_running_average: bool = False,
    ) -> jax.Array:
        features = x.shape[-1]
        # statistics in >= float32 (float64 when the input is float64, for
        # the double-precision oracle parity harness)
        stat_dtype = jnp.promote_types(x.dtype, jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros(features, jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones(features, jnp.float32)
        )

        reduce_axes = tuple(range(x.ndim - 1))
        # One-pass moments (E[x^2] - E[x]^2) in float32-stat mode: both
        # sums reduce over a single read of x, where the centered two-pass
        # form costs an extra full pass over the (large) activation per BN
        # per direction. The two-pass form is kept for float64 stats —
        # the double-precision oracle parity harness pins 1e-8 agreement
        # with torch, and one-pass cancellation error would show there.
        one_pass = stat_dtype == jnp.float32 and not _FORCE_TWO_PASS
        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(stat_dtype)
            if one_pass:
                # Shift-invariant accumulation: var(x) = var(x - c) for any
                # per-feature c, and a c near the data mean prevents the
                # catastrophic cancellation of E[x^2] - E[x]^2 when
                # |mean| >> std (f32 keeps ~7 digits; at mean 1e4, std 1 the
                # unshifted form returns var = 0 and rsqrt AMPLIFIES). The
                # leading row-block is real data (pack_graphs places padding
                # last), and correctness never depends on the choice of c —
                # only the cancellation magnitude does. The subtract fuses
                # into the same single read of x.
                shift = jax.lax.stop_gradient(
                    xf[:1].mean(axis=tuple(range(xf.ndim - 1)))
                )
                if self.axis_name is not None:
                    # shards must agree on c or their (s1, s2) can't be
                    # psum-combined
                    shift = jax.lax.pmean(shift, self.axis_name)
                xs = xf - shift
            else:
                xs = xf
            if mask is not None:
                m = mask.astype(stat_dtype)
                n_real = m.sum()
                xm = xs * m[..., None]
                s1 = xm.sum(axis=reduce_axes)
                s2 = (xm * xs).sum(axis=reduce_axes) if one_pass else None
            else:
                m = None
                n_real = jnp.asarray(
                    np.prod([x.shape[a] for a in reduce_axes]), stat_dtype
                )
                s1 = xs.sum(axis=reduce_axes)
                s2 = (xs * xs).sum(axis=reduce_axes) if one_pass else None
            if self.axis_name is not None:
                if one_pass:
                    n_real, s1, s2 = jax.lax.psum(
                        (n_real, s1, s2), self.axis_name)
                else:
                    n_real, s1 = jax.lax.psum((n_real, s1), self.axis_name)
            n = jnp.maximum(n_real, 1.0)
            if one_pass:
                mean_s = s1 / n
                var = jnp.maximum(s2 / n - mean_s * mean_s, 0.0)
                mean = mean_s + shift
            else:
                mean = s1 / n
                centered = (xf - mean) ** 2
                ss = (
                    (centered * m[..., None]).sum(axis=reduce_axes)
                    if m is not None
                    else centered.sum(axis=reduce_axes)
                )
                if self.axis_name is not None:
                    ss = jax.lax.psum(ss, self.axis_name)
                var = ss / n
            if not self.is_initializing():
                # a fully-masked batch (all padding, e.g. an empty DP eval
                # shard) must not decay the running stats toward (0, 0)
                has_rows = n_real > 0
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                ra_mean.value = jnp.where(
                    has_rows,
                    (1.0 - self.momentum) * ra_mean.value + self.momentum * mean,
                    ra_mean.value,
                )
                ra_var.value = jnp.where(
                    has_rows,
                    (1.0 - self.momentum) * ra_var.value + self.momentum * unbiased,
                    ra_var.value,
                )

        y = (x.astype(stat_dtype) - mean) * jax.lax.rsqrt(
            var.astype(stat_dtype) + self.epsilon
        )
        if self.use_scale:
            y = y * self.param("scale", nn.initializers.ones, (features,), jnp.float32)
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros, (features,), jnp.float32)
        return y.astype(self.dtype or x.dtype)
