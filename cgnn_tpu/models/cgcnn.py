"""CGCNN in Flax: edge-gated graph convolution over flat COO edges.

Reference semantics (SURVEY.md §2 component 6, §3.3) per conv layer:

    z      = cat(v_i, v_j, e_ij)           # per edge
    z      = BatchNorm(Linear(z))          # 2F+G -> 2F, BN over edges
    gate, core = split(z)
    msg    = sigmoid(gate) * softplus(core)
    agg_i  = sum_j msg_ij                  # per-node scatter-sum
    v_i'   = softplus(v_i + BatchNorm(agg_i))

and the full model: Linear(92->F) embedding, n_conv such layers, per-crystal
mean pooling, softplus MLP head (LogSoftmax head for classification).

TPU-first design choices:
- flat COO edge list (gather + masked segment-sum on sorted centers) instead
  of the reference's dense [N, M] gather — composes with bucketed padding and
  maps directly onto XLA scatter / the Pallas kernel (ops/segment.py);
- masked BatchNorm / pooling so static-shape padding never leaks into
  statistics (SURVEY.md §7 hard parts #1, #3);
- optional bfloat16 compute for the MXU, float32 params and statistics.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from cgnn_tpu.data.graph import GraphBatch
from cgnn_tpu.ops.norm import MaskedBatchNorm
from cgnn_tpu.ops.segment import (
    aggregate_edge_messages,
    gather,
    gather_transpose,
    segment_mean,
)


class _SplitFcFull(nn.Module):
    """``fc_full`` (Linear 2F+G -> 2F) computed as three sliced matmuls.

    Parameter shapes/names are EXACTLY nn.Dense(2F) on the concatenated
    [v_i, v_j, e] input — checkpoints and oracle weight transplants are
    unchanged — but the [N, M, 2F+G] concat is never materialized and the
    v_i slice contracts per NODE ([N,F]@[F,2F], then broadcasts over M):
    M-fold fewer FLOPs and bytes for that term. Measured: the concat write
    + read was the largest single HBM cost of the step (trace r3, PERF.md).
    """

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, v_i, v_j, e):  # [N,F], [N,M,F], [N,M,G]
        f, g = v_i.shape[-1], e.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (2 * f + g, self.features),
            jnp.float32,
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), jnp.float32
        )
        k = kernel.astype(self.dtype)
        z = (
            (v_i.astype(self.dtype) @ k[:f])[:, None, :]
            + v_j.astype(self.dtype) @ k[f : 2 * f]
            + e.astype(self.dtype) @ k[2 * f :]
        )
        return z + bias.astype(self.dtype)


class CGConv(nn.Module):
    """One edge-gated crystal-graph convolution (reference ``ConvLayer``)."""

    features: int
    dtype: Any = jnp.float32
    aggregation_impl: str | None = None  # None -> global default (ops/segment.py)
    assume_sorted_edges: bool = True  # GraphBatch from pack_graphs guarantees it
    # BatchNorm makes per-edge outputs depend on batch statistics; for energy
    # models that's the reference semantics, but a force field must NOT use
    # it: F = -dE/dr picks up gradient terms through the batch moments in
    # train mode that vanish under running stats at eval, so the learned
    # forces disagree between modes (measured: eval force MAE ~5x worse).
    use_batchnorm: bool = True
    # edge-sharded graph parallelism (SURVEY.md §5 "long-context analog"):
    # when the edge axis is sharded over this mesh axis, per-node partial
    # aggregates are psum-ed back to full sums and edge-BN moments span all
    # shards. Only valid inside shard_map with the axis bound.
    edge_axis_name: str | None = None
    # dense slot layout (pack_graphs dense_m): node n owns edge slots
    # [n*M, (n+1)*M). Aggregation becomes a plain sum over M — no scatter
    # in the forward, and its transpose is a broadcast — and the per-edge
    # v_i gather becomes a broadcast. On v5e this path removes the XLA
    # scatter that runs ~50x below HBM bandwidth (the CUDA atomicAdd
    # analog of SURVEY.md §2 N2, solved the TPU way: layout, not atomics).
    dense_m: int | None = None
    # fused BN1->gate->mask->sum epilogue (ops/fused_epilogue.py): None
    # keeps the unfused reference path; 'xla' uses the hand-structured
    # minimal-pass custom VJP; 'pallas' adds explicit VMEM blocking.
    # Dense layout + use_batchnorm only; numerics match to f32 roundoff.
    # MEASURED NEGATIVE on v5e (both impls 5-20% slower than unfused —
    # the custom-VJP boundary blocks producer/consumer fusion; PERF.md
    # 6b); default stays None.
    fused_epilogue: str | None = None
    # WHOLE-conv fused kernel (ops/pallas_cgconv.py, ROADMAP item 2):
    # the entire dense branch — gather, fc_full, BN1, gate, mask,
    # sum-over-M — as one custom-VJP op whose 'pallas' impl runs per
    # 128-node block entirely in VMEM (v_j and z never exist in HBM;
    # backward rematerializes). 'xla' is the structured jnp twin (the
    # §6b methodology: isolates structure from hand scheduling). Dense
    # layout + BatchNorm, no graph sharding, mutually exclusive with
    # fused_epilogue. cgconv_window=0 gathers over the whole node range
    # (always correct; tests); a positive value is the CALLER-guaranteed
    # neighbor-window bound from pallas_cgconv.window_width(max graph
    # nodes) — an undersized bound silently zeroes out-of-window
    # neighbors, so only pass one derived from the real dataset.
    cgconv_impl: str | None = None
    cgconv_window: int = 0

    @nn.compact
    def __call__(
        self,
        nodes: jax.Array,  # [N, F]
        edges: jax.Array,  # [E, G]
        centers: jax.Array,  # [E]
        neighbors: jax.Array,  # [E]
        edge_mask: jax.Array,  # [E]
        node_mask: jax.Array,  # [N]
        train: bool = False,
        in_slots: jax.Array | None = None,  # [N*In] i32 flat transpose of
        #   neighbors (pack_graphs stores it flat; gather_transpose wants
        #   flat indices — the on-device 2-D->1-D reshape costs a relayout)
        in_mask: jax.Array | None = None,  # [N, In]
        over_slots: jax.Array | None = None,  # [O] two-tier overflow
        over_nodes: jax.Array | None = None,  # [O]
        over_mask: jax.Array | None = None,  # [O]
    ) -> jax.Array:
        f = self.features
        if self.fused_epilogue is not None and (
            self.dense_m is None or not self.use_batchnorm
            or self.edge_axis_name is not None
        ):
            raise NotImplementedError(
                "fused_epilogue requires the dense layout with BatchNorm "
                "(it fuses the BN1->gate->mask->sum chain) and no graph "
                "sharding"
            )
        if self.cgconv_impl is not None:
            if (self.dense_m is None or not self.use_batchnorm
                    or self.edge_axis_name is not None):
                raise NotImplementedError(
                    "cgconv_impl (the whole-conv fused kernel) requires "
                    "the dense layout with BatchNorm and no graph sharding"
                )
            if self.fused_epilogue is not None:
                raise NotImplementedError(
                    "cgconv_impl subsumes fused_epilogue (the whole conv "
                    "is one op); pick one"
                )
        if self.dense_m is not None and self.edge_axis_name is not None:
            # Node-strip sharded dense layout (graph parallelism composed
            # with the fast path; parallel/edge_parallel.py). Shard s owns
            # the contiguous node strip [s*N/D, (s+1)*N/D) and — by dense
            # slot ownership — exactly its [N/D, M] edge slots, so the
            # per-node message sum is COMPLETE shard-locally (no psum for
            # aggregation, unlike the COO edge-sharded branch). The one
            # per-conv collective is the psum of the zero-padded strip
            # aggregates back to full [N, F] (its transpose distributes the
            # next conv's cotangent). BN1 moments span shards via
            # axis_name; BN2 + the residual run on the replicated full
            # aggregate, bit-identical to the unsharded dense path.
            axis = self.edge_axis_name
            m = self.dense_m
            n_full = nodes.shape[0]
            fdim = nodes.shape[-1]
            e = edges.astype(nodes.dtype)
            if e.ndim == 2:
                e = e.reshape(-1, m, e.shape[-1])
            n_strip = e.shape[0]
            idx = jax.lax.axis_index(axis)
            # linear_call (gather_transpose) does not insert the implicit
            # replicated->varying cast standard ops get, so cast explicitly:
            # the cast's transpose is the psum that completes each shard's
            # partial [N, F] node cotangent (compat: identity on jax
            # without pcast, where check_rep is off and the psum comes
            # from the P() in-spec transpose — parallel/compat.py)
            from cgnn_tpu.parallel.compat import pcast

            nodes_v = pcast(nodes, axis, to="varying")
            if in_slots is not None:
                # per-shard two-tier mappings arrive with a leading
                # singleton from the shard-stack axis (graph.py
                # shard_transpose_slots): squeeze to this shard's mapping.
                # A non-singleton means the mapping was built for a
                # different shard count than this mesh — [0] would then
                # silently drop cotangents, so refuse at trace time.
                if in_slots.shape[0] != 1:
                    raise ValueError(
                        f"per-shard transpose mapping was built for "
                        f"{in_slots.shape[0]}x this mesh's graph-shard "
                        f"count (pack with transpose_shards == the mesh's "
                        f"'graph' axis size)"
                    )
                v_j = gather_transpose(
                    nodes_v, neighbors, in_slots[0], in_mask[0],
                    over_slots=None if over_slots is None else over_slots[0],
                    over_nodes=None if over_nodes is None else over_nodes[0],
                    over_mask=None if over_mask is None else over_mask[0],
                ).reshape(n_strip, m, fdim)
            else:  # eval batches carry no transpose mapping
                v_j = gather(nodes_v, neighbors).reshape(n_strip, m, fdim)
            nodes_strip = jax.lax.dynamic_slice_in_dim(
                nodes, idx * n_strip, n_strip
            )
            z = _SplitFcFull(2 * f, dtype=self.dtype, name="fc_full")(
                nodes_strip, v_j, e
            )
            emask = edge_mask.reshape(n_strip, m)
            if self.use_batchnorm:
                z = MaskedBatchNorm(
                    dtype=self.dtype, name="bn1", axis_name=axis
                )(z, mask=emask, use_running_average=not train)
            gate, core = jnp.split(z, 2, axis=-1)
            msg = nn.sigmoid(gate) * nn.softplus(core)
            # zero cotangent on padding slots — load-bearing for the
            # scatter-free backward exactly as in the unsharded branch
            msg = msg * emask[..., None].astype(msg.dtype)
            agg_strip = msg.sum(axis=1)  # [N/D, F], complete per node
            agg = jax.lax.psum(
                jax.lax.dynamic_update_slice_in_dim(
                    jnp.zeros((n_full, f), agg_strip.dtype), agg_strip,
                    idx * n_strip, axis=0,
                ),
                axis,
            )
        elif self.dense_m is not None and self.cgconv_impl is not None:
            # WHOLE-conv fused kernel (ops/pallas_cgconv.py): gather +
            # fc_full + BN1 + gate + mask + sum as ONE custom-VJP op —
            # v_j and z never exist in HBM ('pallas') or as named
            # intermediates ('xla' structured twin). Parameter tree
            # identical to the unfused branch (fc_full + bn1 shells);
            # BN2 + the residual below are unchanged.
            from cgnn_tpu.ops.pallas_cgconv import (
                BN1Params,
                FcFullParams,
                fused_cgconv,
                fused_cgconv_eval,
            )

            m = self.dense_m
            n = nodes.shape[0]
            e = edges
            if e.ndim == 2:
                e = e.reshape(n, m, -1)
            emask2 = edge_mask.reshape(n, m)
            tr = (None if in_slots is None else
                  (in_slots, in_mask, over_slots, over_nodes, over_mask))
            kernel, kbias = FcFullParams(2 * f, name="fc_full")(
                2 * f + e.shape[-1]
            )
            bn1 = BN1Params(name="bn1")
            scale, bn_bias, r_mean, r_var = bn1(2 * f)
            if train:
                agg, mean, var, n_real = fused_cgconv(
                    nodes, e, kernel, kbias, scale, bn_bias, neighbors,
                    emask2, tr, impl=self.cgconv_impl,
                    window=self.cgconv_window, dtype=self.dtype,
                )
                bn1(2 * f, update=(mean, var, n_real))
            else:
                agg = fused_cgconv_eval(
                    nodes, e, kernel, kbias, scale, bn_bias, neighbors,
                    emask2, r_mean, r_var, tr, impl=self.cgconv_impl,
                    window=self.cgconv_window, dtype=self.dtype,
                )
            agg = agg.astype(nodes.dtype)
        elif self.dense_m is not None:
            m = self.dense_m
            n = nodes.shape[0]
            fdim = nodes.shape[-1]
            if in_slots is not None:
                # scatter-free backward via the packed transpose mapping
                # (two-tier when the batch carries overflow slots). NOTE:
                # a slot-space variant (2-D index gathers keeping both
                # directions in [N, M, F]) was tried to kill the relayout
                # copies and measured 19% SLOWER end-to-end (17.2 vs 14.5
                # ms/step, r3 trace5) — multi-dim gather lowering costs
                # more than the copies it saves; keep the flat form.
                v_j = gather_transpose(
                    nodes, neighbors, in_slots, in_mask,
                    over_slots=over_slots, over_nodes=over_nodes,
                    over_mask=over_mask,
                ).reshape(n, m, fdim)
            else:
                v_j = gather(nodes, neighbors).reshape(n, m, fdim)
            # dense batches carry edges pre-shaped [N, M, G] (pack_graphs)
            e = edges.astype(nodes.dtype)
            if e.ndim == 2:  # direct pack_graphs callers with flat edges
                e = e.reshape(n, m, -1)
            # sliced matmuls: no [N, M, 2F+G] concat, v_i term per-node
            z = _SplitFcFull(2 * f, dtype=self.dtype, name="fc_full")(
                nodes, v_j, e
            )
            if self.use_batchnorm and self.fused_epilogue is not None:
                # one custom-VJP op for BN1+gate+mask+sum with minimal
                # activation passes (ops/fused_epilogue.py). Parameter
                # tree identical to the unfused path (name='bn1'). The
                # padding-slot zero-cotangent contract below holds here
                # too: the kernel folds the mask into both the forward
                # message and dz.
                from cgnn_tpu.ops.fused_epilogue import FusedBN1GateSum

                agg = FusedBN1GateSum(
                    impl=self.fused_epilogue, name="bn1"
                )(
                    z, edge_mask.reshape(n, m),
                    use_running_average=not train,
                ).astype(nodes.dtype)
            else:
                if self.use_batchnorm:
                    # 3-D BN: statistics over the (N, M) slot axes directly —
                    # flattening to [N*M, 2F] costs a real layout-change copy
                    z = MaskedBatchNorm(dtype=self.dtype, name="bn1")(
                        z, mask=edge_mask.reshape(n, m),
                        use_running_average=not train,
                    )
                gate, core = jnp.split(z, 2, axis=-1)
                msg = nn.sigmoid(gate) * nn.softplus(core)
                # LOAD-BEARING for gradients, not just values:
                # gather_transpose's scatter-free VJP assumes zero cotangent
                # on padding edge slots, which THIS mask (together with
                # masked BN statistics) guarantees. Removing it would
                # silently corrupt node gradients (ops/segment.py
                # gather_transpose docstring; parity test:
                # tests/test_batching.py two-tier backward).
                msg = msg * edge_mask.reshape(n, m, 1).astype(msg.dtype)
                agg = msg.sum(axis=1)
        else:
            v_i = gather(nodes, centers)
            v_j = gather(nodes, neighbors)
            z = jnp.concatenate([v_i, v_j, edges.astype(nodes.dtype)], axis=-1)
            z = nn.Dense(2 * f, dtype=self.dtype, name="fc_full")(z)
            if self.use_batchnorm:
                z = MaskedBatchNorm(
                    dtype=self.dtype, name="bn1", axis_name=self.edge_axis_name
                )(z, mask=edge_mask, use_running_average=not train)
            gate, core = jnp.split(z, 2, axis=-1)
            msg = nn.sigmoid(gate) * nn.softplus(core)
            msg = msg * edge_mask[:, None].astype(msg.dtype)
            agg = aggregate_edge_messages(
                msg,
                centers,
                nodes.shape[0],
                impl=self.aggregation_impl,
                indices_are_sorted=self.assume_sorted_edges,
            )
            if self.edge_axis_name is not None:
                # partial per-node sums from this edge shard -> full sums
                agg = jax.lax.psum(agg, self.edge_axis_name)
        if self.use_batchnorm:
            agg = MaskedBatchNorm(dtype=self.dtype, name="bn2")(
                agg, mask=node_mask, use_running_average=not train
            )
        out = nn.softplus(nodes + agg)
        return out * node_mask[:, None].astype(out.dtype)


class CrystalGraphConvNet(nn.Module):
    """Full CGCNN (reference ``CrystalGraphConvNet``, SURVEY.md §2 component 7).

    Returns [G, num_targets] regression outputs (or [G, num_classes] log-probs
    when ``classification``), one row per graph slot; padding slots are
    zeroed. Use ``target_mask``/``graph_mask`` in the loss.
    """

    atom_fea_len: int = 64
    n_conv: int = 3
    h_fea_len: int = 128
    n_h: int = 1
    num_targets: int = 1
    classification: bool = False
    num_classes: int = 2
    dropout_rate: float = 0.0  # reference applies dropout for classification
    dtype: Any = jnp.float32
    aggregation_impl: str | None = None
    assume_sorted_edges: bool = True
    head: nn.Module | None = None  # e.g. MultiTaskHead; replaces fc stack
    edge_axis_name: str | None = None  # edge-sharded graph parallelism
    dense_m: int | None = None  # dense slot layout (see CGConv.dense_m)
    fused_epilogue: str | None = None  # see CGConv.fused_epilogue
    cgconv_impl: str | None = None  # whole-conv fused kernel (CGConv)
    cgconv_window: int = 0  # neighbor-window bound (CGConv.cgconv_window)

    @nn.compact
    def __call__(
        self, batch: GraphBatch, train: bool = False, return_node_features: bool = False
    ):
        nodes = nn.Dense(self.atom_fea_len, dtype=self.dtype, name="embedding")(
            batch.nodes.astype(self.dtype)
        )
        nodes = nodes * batch.node_mask[:, None].astype(nodes.dtype)
        for i in range(self.n_conv):
            nodes = CGConv(
                features=self.atom_fea_len,
                dtype=self.dtype,
                aggregation_impl=self.aggregation_impl,
                assume_sorted_edges=self.assume_sorted_edges,
                edge_axis_name=self.edge_axis_name,
                dense_m=self.dense_m,
                fused_epilogue=self.fused_epilogue,
                cgconv_impl=self.cgconv_impl,
                cgconv_window=self.cgconv_window,
                name=f"conv_{i}",
            )(
                nodes,
                batch.edges,
                batch.centers,
                batch.neighbors,
                batch.edge_mask,
                batch.node_mask,
                train=train,
                in_slots=batch.in_slots,
                in_mask=batch.in_mask,
                over_slots=batch.over_slots,
                over_nodes=batch.over_nodes,
                over_mask=batch.over_mask,
            )
        # per-crystal masked mean pooling (reference `pooling`)
        crys = segment_mean(
            nodes,
            batch.node_graph,
            batch.graph_capacity,
            weights=batch.node_mask.astype(nodes.dtype),
        )
        crys = nn.Dense(self.h_fea_len, dtype=self.dtype, name="conv_to_fc")(
            nn.softplus(crys)
        )
        crys = nn.softplus(crys)
        if self.classification and self.dropout_rate > 0:
            crys = nn.Dropout(self.dropout_rate, deterministic=not train)(crys)
        if self.head is not None:
            out = self.head(crys)
        else:
            for i in range(self.n_h - 1):
                crys = nn.softplus(
                    nn.Dense(self.h_fea_len, dtype=self.dtype, name=f"fc_{i}")(crys)
                )
            out_dim = self.num_classes if self.classification else self.num_targets
            out = nn.Dense(out_dim, dtype=self.dtype, name="fc_out")(crys)
            if self.classification:
                out = nn.log_softmax(out, axis=-1)
        out = out * batch.graph_mask[:, None].astype(out.dtype)
        # promote low-precision (bf16) compute back to f32; keep f64 as-is
        out = out.astype(jnp.promote_types(jnp.float32, out.dtype))
        if return_node_features:
            return out, nodes
        return out
