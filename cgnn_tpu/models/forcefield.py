"""Differentiable force field: positions -> energy -> forces by autodiff.

BASELINE.json config #5 (MD17 per-atom force head) requires forces. The
reference lineage's data path precomputes distances on the host, which cuts
the autodiff graph at the geometry — so this model recomputes displacement
vectors *inside* the forward pass from positions + neighbor indices +
periodic image offsets (SURVEY.md §7 phase 7). Forces are then exactly
``F = -dE/dr`` and automatically rotation-equivariant, because E depends on
positions only through interatomic distances.

The conv trunk reuses CGConv; only the edge featurization moves in-model.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from cgnn_tpu.data.graph import GraphBatch
from cgnn_tpu.models.cgcnn import CGConv
from cgnn_tpu.models.heads import ForceHead
from cgnn_tpu.ops.segment import segment_sum


def gaussian_expand(d: jax.Array, dmin: float, dmax: float, step: float) -> jax.Array:
    """jnp twin of data/featurize.py GaussianDistance (differentiable)."""
    mu = jnp.arange(dmin, dmax + step, step, dtype=d.dtype)
    return jnp.exp(-((d[..., None] - mu) ** 2) / step**2)


def edge_distances(batch: GraphBatch, positions: jax.Array) -> jax.Array:
    """Per-edge periodic distances recomputed from positions (differentiable).

    ``positions`` is passed explicitly (not read from the batch) so callers
    can take gradients with respect to it.
    """
    lat_e = batch.lattices[batch.node_graph[batch.centers]]  # [E, 3, 3]
    shift = jnp.einsum("ek,ekj->ej", batch.edge_offsets, lat_e)
    rel = positions[batch.neighbors] + shift - positions[batch.centers]
    # epsilon under the sqrt keeps the gradient finite on masked padding
    # edges (rel == 0); real edges have d >> eps so values are unaffected
    return jnp.sqrt(jnp.sum(rel * rel, axis=-1) + 1e-12)


class ForceFieldCGCNN(nn.Module):
    """CGCNN trunk + per-atom energy readout over in-model edge features."""

    atom_fea_len: int = 64
    n_conv: int = 3
    h_fea_len: int = 64
    dmin: float = 0.0
    dmax: float = 8.0
    step: float = 0.2
    dtype: Any = jnp.float32
    aggregation_impl: str | None = None
    # dense edge-slot layout (data/graph.py pack_graphs dense_m): the
    # scatter-free aggregation applies to the force task too — in-model
    # edge distances compose because dense batches keep the flat
    # centers/neighbors/edge_offsets vectors in slot order. Requires
    # batches packed with the same dense_m.
    dense_m: int | None = None

    @nn.compact
    def __call__(
        self,
        batch: GraphBatch,
        positions: jax.Array | None = None,
        train: bool = False,
    ) -> jax.Array:
        """-> per-graph total energies [G] (padding slots zero).

        ``positions`` defaults to ``batch.positions``; the force path passes
        it explicitly so it can differentiate with respect to it.
        """
        if positions is None:
            positions = batch.positions
        d = edge_distances(batch, positions)
        edge_fea = gaussian_expand(
            d.astype(self.dtype), self.dmin, self.dmax, self.step
        ) * batch.edge_mask[:, None].astype(self.dtype)
        nodes = nn.Dense(self.atom_fea_len, dtype=self.dtype, name="embedding")(
            batch.nodes.astype(self.dtype)
        )
        nodes = nodes * batch.node_mask[:, None].astype(nodes.dtype)
        for i in range(self.n_conv):
            nodes = CGConv(
                features=self.atom_fea_len,
                dtype=self.dtype,
                aggregation_impl=self.aggregation_impl,
                # BatchNorm breaks train/eval force consistency (see CGConv)
                use_batchnorm=False,
                dense_m=self.dense_m,
                name=f"conv_{i}",
            )(
                nodes,
                edge_fea,
                batch.centers,
                batch.neighbors,
                batch.edge_mask,
                batch.node_mask,
                train=train,
                # dense two-tier transpose slots (None on COO / in_cap=0
                # batches -> CGConv falls back to the plain gather)
                in_slots=batch.in_slots,
                in_mask=batch.in_mask,
                over_slots=batch.over_slots,
                over_nodes=batch.over_nodes,
                over_mask=batch.over_mask,
            )
        atom_energy = ForceHead(h_fea_len=self.h_fea_len, dtype=self.dtype)(
            nodes, batch.node_mask
        )
        per_graph = segment_sum(
            atom_energy.astype(jnp.float32), batch.node_graph, batch.graph_capacity
        )
        return per_graph * batch.graph_mask


def energy_and_forces(
    model: ForceFieldCGCNN, variables, batch: GraphBatch, train: bool = False
):
    """(energies [G], forces [N, 3], new_batch_stats) with F = -dE/dr.

    ``new_batch_stats`` is None in eval mode; in train mode it carries the
    updated BatchNorm running statistics for the caller's state update.
    """

    def total_energy(pos):
        if train:
            e, mutated = model.apply(
                variables, batch, pos, train=True, mutable=["batch_stats"]
            )
            # the trunk is BatchNorm-free (see CGConv.use_batchnorm), so the
            # mutated collection is typically empty
            return jnp.sum(e), (e, mutated.get("batch_stats", {}))
        e = model.apply(variables, batch, pos, train=False)
        return jnp.sum(e), (e, None)

    (_, (energies, new_stats)), grad_pos = jax.value_and_grad(
        total_energy, has_aux=True
    )(batch.positions)
    forces = -grad_pos * batch.node_mask[:, None]
    return energies, forces, new_stats
