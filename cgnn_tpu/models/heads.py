"""Output heads: multi-task regression and per-atom readouts.

The reference's multi-task config (BASELINE.json config #3: formation energy
+ band gap + bulk/shear modulus) shares one conv trunk and predicts several
scalars. Missing labels are handled by ``target_mask`` in the loss, so
datasets with partial label coverage batch together.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class MultiTaskHead(nn.Module):
    """Per-task MLP stacks over shared pooled crystal features.

    Richer than the single shared ``fc_out`` with T outputs (which
    CrystalGraphConvNet(num_targets=T) already provides): each task gets its
    own hidden stack, which matters when tasks have very different scales
    (formation energy vs. bulk modulus).
    """

    num_tasks: int
    h_fea_len: int = 128
    n_h: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, pooled: jax.Array) -> jax.Array:  # [G, H] -> [G, T]
        outs = []
        for t in range(self.num_tasks):
            h = pooled
            for i in range(self.n_h - 1):
                h = nn.softplus(
                    nn.Dense(self.h_fea_len, dtype=self.dtype, name=f"task{t}_fc{i}")(h)
                )
            outs.append(nn.Dense(1, dtype=self.dtype, name=f"task{t}_out")(h))
        return jnp.concatenate(outs, axis=-1)


class ForceHead(nn.Module):
    """Per-atom scalar-energy readout (node features -> per-atom energy).

    Used by the force-field model (models/forcefield.py): per-atom energies
    are summed per crystal and forces come from ``-d(total energy)/d(positions)``
    via autodiff — an equivariant readout by construction (energies depend on
    positions only through interatomic distances).
    """

    h_fea_len: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, node_fea: jax.Array, node_mask: jax.Array) -> jax.Array:
        h = nn.softplus(nn.Dense(self.h_fea_len, dtype=self.dtype, name="fc")(node_fea))
        e = nn.Dense(1, dtype=self.dtype, name="out")(h)[:, 0]
        return e * node_mask.astype(e.dtype)  # [N] per-atom energies
