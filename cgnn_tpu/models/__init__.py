"""Model layer: Flax crystal-graph networks (SURVEY.md §2 components 6-7).

The reference's ``model.py`` (``ConvLayer`` + ``CrystalGraphConvNet``,
PyTorch, dense [N, M] neighbor layout) is rebuilt here on flat COO edges with
masked ops — the idiomatic XLA/segment-op shape (SURVEY.md §7 phase 2).
"""

from cgnn_tpu.models.cgcnn import CGConv, CrystalGraphConvNet
from cgnn_tpu.models.heads import MultiTaskHead, ForceHead
from cgnn_tpu.models.forcefield import ForceFieldCGCNN, energy_and_forces

__all__ = [
    "CGConv",
    "CrystalGraphConvNet",
    "MultiTaskHead",
    "ForceHead",
    "ForceFieldCGCNN",
    "energy_and_forces",
]
