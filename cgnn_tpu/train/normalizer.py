"""Target normalization (SURVEY.md §2 component 8).

The reference's ``Normalizer`` standardizes regression targets with
train-sample mean/std, stores its state inside checkpoints, and denormalizes
at eval/predict time. Here the stats are jnp arrays of shape [T] (one per
task) so they live inside the jitted step and the checkpoint pytree, and
multi-task targets with missing labels are handled via the target mask.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from flax import struct


class Normalizer(struct.PyTreeNode):
    mean: jnp.ndarray  # [T]
    std: jnp.ndarray  # [T]

    @classmethod
    def fit(cls, targets: np.ndarray, mask: np.ndarray | None = None) -> "Normalizer":
        """Per-task masked mean/std over a training sample ([S, T] arrays)."""
        t = np.atleast_2d(np.asarray(targets, np.float64))
        if mask is None:
            m = np.ones_like(t)
        else:
            m = np.atleast_2d(np.asarray(mask, np.float64))
        n = np.maximum(m.sum(axis=0), 1.0)
        mean = (t * m).sum(axis=0) / n
        var = (((t - mean) ** 2) * m).sum(axis=0) / n
        std = np.sqrt(np.maximum(var, 1e-12))
        return cls(
            mean=jnp.asarray(mean, jnp.float32), std=jnp.asarray(std, jnp.float32)
        )

    @classmethod
    def identity(cls, num_targets: int = 1) -> "Normalizer":
        """No-op normalizer (classification / pre-normalized targets)."""
        return cls(
            mean=jnp.zeros(num_targets, jnp.float32),
            std=jnp.ones(num_targets, jnp.float32),
        )

    def norm(self, x):
        return (x - self.mean) / self.std

    def denorm(self, x):
        return x * self.std + self.mean

    def state_dict(self) -> dict:
        return {"mean": np.asarray(self.mean), "std": np.asarray(self.std)}

    @classmethod
    def from_state_dict(cls, d: dict) -> "Normalizer":
        return cls(
            mean=jnp.asarray(d["mean"], jnp.float32),
            std=jnp.asarray(d["std"], jnp.float32),
        )
