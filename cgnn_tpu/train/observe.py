"""Compatibility shim: observability moved to the ``cgnn_tpu.observe``
package (in-scan metric streaming, span tracing, gauges, run manifest —
see its module docs). The names historically importable from here keep
working."""

from cgnn_tpu.observe.metrics_io import (  # noqa: F401
    MetricsLogger,
    enable_debug_nans,
    profile_trace,
)

__all__ = ["MetricsLogger", "enable_debug_nans", "profile_trace"]
