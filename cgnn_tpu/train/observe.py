"""Observability: machine-readable metrics, profiling, NaN debugging.

SURVEY.md §5 prescribes clu.metric_writers -> stdout + TSV/TensorBoard,
a jax.profiler harness, and a debug-nans flag on top of the reference's
print-only logging. ``MetricsLogger`` writes:

- ``metrics.jsonl`` — one JSON object per epoch/event (always; no deps)
- TensorBoard event files via ``clu.metric_writers.SummaryWriter`` when clu
  (+ its TF backing) is importable; degraded silently otherwise

``profile_trace`` wraps a step range in ``jax.profiler.trace`` producing an
xprof/perfetto trace under the log dir.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Iterator


class MetricsLogger:
    """Epoch/event metrics -> metrics.jsonl (+ TensorBoard when available)."""

    def __init__(self, log_dir: str, use_clu: bool = True):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._jsonl = open(
            os.path.join(log_dir, "metrics.jsonl"), "a", buffering=1
        )
        self._writer = None
        if use_clu:
            try:
                from clu import metric_writers

                self._writer = metric_writers.SummaryWriter(log_dir)
            except Exception:  # noqa: BLE001 — TF backing may be absent
                self._writer = None

    def write(self, step: int, values: dict, prefix: str = "") -> None:
        scalars = {
            (f"{prefix}/{k}" if prefix else k): float(v)
            for k, v in values.items()
            if isinstance(v, (int, float)) and v == v  # drop NaNs
        }
        rec = {"step": int(step), "time": time.time(), **scalars}
        self._jsonl.write(json.dumps(rec) + "\n")
        if self._writer is not None:
            self._writer.write_scalars(int(step), scalars)

    def close(self) -> None:
        self._jsonl.close()
        if self._writer is not None:
            self._writer.close()


@contextlib.contextmanager
def profile_trace(log_dir: str, enabled: bool = True) -> Iterator[None]:
    """jax.profiler.trace context (xprof/perfetto trace under log_dir)."""
    if not enabled:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield


def enable_debug_nans() -> None:
    """Fail fast with a traceback at the first NaN any jitted op produces."""
    import jax

    jax.config.update("jax_debug_nans", True)
