"""Train state + optimizer factory (SURVEY.md §2 components 10-11).

One pytree holds everything the jitted step mutates — params, BatchNorm
running stats, optimizer state, step counter, and the target Normalizer —
so checkpointing is a single pytree save and the step can donate the whole
state buffer (XLA reuses the memory in place).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import struct

from cgnn_tpu.train.normalizer import Normalizer


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray  # scalar int32
    params: Any
    batch_stats: Any
    opt_state: Any
    normalizer: Normalizer
    rng: jax.Array  # base key; per-step keys are fold_in(rng, step)
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def variables(self) -> dict:
        return {"params": self.params, "batch_stats": self.batch_stats}

    def apply_gradients(self, grads, new_batch_stats):
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            batch_stats=new_batch_stats,
            opt_state=new_opt_state,
        )


def multistep_lr(
    base_lr: float, milestones: Sequence[int], gamma: float = 0.1
) -> optax.Schedule:
    """torch MultiStepLR twin: multiply lr by gamma at each milestone step."""
    if not milestones:
        return optax.constant_schedule(base_lr)
    return optax.piecewise_constant_schedule(
        base_lr, {int(m): gamma for m in milestones}
    )


def make_optimizer(
    optim: str = "sgd",
    lr: float = 0.01,
    momentum: float = 0.9,
    weight_decay: float = 0.0,
    lr_milestones: Sequence[int] = (),
    lr_gamma: float = 0.1,
    grad_clip: float = 0.0,
) -> optax.GradientTransformation:
    """SGD+momentum or Adam with a MultiStepLR schedule (reference defaults)."""
    schedule = multistep_lr(lr, lr_milestones, lr_gamma)
    if optim.lower() == "sgd":
        core = optax.sgd(schedule, momentum=momentum)
    elif optim.lower() == "adam":
        core = optax.adam(schedule)
    elif optim.lower() == "adamw":
        core = optax.adamw(schedule, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {optim!r} (sgd|adam|adamw)")
    parts = []
    if grad_clip > 0:
        parts.append(optax.clip_by_global_norm(grad_clip))
    if weight_decay > 0 and optim.lower() == "sgd":
        # torch SGD couples weight decay into the gradient
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(core)
    return optax.chain(*parts)


def create_train_state(
    model,
    example_batch,
    tx: optax.GradientTransformation,
    normalizer: Normalizer,
    rng: jax.Array | None = None,
) -> TrainState:
    rng = rng if rng is not None else jax.random.key(0)
    init_rng, state_rng = jax.random.split(rng)
    variables = model.init(init_rng, example_batch)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        normalizer=normalizer,
        rng=state_rng,
        apply_fn=model.apply,
        tx=tx,
    )
