"""Pipelined forward-only inference — predict.py's fast path.

The round-2-era predict loop fetched every batch synchronously; on a
high-latency link every fetch is a full round trip, so inference ran at
r2-era rates while training had moved on (VERDICT r4 weak #5). This path
applies the training loop's lessons to the forward pass:

- snug fill-to-capacity packing + size-class buckets (same policies as
  train.py; >=0.97 padding efficiency at MP scale);
- dispatch pipelining with a windowed value-fetch fence (bounds in-flight
  staged batches without a per-batch round trip);
- ONE stacked device_get per compiled shape instead of one transfer per
  batch (a device-side jnp.stack then a single link transfer).

ISSUE 3 made the compiled shapes injectable: pass ``shape_set`` (a
``serve.shapes.ShapeSet`` — the serving ladder) and batches pack into
those FIXED precompiled rungs instead of deriving fresh per-bucket
capacities — an offline predict job then reuses the online service's
shapes (and, through the persistent XLA cache, its compiled programs),
and the total compile count is pinned at ``len(shape_set)`` regardless
of dataset. ``predict_step`` is likewise injectable, so serve and
predict can share one jitted callable and its jit cache.

ISSUE 4 closed the remaining host gap (BENCH_r05: device 112,305
structs/s vs 1,461 end-to-end — 98.7% of a cold predict run was host
packing on the critical path) three ways, all in this function:

- **compact staging** (``compact=`` / a compact shape set): batches
  stage the raw ``CompactBatch`` form (~12x fewer host bytes written
  and H2D bytes moved) and the exact GraphBatch is rebuilt inside the
  jitted ``predict_step`` via ``make_expander`` — the train path's §7
  win, applied to the forward path, same parity pins;
- **parallel packing** (``pack_workers=``): a bounded pool of packer
  threads (data/pipeline.py) with order-restoring reassembly feeds the
  dispatch window, so the device never waits on a single packer;
- **buffer pooling**: compact packers write into reusable preallocated
  per-shape buffers instead of allocating per batch (the §7 page-fault
  bound); a buffer is recycled only after the window fence proves the
  dispatch that read it completed (FIFO per-device execution order).
"""

from __future__ import annotations

import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cgnn_tpu.data import invariants
from cgnn_tpu.data.graph import (
    assign_size_buckets,
    capacities_for,
    graph_cap_for,
    pack_graphs,
    plan_batches,
)
from cgnn_tpu.data.pipeline import BufferPool, parallel_pack
from cgnn_tpu.train.step import make_predict_step

# in-flight dispatch window before a bounding value fetch (same role as
# train.loop._WINDOW; one fence per window, NOT per batch)
_WINDOW = 16


def _shape_set_plan(graphs: Sequence, shape_set):
    """Yield (index span, graph sublist, shape): greedy fill to the
    LARGEST rung in input order; the ragged tail takes the smallest rung
    that fits it. Input order is preserved by construction, so spans are
    contiguous."""
    big = shape_set.largest
    start = 0
    cur: list = []
    n = e = 0
    for i, g in enumerate(graphs):
        if not shape_set.admits(g):
            raise ValueError(
                f"graph {getattr(g, 'cif_id', i)!r} exceeds the shape set: "
                f"{shape_set.oversize_detail(g)}"
            )
        gn, ge = shape_set.graph_counts(g)
        if cur and not big.fits(len(cur) + 1, n + gn, e + ge):
            yield np.arange(start, i), cur, big
            start, cur, n, e = i, [], 0, 0
        cur.append(g)
        n += gn
        e += ge
    if cur:
        yield (np.arange(start, len(graphs)), cur,
               shape_set.shape_for(len(cur), n, e))


def run_raw_inference(
    state,
    items: Sequence,
    shape_set,
    *,
    predict_step=None,
    devices: Sequence | None = None,
    engine: str = "auto",
    raw_fallback=None,
) -> tuple[np.ndarray, float]:
    """Predict over wire-form ``RawStructure`` items through the
    in-program neighbor search (ISSUE 11) -> ([n, T] predictions in
    input order, end-to-end structures/sec).

    ``shape_set`` must carry a raw spec; every item must pass
    ``shape_set.admits_raw`` (callers route the rest through the
    featurized path — predict.py does). Packing is near-zero host work
    (slot copies), so there is no pack pipeline here; batches fill the
    largest rung's graph slots greedily and the tail takes the smallest
    fitting rung. In-program cap-overflow flags (a lattice needing more
    images than the rung provides — possible only within the f32/f64
    eps band once ``admits_raw`` passed) are re-served through
    ``raw_fallback`` (RawStructure -> CrystalGraph) when given, else
    raised — NEVER silently answered from a truncated graph.

    ``devices``/``engine`` mirror ``run_fast_inference``: 'mesh' stacks
    batches N-at-a-time under one sharded dispatch; 'threads'
    round-robins per-device replicas; both bit-exact vs single-device.
    """
    from cgnn_tpu.data.rawbatch import RawStructure

    if shape_set is None or shape_set.raw is None:
        raise ValueError("run_raw_inference needs a shape set with a "
                         "raw spec (plan_shape_set(raw=...))")
    if not len(items):
        raise ValueError("no structures to predict")
    for it in items:
        if not isinstance(it, RawStructure):
            raise ValueError("run_raw_inference takes RawStructure items")
        if not shape_set.admits_raw(it):
            raise ValueError(
                f"structure {it.cif_id!r} exceeds the raw rung caps: "
                f"{shape_set.raw.oversize_detail(it)} — route it "
                f"through the featurized path"
            )
    if predict_step is None:
        predict_body = make_predict_step(
            shape_set.expander(), shape_set.raw_expander())
        predict_step = jax.jit(predict_body)
    else:
        predict_body = predict_step
    n = len(items)
    t0 = time.perf_counter()

    big = shape_set.largest

    def plan():
        start = 0
        while start < n:
            end = min(start + big.graph_cap, n)
            count = end - start
            shape = next(s for s in shape_set.shapes
                         if s.graph_cap >= count)
            yield np.arange(start, end), items[start:end], shape
            start = end

    use_mesh = (devices is not None and len(devices) > 1
                and engine in ("auto", "mesh"))
    if use_mesh:
        from cgnn_tpu.parallel.executor import MeshExecutor

        executor = MeshExecutor(devices)
        mesh_predict = executor.shard_predict(predict_body)
        placed = executor.place_params(state)
        states, n_dev = (state,), 1
    elif devices is not None and len(devices) > 1:
        from cgnn_tpu.serve.devices import replicate_state

        states = replicate_state(state, devices)
        n_dev = len(states)
    else:
        states, n_dev = (state,), 1

    preds: np.ndarray | None = None
    overflow_at: list = []  # (global index, item) pairs to re-serve
    outs: list = []  # (spans, shape, out tuple) per dispatch
    recent: list[list] = [[] for _ in range(max(n_dev, 1))]
    di_seq = [0]

    if use_mesh:
        group: list = []
        group_shape = [None]

        def _flush_group():
            if not group:
                return
            batches = [b for _, b in group]
            while len(batches) < len(executor):
                batches.append(batches[-1])
            staged = executor.stage(executor.stack(batches))
            out = mesh_predict(placed, staged)
            outs.append(([s for s, _ in group], group_shape[0], out))
            recent[0].append(out)
            if len(recent[0]) == _WINDOW:
                # fence on the OLDEST in-window result (the _WINDOW
                # discipline): the newer dispatches stay in flight
                float(recent[0][0][0][0, 0, 0])
                del recent[0][:]
            del group[:]

        for span, sub, shape in plan():
            if group_shape[0] is not None and (
                shape != group_shape[0] or len(group) == len(executor)
            ):
                _flush_group()
            group_shape[0] = shape
            group.append((span, shape_set.pack_raw(sub, shape=shape)))
            if len(group) == len(executor):
                _flush_group()
        _flush_group()
        for spans, _shape, out in outs:
            fetched = jax.tree_util.tree_map(
                lambda x: np.array(jax.device_get(x)), out)
            p, ovf = fetched[0], fetched[1]
            if preds is None:
                preds = np.zeros((n, p.shape[-1]), np.float32)
            for i, span in enumerate(spans):
                preds[span] = p[i][: len(span)]
                for k in np.nonzero(ovf[i][: len(span)])[0]:
                    overflow_at.append(int(span[k]))
    else:
        for span, sub, shape in plan():
            batch = shape_set.pack_raw(sub, shape=shape)
            di = di_seq[0] % n_dev
            di_seq[0] += 1
            out = predict_step(states[di], batch)
            outs.append(([span], shape, out))
            recent[di].append(out)
            if len(recent[di]) == _WINDOW:
                # value-fetch fence on the oldest in-window result
                # (train.loop._WINDOW discipline, tuple-aware)
                float(recent[di][0][0][0, 0])
                del recent[di][:]
        for spans, _shape, out in outs:
            p = np.array(jax.device_get(out[0]))
            ovf = np.array(jax.device_get(out[1]))
            span = spans[0]
            if preds is None:
                preds = np.zeros((n, p.shape[-1]), np.float32)
            preds[span] = p[: len(span)]
            for k in np.nonzero(ovf[: len(span)])[0]:
                overflow_at.append(int(span[k]))

    if overflow_at:
        # the in-program flag fired (INVARIANTS.md: never serve a
        # truncated graph): re-serve those rows host-featurized
        if raw_fallback is None:
            bad = [items[i].cif_id or str(i) for i in overflow_at]
            raise RuntimeError(
                f"in-program cap-overflow flag on {bad}; pass "
                f"raw_fallback= to re-serve them host-featurized"
            )
        fgraphs = [raw_fallback(items[i]) for i in overflow_at]
        fpreds, _ = run_fast_inference(
            state, fgraphs, max(1, len(fgraphs)), shape_set=shape_set,
            predict_step=predict_step,
        )
        for row, i in enumerate(overflow_at):
            preds[i] = fpreds[row]
    return preds, n / (time.perf_counter() - t0)


def run_fast_inference(
    state,
    graphs: Sequence,
    batch_size: int,
    *,
    buckets: int = 1,
    dense_m: int | None = None,
    snug: bool = True,
    edge_dtype=np.float32,
    predict_step=None,
    shape_set=None,
    compact=None,
    pack_workers: int = 0,
    devices: Sequence | None = None,
    engine: str = "auto",
    telemetry=None,
) -> tuple[np.ndarray, float]:
    """Predict over ``graphs`` -> ([n, T] predictions in input order,
    end-to-end structures/sec including host packing).

    Without ``shape_set``: buckets are processed one at a time with their
    own snug capacities; within a bucket the original graph order is
    preserved, so the output rows map back to the input by construction.

    With ``shape_set``: batches pack into the fixed rungs (module
    docstring); ``buckets``/``dense_m``/``snug``/``edge_dtype`` are
    ignored — the set carries the layout (including its compact spec).

    ``compact`` (a ``data.compact.CompactSpec``) stages the raw compact
    form; a compact ``shape_set`` implies it. The default
    ``predict_step`` then carries the matching expander — an INJECTED
    step must accept ``CompactBatch`` (``make_predict_step(expander)``).

    ``pack_workers > 0`` packs batches on that many pipeline threads
    (data/pipeline.py) overlapping the dispatch loop; ``0`` packs
    serially on the calling thread (identical outputs, pinned by test).

    ``devices`` (ISSUE 5; e.g. ``serve.devices.resolve_devices('auto')``)
    distributes the dispatch over that many devices; ``None`` keeps the
    single-device loop. ``engine`` picks HOW (ISSUE 10):

    - ``'mesh'`` (the ``'auto'`` default with > 1 device): consecutive
      same-shape batches stack N-at-a-time on a device axis and ONE
      sharded jitted dispatch (Mesh + NamedSharding,
      parallel/executor.py) runs all N — the program count stays at one
      per compiled shape (never programs x N executables), and the
      windowed value-fetch fence bounds in-flight stacks exactly like
      the single-device loop;
    - ``'threads'`` keeps the ISSUE-5 replica path: batch k runs on
      device k % N against that device's committed replica, per-device
      in-flight windows, ONE stacked fetch per (shape, device).

    Both are BIT-identical to the single-device path over identical
    batches (same packing plan, same per-shard program — pinned by
    tests/test_executor.py and test_infer.py).
    """
    if not len(graphs):
        raise ValueError("no graphs to predict")
    if shape_set is not None and shape_set.compact is not None:
        if compact is not None and compact is not shape_set.compact:
            raise ValueError("shape_set already carries a compact spec")
        compact = shape_set.compact
    if engine not in ("auto", "mesh", "threads"):
        raise ValueError(
            f"engine must be 'auto', 'mesh', or 'threads', got {engine!r}"
        )
    predict_body = None
    if predict_step is None:
        expander = None
        if compact is not None:
            from cgnn_tpu.data.compact import make_expander

            expander = make_expander(compact)
        predict_body = make_predict_step(expander)
        predict_step = jax.jit(predict_body)
    n = len(graphs)
    preds: np.ndarray | None = None
    t0 = time.perf_counter()

    # the execution layer over the device set (ISSUE 10): 'mesh' = one
    # sharded dispatch covers N devices (the default); 'threads' = the
    # ISSUE-5 per-device replica round-robin, kept for the A/B
    use_mesh = (devices is not None and len(devices) > 1
                and engine in ("auto", "mesh"))
    executor = mesh_predict = placed_state = None
    if use_mesh:
        from cgnn_tpu.parallel.executor import MeshExecutor

        executor = MeshExecutor(devices)
        # wrap the raw body when we built it; an injected (jitted)
        # predict_step traces through inside the sharded program
        mesh_predict = executor.shard_predict(predict_body or predict_step)
        placed_state = executor.place_params(state)
        states = (state,)
        n_dev = 1  # the per-batch round-robin below is bypassed
    # device replicas (threads engine): batch k dispatches against
    # states[k % n_dev] — the replica is committed to its device, the
    # staged batch is uncommitted host memory, so computation follows
    # the params to the right chip (serve/devices.py)
    elif devices is not None and len(devices):
        from cgnn_tpu.serve.devices import replicate_state

        states = replicate_state(state, devices)
        n_dev = len(states)
    else:
        states = (state,)
        n_dev = 1
    dispatched = [0]

    # ((shape key, device) -> [(span, out)]) so the single stacked fetch
    # groups by compiled shape AND by the device holding the outputs;
    # spans restore input order on the host afterwards
    outs_by_shape: dict = {}
    recent: list[list] = [[] for _ in range(n_dev)]
    # compact staging buffers in per-device dispatch order; an entry is
    # released to the pool once ITS device's window fence proves its
    # dispatch completed (execution is FIFO per device, not across them).
    # The mesh engine packs fresh arrays instead: the group stack copies
    # every staged byte immediately, so a recycle fence buys nothing
    pool = BufferPool() if compact is not None and not use_mesh else None
    pending: list[list] = [[] for _ in range(n_dev)]

    def _release_fenced(di):
        # the fence blocked on the FIRST dispatch of device di's closing
        # window: everything dispatched before it on THAT device
        # completed (FIFO per device), so all but the window's remaining
        # _WINDOW - 1 dispatches are safe
        safe = len(pending[di]) - (_WINDOW - 1)
        if safe > 0:
            for item in pending[di][:safe]:
                if item is not None:
                    pool.release(*item)
            del pending[di][:safe]

    def _dispatch(span, batch, key, buf=None):
        di = dispatched[0] % n_dev  # round-robin across the device set
        dispatched[0] += 1
        out = predict_step(states[di], batch)
        outs_by_shape.setdefault((key, di), []).append((span, out))
        recent[di].append(out)
        if pool is not None:
            pending[di].append(buf)
        if len(recent[di]) == _WINDOW:
            # true fence (block_until_ready returns early on tunneled
            # runtimes) on the OLDEST in-window result: proves everything
            # dispatched before it ON THIS DEVICE finished — bounding
            # staged-batch HBM per chip — while the newer _WINDOW-1
            # dispatches stay in flight
            float(recent[di][0][0, 0])
            del recent[di][:]
            if pool is not None:
                _release_fenced(di)

    if shape_set is not None:
        def pack_job(job):
            span, sub, shape = job
            buf = None
            if pool is not None:
                key = shape_set.buffer_key(shape)
                buf = (key, pool.acquire(key, shape_set.buffer_factory(shape)))
            batch = shape_set.pack(sub, shape=shape,
                                   out=None if buf is None else buf[1])
            return span, invariants.maybe_check(batch, shape_set.dense_m), \
                shape, buf

        jobs = _shape_set_plan(graphs, shape_set)
    else:
        bucket_of = assign_size_buckets(graphs, buckets)
        graph_cap = graph_cap_for(batch_size) if snug else batch_size
        tdim = int(np.atleast_1d(graphs[0].target).shape[0])

        def bucket_jobs():
            for b in range(int(bucket_of.max()) + 1):
                idxs = np.nonzero(bucket_of == b)[0]
                if len(idxs) == 0:
                    continue
                sub = [graphs[int(i)] for i in idxs]
                nc, ec = capacities_for(sub, batch_size, dense_m=dense_m,
                                        snug=snug)
                for s, e in plan_batches(sub, batch_size, nc, ec, snug=snug):
                    yield idxs[s:e], sub[s:e], (b, nc, ec), nc, ec

        def pack_job(job):
            span, sub, key, nc, ec = job
            buf = None
            if compact is not None:
                from cgnn_tpu.data.compact import (
                    alloc_compact_buffers,
                    compact_buffer_key,
                    pack_compact,
                )

                if pool is not None:
                    bkey = compact_buffer_key(nc, dense_m, graph_cap, tdim)
                    buf = (bkey, pool.acquire(
                        bkey,
                        lambda: alloc_compact_buffers(nc, dense_m,
                                                      graph_cap, tdim),
                    ))
                batch = pack_compact(sub, nc, ec, graph_cap, compact,
                                     num_targets=tdim, dense_m=dense_m,
                                     out=None if buf is None else buf[1])
            else:
                batch = pack_graphs(sub, nc, ec, graph_cap, dense_m=dense_m,
                                    edge_dtype=edge_dtype)
            return span, invariants.maybe_check(batch, dense_m), key, buf

        jobs = bucket_jobs()

    if pack_workers > 0:
        packed = parallel_pack(jobs, pack_job, workers=pack_workers,
                               telemetry=telemetry)
    else:
        packed = map(pack_job, jobs)

    if use_mesh:
        # mesh engine: consecutive same-shape batches stack N-at-a-time
        # on the device axis; ONE sharded dispatch runs all N. A group
        # shorter than the mesh (the shape-boundary or dataset tail)
        # pads by repeating its last batch — padded rows are never read.
        group: list = []  # [(span, batch)]
        group_key = [None]
        recent_m: list = []

        def _flush_group():
            if not group:
                return
            batches = [b for _, b in group]
            while len(batches) < len(executor):
                batches.append(batches[-1])
            staged = executor.stage(executor.stack(batches))
            out = mesh_predict(placed_state, staged)
            outs_by_shape.setdefault(group_key[0], []).append(
                ([s for s, _ in group], out))
            recent_m.append(out)
            if len(recent_m) == _WINDOW:
                # the same in-flight bound as the single-device loop,
                # per sharded dispatch: a true value fetch on the
                # oldest in-window result (FIFO dispatch stream)
                float(recent_m[0][0, 0, 0])
                del recent_m[:]
            del group[:]

        for span, batch, key, _buf in packed:
            if group_key[0] is not None and (
                key != group_key[0] or len(group) == len(executor)
            ):
                _flush_group()
            group_key[0] = key
            group.append((span, batch))
            if len(group) == len(executor):
                _flush_group()
        _flush_group()

        for entries in outs_by_shape.values():
            # one stacked fetch per compiled shape: [D, N, G, T]
            fetched = np.array(  # true copy, not an alias (GC-ALIAS)
                jax.device_get(jnp.stack([o for _, o in entries]))
            )
            if preds is None:
                preds = np.zeros((n, fetched.shape[-1]), np.float32)
            for (spans, _), o in zip(entries, fetched):
                for i, span in enumerate(spans):
                    preds[span] = o[i][: len(span)]
        return preds, n / (time.perf_counter() - t0)

    for span, batch, key, buf in packed:
        _dispatch(span, batch, key, buf)

    for group in outs_by_shape.values():
        stacked = np.array(  # true copy, not an aliasing view (GC-ALIAS)
            jax.device_get(jnp.stack([out for _, out in group]))
        )
        if preds is None:
            preds = np.zeros((n, stacked.shape[-1]), np.float32)
        for (span, _), o in zip(group, stacked):
            preds[span] = o[: len(span)]
    return preds, n / (time.perf_counter() - t0)
