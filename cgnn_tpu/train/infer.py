"""Pipelined forward-only inference — predict.py's fast path.

The round-2-era predict loop fetched every batch synchronously; on a
high-latency link every fetch is a full round trip, so inference ran at
r2-era rates while training had moved on (VERDICT r4 weak #5). This path
applies the training loop's lessons to the forward pass:

- snug fill-to-capacity packing + size-class buckets (same policies as
  train.py; >=0.97 padding efficiency at MP scale);
- dispatch pipelining with a windowed value-fetch fence (bounds in-flight
  staged batches without a per-batch round trip);
- ONE stacked device_get per compiled shape instead of one transfer per
  batch (a device-side jnp.stack then a single link transfer).

ISSUE 3 made the compiled shapes injectable: pass ``shape_set`` (a
``serve.shapes.ShapeSet`` — the serving ladder) and batches pack into
those FIXED precompiled rungs instead of deriving fresh per-bucket
capacities — an offline predict job then reuses the online service's
shapes (and, through the persistent XLA cache, its compiled programs),
and the total compile count is pinned at ``len(shape_set)`` regardless
of dataset. ``predict_step`` is likewise injectable, so serve and
predict can share one jitted callable and its jit cache.
"""

from __future__ import annotations

import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cgnn_tpu.data.graph import (
    assign_size_buckets,
    batch_iterator,
    capacities_for,
)
from cgnn_tpu.train.step import make_predict_step

# in-flight dispatch window before a bounding value fetch (same role as
# train.loop._WINDOW; one fence per window, NOT per batch)
_WINDOW = 16


def _shape_set_plan(graphs: Sequence, shape_set):
    """Yield (index span, graph sublist, shape): greedy fill to the
    LARGEST rung in input order; the ragged tail takes the smallest rung
    that fits it. Input order is preserved by construction, so spans are
    contiguous."""
    big = shape_set.largest
    start = 0
    cur: list = []
    n = e = 0
    for i, g in enumerate(graphs):
        if not shape_set.admits(g):
            raise ValueError(
                f"graph {getattr(g, 'cif_id', i)!r} exceeds the shape set: "
                f"{shape_set.oversize_detail(g)}"
            )
        gn, ge = shape_set.graph_counts(g)
        if cur and not big.fits(len(cur) + 1, n + gn, e + ge):
            yield np.arange(start, i), cur, big
            start, cur, n, e = i, [], 0, 0
        cur.append(g)
        n += gn
        e += ge
    if cur:
        yield (np.arange(start, len(graphs)), cur,
               shape_set.shape_for(len(cur), n, e))


def run_fast_inference(
    state,
    graphs: Sequence,
    batch_size: int,
    *,
    buckets: int = 1,
    dense_m: int | None = None,
    snug: bool = True,
    edge_dtype=np.float32,
    predict_step=None,
    shape_set=None,
) -> tuple[np.ndarray, float]:
    """Predict over ``graphs`` -> ([n, T] predictions in input order,
    end-to-end structures/sec including host packing).

    Without ``shape_set``: buckets are processed one at a time with their
    own snug capacities; within a bucket the original graph order is
    preserved, so the output rows map back to the input by construction.

    With ``shape_set``: batches pack into the fixed rungs (module
    docstring); ``buckets``/``dense_m``/``snug``/``edge_dtype`` are
    ignored — the set carries the layout.
    """
    if not len(graphs):
        raise ValueError("no graphs to predict")
    predict_step = predict_step or jax.jit(make_predict_step())
    n = len(graphs)
    preds: np.ndarray | None = None
    t0 = time.perf_counter()

    # (shape key -> [(span, out)]) so the single stacked fetch groups by
    # compiled shape; spans restore input order on the host afterwards
    outs_by_shape: dict = {}
    recent: list = []

    def _dispatch(span, batch, key):
        out = predict_step(state, batch)
        outs_by_shape.setdefault(key, []).append((span, out))
        recent.append(out)
        if len(recent) == _WINDOW:
            # true fence (block_until_ready returns early on tunneled
            # runtimes) on the OLDEST in-window result: proves everything
            # dispatched before it finished — bounding staged-batch HBM —
            # while the newer _WINDOW-1 dispatches stay in flight
            float(recent[0][0, 0])
            del recent[:]

    if shape_set is not None:
        for span, sub, shape in _shape_set_plan(graphs, shape_set):
            _dispatch(span, shape_set.pack(sub, shape=shape), shape)
    else:
        bucket_of = assign_size_buckets(graphs, buckets)
        for b in range(int(bucket_of.max()) + 1):
            idxs = np.nonzero(bucket_of == b)[0]
            if len(idxs) == 0:
                continue
            sub = [graphs[int(i)] for i in idxs]
            nc, ec = capacities_for(sub, batch_size, dense_m=dense_m,
                                    snug=snug)
            ptr = 0
            # in_cap=0: no backward, so no transpose-slot packing
            for batch in batch_iterator(sub, batch_size, nc, ec,
                                        dense_m=dense_m, in_cap=0, snug=snug,
                                        edge_dtype=edge_dtype):
                n_real = int(np.asarray(batch.graph_mask).sum())
                _dispatch(idxs[ptr : ptr + n_real], batch, (b, nc, ec))
                ptr += n_real

    for group in outs_by_shape.values():
        stacked = np.asarray(
            jax.device_get(jnp.stack([out for _, out in group]))
        )
        if preds is None:
            preds = np.zeros((n, stacked.shape[-1]), np.float32)
        for (span, _), o in zip(group, stacked):
            preds[span] = o[: len(span)]
    return preds, n / (time.perf_counter() - t0)
