"""Pipelined forward-only inference — predict.py's fast path.

The round-2-era predict loop fetched every batch synchronously; on a
high-latency link every fetch is a full round trip, so inference ran at
r2-era rates while training had moved on (VERDICT r4 weak #5). This path
applies the training loop's lessons to the forward pass:

- snug fill-to-capacity packing + size-class buckets (same policies as
  train.py; >=0.97 padding efficiency at MP scale);
- dispatch pipelining with a windowed value-fetch fence (bounds in-flight
  staged batches without a per-batch round trip);
- ONE stacked device_get per bucket instead of one transfer per batch
  (a device-side jnp.stack then a single link transfer).
"""

from __future__ import annotations

import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cgnn_tpu.data.graph import (
    assign_size_buckets,
    batch_iterator,
    capacities_for,
)
from cgnn_tpu.train.step import make_predict_step

# in-flight dispatch window before a bounding value fetch (same role as
# train.loop._WINDOW; one fence per window, NOT per batch)
_WINDOW = 16


def run_fast_inference(
    state,
    graphs: Sequence,
    batch_size: int,
    *,
    buckets: int = 1,
    dense_m: int | None = None,
    snug: bool = True,
    edge_dtype=np.float32,
    predict_step=None,
) -> tuple[np.ndarray, float]:
    """Predict over ``graphs`` -> ([n, T] predictions in input order,
    end-to-end structures/sec including host packing).

    Buckets are processed one at a time with their own snug capacities;
    within a bucket the original graph order is preserved, so the output
    rows map back to the input by construction.
    """
    if not len(graphs):
        raise ValueError("no graphs to predict")
    predict_step = predict_step or jax.jit(make_predict_step())
    n = len(graphs)
    preds: np.ndarray | None = None
    t0 = time.perf_counter()
    bucket_of = assign_size_buckets(graphs, buckets)
    for b in range(int(bucket_of.max()) + 1):
        idxs = np.nonzero(bucket_of == b)[0]
        if len(idxs) == 0:
            continue
        sub = [graphs[int(i)] for i in idxs]
        nc, ec = capacities_for(sub, batch_size, dense_m=dense_m, snug=snug)
        outs: list = []
        spans: list = []
        ptr = 0
        # in_cap=0: no backward, so no transpose-slot packing
        for batch in batch_iterator(sub, batch_size, nc, ec, dense_m=dense_m,
                                    in_cap=0, snug=snug,
                                    edge_dtype=edge_dtype):
            n_real = int(np.asarray(batch.graph_mask).sum())
            outs.append(predict_step(state, batch))
            spans.append(idxs[ptr : ptr + n_real])
            ptr += n_real
            if len(outs) % _WINDOW == 0:
                # true fence (block_until_ready returns early on tunneled
                # runtimes): proves the window's steps finished, bounding
                # staged-batch HBM without a per-batch round trip
                float(outs[-_WINDOW][0, 0])
        stacked = np.asarray(jax.device_get(jnp.stack(outs)))
        if preds is None:
            preds = np.zeros((n, stacked.shape[-1]), np.float32)
        for o, span in zip(stacked, spans):
            preds[span] = o[: len(span)]
    return preds, n / (time.perf_counter() - t0)
