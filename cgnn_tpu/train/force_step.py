"""Jitted train/eval steps for the force-field task (BASELINE config #5).

The loss is the standard energy+force composite used for ML force fields:

    L = w_e * MSE(E_norm) + w_f * MSE(F / std)

Energies are normalized with the target Normalizer (mean/std over training
energies); force labels are scaled by 1/std so predicted forces — which are
``-d(E_norm)/dr`` up to the same 1/std factor — live on a matching scale.
Metrics report both MAEs in ORIGINAL units.

The step differentiates twice: an inner ``jax.grad`` over positions produces
forces inside the loss, and the outer ``value_and_grad`` over params
backpropagates through that force computation (second-order mixed
derivatives, handled natively by JAX). The reference lineage cannot express
this — its data path precomputes distances on the host, severing the
autodiff graph at the geometry (SURVEY.md §7 phase 7).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from cgnn_tpu.data.graph import GraphBatch
from cgnn_tpu.train.state import TrainState


def force_loss(
    energies: jax.Array,
    forces: jax.Array,
    batch: GraphBatch,
    normalizer,
    w_energy: float = 1.0,
    w_force: float = 10.0,
):
    """Composite masked loss; metrics as (sum, count) pairs in original units."""
    std = normalizer.std[0]
    e_norm_target = normalizer.norm(batch.targets)[:, 0]
    gw = batch.graph_mask
    n_g = jnp.maximum(gw.sum(), 1.0)
    e_se = (energies - e_norm_target) ** 2 * gw
    e_loss = e_se.sum() / n_g

    f_target_scaled = batch.node_targets / std
    nw = batch.node_mask[:, None]
    f_se = ((forces - f_target_scaled) ** 2) * nw
    n_f = jnp.maximum(nw.sum() * 3.0, 1.0)
    f_loss = f_se.sum() / n_f

    loss = w_energy * e_loss + w_force * f_loss
    e_ae = jnp.abs(normalizer.denorm(energies[:, None])[:, 0] - batch.targets[:, 0]) * gw
    f_ae = jnp.abs(forces * std - batch.node_targets) * nw
    metrics = {
        "loss_sum": loss * n_g,  # so loss averages like the other tasks
        "mae_sum": e_ae.sum(),
        "count": gw.sum(),
        "force_mae_sum": f_ae.sum(),
        "force_mae_count": nw.sum() * 3.0,
    }
    return loss, metrics


def _energy_and_grad_pos(apply_fn, variables, batch, train: bool):
    """(energies [G], dE/dpos [N,3], new_batch_stats) — differentiable in params."""

    def total_energy(pos):
        if train:
            e, mutated = apply_fn(
                variables, batch, pos, train=True, mutable=["batch_stats"]
            )
            return jnp.sum(e), (e, mutated.get("batch_stats", {}))
        e = apply_fn(variables, batch, pos, train=False)
        return jnp.sum(e), (e, None)

    (_, (energies, new_stats)), grad_pos = jax.value_and_grad(
        total_energy, has_aux=True
    )(batch.positions)
    return energies, grad_pos, new_stats


def make_force_train_step(
    w_energy: float = 1.0,
    w_force: float = 10.0,
    axis_name: str | None = None,
    grad_health: bool = False,
) -> Callable:
    """(state, batch) -> (state, metrics); energy+force composite objective.

    ``grad_health`` adds in-graph grad/update-norm and NaN/Inf-count
    metrics (observe.health) — extra outputs only; the update and hence
    the trajectory are identical with it on or off. Especially relevant
    here: the force task's second-order differentiation is the likeliest
    NaN source in the codebase, and under the epoch scan its onset used
    to be invisible until the epoch aggregate came back.
    """

    def train_step(state: TrainState, batch: GraphBatch):
        def loss_with_aux(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            energies, grad_pos, new_stats = _energy_and_grad_pos(
                state.apply_fn, variables, batch, train=True
            )
            forces = -grad_pos * batch.node_mask[:, None]
            loss, metrics = force_loss(
                energies, forces, batch, state.normalizer, w_energy, w_force
            )
            return loss, (metrics, new_stats)

        (loss, (metrics, new_stats)), grads = jax.value_and_grad(
            loss_with_aux, has_aux=True
        )(state.params)
        if axis_name is not None:
            grads = lax.pmean(grads, axis_name)
            new_stats = lax.pmean(new_stats, axis_name)
            metrics = lax.psum(metrics, axis_name)
        new_state = state.apply_gradients(grads, new_stats)
        if grad_health:
            from cgnn_tpu.observe.health import grad_health_metrics

            # per-shard loss under axis_name: reduce before the NaN
            # check (see train.step.make_train_step) — a NaN on any
            # shard must be visible, not just shard 0's value
            health_loss = (
                loss if axis_name is None else lax.pmean(loss, axis_name)
            )
            metrics = metrics | grad_health_metrics(
                grads, state.params, new_state.params, loss=health_loss
            )
        return new_state, metrics

    return train_step


def make_force_eval_step(
    w_energy: float = 1.0,
    w_force: float = 10.0,
    axis_name: str | None = None,
) -> Callable:
    """(state, batch) -> metrics using running BatchNorm statistics."""

    def eval_step(state: TrainState, batch: GraphBatch):
        energies, grad_pos, _ = _energy_and_grad_pos(
            state.apply_fn, state.variables(), batch, train=False
        )
        forces = -grad_pos * batch.node_mask[:, None]
        _, metrics = force_loss(
            energies, forces, batch, state.normalizer, w_energy, w_force
        )
        if axis_name is not None:
            metrics = lax.psum(metrics, axis_name)
        return metrics

    return eval_step


def make_force_predict_step() -> Callable:
    """(state, batch) -> (energies [G] denormalized, forces [N,3] orig units)."""

    def predict_step(state: TrainState, batch: GraphBatch):
        energies, grad_pos, _ = _energy_and_grad_pos(
            state.apply_fn, state.variables(), batch, train=False
        )
        std = state.normalizer.std[0]
        forces = -grad_pos * batch.node_mask[:, None] * std
        e = state.normalizer.denorm(energies[:, None])[:, 0] * batch.graph_mask
        return e, forces

    return predict_step
