"""Jitted train/eval steps (SURVEY.md §3.1 hot loop, rebuilt for XLA).

One traced function per (model, task): forward, masked loss, grads, optimizer
update, BatchNorm stat update — all fused by XLA into a single device
program. The same step body runs single-device (plain ``jit``) or
data-parallel (inside ``shard_map`` with ``axis_name='data'`` — grads and
stats are ``pmean``-ed over ICI, metrics ``psum``-ed; cgnn_tpu.parallel).

Metrics are returned as (sum, count) pairs, never means, so cross-device and
cross-batch accumulation is exact.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from cgnn_tpu.data.graph import GraphBatch
from cgnn_tpu.data.rawbatch import RawBatch
from cgnn_tpu.train.state import TrainState


def regression_loss(out, batch: GraphBatch, normalizer):
    """Masked MSE on normalized targets; metrics in original units.

    Multi-task outputs (T > 1, BASELINE config #3) additionally report one
    MAE per task column, each averaged over its own label count (labels can
    be missing per task via target_mask).
    """
    t_norm = normalizer.norm(batch.targets)
    w = batch.target_mask * batch.graph_mask[:, None]
    se = (out - t_norm) ** 2 * w
    n = jnp.maximum(w.sum(), 1.0)
    loss = se.sum() / n
    ae = jnp.abs(normalizer.denorm(out) - batch.targets) * w
    metrics = {"loss_sum": se.sum(), "mae_sum": ae.sum(), "count": w.sum()}
    if out.shape[-1] > 1:
        for t in range(out.shape[-1]):
            metrics[f"mae_task{t}_sum"] = ae[:, t].sum()
            metrics[f"mae_task{t}_count"] = w[:, t].sum()
    return loss, metrics


def classification_loss(out, batch: GraphBatch, normalizer):
    """NLL over log-probs (reference: NLLLoss after LogSoftmax) + accuracy."""
    labels = batch.targets[:, 0].astype(jnp.int32)
    w = batch.graph_mask
    nll = -jnp.take_along_axis(out, labels[:, None], axis=1)[:, 0] * w
    n = jnp.maximum(w.sum(), 1.0)
    loss = nll.sum() / n
    correct = (jnp.argmax(out, axis=-1) == labels).astype(jnp.float32) * w
    metrics = {"loss_sum": nll.sum(), "correct_sum": correct.sum(), "count": w.sum()}
    return loss, metrics


def make_train_step(
    classification: bool = False,
    axis_name: str | tuple[str, ...] | None = None,
    loss_fn: Callable | None = None,
    loss_scale: float = 1.0,
    pmean_grads: bool = True,
    grad_health: bool = False,
) -> Callable:
    """Build the (state, batch) -> (state, metrics) step body.

    ``axis_name`` activates cross-device reductions (a tuple reduces over
    several mesh axes at once — hierarchical multi-host DP over
    ('dcn', 'data')); only set it when the step runs inside shard_map/vmap
    with those axes bound.

    ``loss_scale`` multiplies the loss before differentiation (metrics are
    unscaled) and ``pmean_grads=False`` skips the explicit grad allreduce —
    both exist for steps running under shard_map with replication checking
    ON, where the transpose already psums parameter cotangents over every
    mesh axis: scaling by 1/axis_size turns that sum into the DDP mean
    (cgnn_tpu.parallel.edge_parallel 2-D mesh step).

    ``grad_health`` adds in-graph grad-norm / update-norm / NaN-Inf-count
    metrics (observe.health) — extra metric OUTPUTS only, computed from
    the applied (post-``pmean``) grads; the update itself is untouched,
    so the training trajectory is identical with it on or off. Not psum-ed
    under ``axis_name``: post-pmean grads are replicated, so the values
    (and their per-step counts of 1) are already consistent across shards.
    """
    compute_loss = loss_fn or (classification_loss if classification else regression_loss)

    def train_step(state: TrainState, batch: GraphBatch):
        rngs = {"dropout": jax.random.fold_in(state.rng, state.step)}

        def loss_with_aux(params):
            out, mutated = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                batch,
                train=True,
                mutable=["batch_stats"],
                rngs=rngs,
            )
            loss, metrics = compute_loss(out, batch, state.normalizer)
            return loss * loss_scale, (metrics, mutated["batch_stats"])

        (loss, (metrics, new_stats)), grads = jax.value_and_grad(
            loss_with_aux, has_aux=True
        )(state.params)
        if axis_name is not None:
            # DDP-equivalent: average grads across replicas; running stats are
            # also averaged (stronger than torch DDP, which keeps rank-0's);
            # metric sums add up exactly.
            if pmean_grads:
                grads = lax.pmean(grads, axis_name)
            new_stats = lax.pmean(new_stats, axis_name)
            metrics = lax.psum(metrics, axis_name)
        new_state = state.apply_gradients(grads, new_stats)
        if grad_health:
            from cgnn_tpu.observe.health import grad_health_metrics

            # the raw loss is per-shard under axis_name (unlike the
            # post-pmean grads): reduce it first so a NaN on ANY shard is
            # visible everywhere instead of shard 0's value escaping the
            # shard_map as the replicated output
            health_loss = (
                loss if axis_name is None else lax.pmean(loss, axis_name)
            )
            metrics = metrics | grad_health_metrics(
                grads, state.params, new_state.params, loss=health_loss
            )
        return new_state, metrics

    return train_step


def make_eval_step(
    classification: bool = False,
    axis_name: str | tuple[str, ...] | None = None,
    loss_fn: Callable | None = None,
) -> Callable:
    """(state, batch) -> metrics, using running BatchNorm statistics."""
    compute_loss = loss_fn or (classification_loss if classification else regression_loss)

    def eval_step(state: TrainState, batch: GraphBatch):
        out = state.apply_fn(state.variables(), batch, train=False)
        _, metrics = compute_loss(out, batch, state.normalizer)
        if axis_name is not None:
            metrics = lax.psum(metrics, axis_name)
        return metrics

    return eval_step


# the train step donates its state carry (argument 0): XLA reuses the
# parameter/optimizer buffers in place instead of allocating a second
# copy per step. TRAIN_STEP_DONATE is the ONE declaration of WHICH
# argument is donated — consumed by jit_train_step (single-device
# bodies), the scan driver, and the DP/edge-sharded wrappers in
# parallel/ — and the graftaudit GA-DONATION check verifies XLA
# actually applied the aliasing (analysis/program_audit).
TRAIN_STEP_DONATE = (0,)


def jit_train_step(body: Callable):
    """The canonical jit wrapper for single-device (state, batch) ->
    (state, metrics) train-step bodies.

    ``body`` may be the raw step, guard-wrapped (resilience.guard), or
    telemetry-wrapped (observe) — anything with the train-step carry
    signature. Used by train/loop.py, scripts/hlo_dump.py, and the
    program auditor, so a single-device train step reaches XLA exactly
    one way; the shard_map wrappers in parallel/ jit themselves but
    share the TRAIN_STEP_DONATE contract."""
    return jax.jit(body, donate_argnums=TRAIN_STEP_DONATE)


def make_predict_step(expander: Callable | None = None,
                      raw_expander: Callable | None = None) -> Callable:
    """(state, batch) -> denormalized predictions [G, T].

    ``expander`` (``data.compact.make_expander``) lets the step accept
    compact-staged batches: a ``CompactBatch`` argument is rebuilt into
    the exact ``GraphBatch`` INSIDE the compiled program (table gather +
    ``exp`` fuse into the forward pass), so only the ~12x smaller raw
    form crosses the host->device link. The type dispatch happens at
    trace time, so ONE jitted callable serves both staging modes — a
    full-fidelity ``GraphBatch`` traces its own cache entry and runs
    unchanged (the serving fallback for non-compactable requests).

    ``raw_expander`` (``ops.neighbor_search.make_raw_expander``) adds
    the third staging form (ISSUE 11): a ``RawBatch`` of wire-form
    structures is turned into a GraphBatch by the IN-PROGRAM periodic
    neighbor search + featurization, and the step returns the tuple
    ``(predictions [G, T], cap_overflow [G] bool, n_edges [G] i32)`` —
    the overflow flag is part of the program's contract (a flagged
    structure's row must never be served; INVARIANTS.md), and the edge
    counts feed the per-rung edge-occupancy gauges.
    """

    def predict_step(state: TrainState, batch):
        if raw_expander is not None and isinstance(batch, RawBatch):
            gb, overflow, n_edges = raw_expander(batch)
            out = state.apply_fn(state.variables(), gb, train=False)
            preds = state.normalizer.denorm(out) * gb.graph_mask[:, None]
            return preds, overflow, n_edges
        if expander is not None and not isinstance(batch, GraphBatch):
            batch = expander(batch)
        out = state.apply_fn(state.variables(), batch, train=False)
        return state.normalizer.denorm(out) * batch.graph_mask[:, None]

    return predict_step
