"""Meters and eval metrics (SURVEY.md §2 component 9).

Console-visible quantities match the reference's operator experience: loss,
MAE (regression) or accuracy/AUC/F1 (classification), batch/data timing.
sklearn is not installed; AUC/F1 are implemented in-tree on numpy.
"""

from __future__ import annotations

import numpy as np


# jitted whole-dict add, cached per key-structure: K per-key `a + b`
# dispatches per chunk become ONE fused dispatch (the scan driver's
# per-chunk host fixed cost — PERF.md §6c; on a tunneled link every
# dispatch is host work on the critical path)
_ACCUM_FNS: dict = {}


def _accum_fn(keys: tuple):
    fn = _ACCUM_FNS.get(keys)
    if fn is None:
        import jax

        fn = _ACCUM_FNS[keys] = jax.jit(
            lambda a, b: {k: a[k] + b[k] for k in keys}
        )
    return fn


def accumulate_on_device(dev_sums: dict | None, metrics: dict) -> dict:
    """Add a step's metric dict into device-side running sums.

    The adds are dispatched asynchronously — no host<->device round trip
    per step (which would dominate epoch time on remote/tunneled
    accelerators and throttle dispatch pipelining everywhere). The
    steady-state case (same key set chunk after chunk) goes through one
    jitted dict-add — one dispatch instead of one per key. Tolerates
    keys appearing mid-epoch (mixed step bodies) via the per-key
    fallback."""
    if dev_sums is None:
        return dict(metrics)
    if dev_sums.keys() == metrics.keys():
        try:
            return _accum_fn(tuple(sorted(metrics)))(dev_sums, metrics)
        except TypeError:
            pass  # non-jittable values (python floats mid-migration)
    for k, v in metrics.items():
        dev_sums[k] = dev_sums[k] + v if k in dev_sums else v
    return dev_sums


def fetch_device_sums(dev_sums: dict | None) -> dict:
    """One blocking fetch of the accumulated sums -> python floats.

    The scalars are PACKED into a single device array first (one stack
    dispatch) so the fetch is ONE transfer: a dict device_get moves each
    scalar separately, and on a remote/tunneled runtime every scalar is a
    full link round trip — measured ~250 ms/epoch in the scan driver
    (~17 chunk dicts x 4 keys) before packing, i.e. the entire
    driver-vs-steady-step gap at bench scale (SCAN_COST.json r4).
    """
    import jax
    import jax.numpy as jnp

    if dev_sums is None:
        return {}
    keys = sorted(dev_sums)
    packed = jnp.stack(
        [jnp.asarray(dev_sums[k], jnp.float32) for k in keys]
    )
    # np.array, not asarray: device_get ALIASES device buffers on CPU
    # (graftcheck GC-ALIAS) and these sums outlive the next dispatch
    vals = np.array(jax.device_get(packed))
    return dict(zip(keys, (float(v) for v in vals)))


def means_from_sums(sums: dict, steps: int) -> dict:
    """Epoch metric means from '<name>_sum' totals: each sum averages by
    its matching '<name>_count' when present (e.g. force MAE counts atom
    components, not graphs), else by the global 'count'."""
    count = max(sums.get("count", 1.0), 1.0)
    out = {
        k[: -len("_sum")]: v
        / max(sums.get(k[: -len("_sum")] + "_count", count), 1.0)
        for k, v in sums.items()
        if k.endswith("_sum")
    }
    out["count"] = sums.get("count", 0.0)
    out["steps"] = steps
    return out


class AverageMeter:
    """Running (value, average) meter — the reference's training display."""

    def __init__(self, name: str = ""):
        self.name = name
        self.reset()

    def reset(self):
        self.val = 0.0
        self.sum = 0.0
        self.count = 0.0
        self.avg = 0.0

    def update(self, val: float, n: float = 1):
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(self.count, 1e-12)


def mae(pred: np.ndarray, target: np.ndarray) -> float:
    return float(np.mean(np.abs(np.asarray(pred) - np.asarray(target))))


def _binary_auc(scores: np.ndarray, labels: np.ndarray) -> float:
    """Rank-based AUC (Mann-Whitney), ties handled by midranks."""
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    sorted_scores = scores[order]
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        ranks[order[i : j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    pos = labels == 1
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def class_eval(log_probs: np.ndarray, labels: np.ndarray) -> dict:
    """accuracy / precision / recall / F1 / AUC for binary classification.

    Mirrors the reference's ``class_eval`` metric set (computed there with
    sklearn, which is unavailable in this image).
    """
    log_probs = np.asarray(log_probs)
    labels = np.asarray(labels).astype(int)
    pred = log_probs.argmax(axis=-1)
    acc = float((pred == labels).mean()) if len(labels) else float("nan")
    out = {"accuracy": acc}
    if log_probs.shape[-1] == 2:
        tp = float(((pred == 1) & (labels == 1)).sum())
        fp = float(((pred == 1) & (labels == 0)).sum())
        fn = float(((pred == 0) & (labels == 1)).sum())
        precision = tp / (tp + fp) if tp + fp else float("nan")
        recall = tp / (tp + fn) if tp + fn else float("nan")
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision == precision and recall == recall and precision + recall
            else float("nan")
        )
        out.update(
            precision=precision,
            recall=recall,
            f1=f1,
            auc=_binary_auc(np.exp(log_probs[:, 1]), labels),
        )
    return out
