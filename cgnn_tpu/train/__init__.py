"""Training runtime (SURVEY.md §1 "Training runtime", §2 components 1, 8-12).

The reference's ``main.py`` epoch loop, Normalizer, checkpointing, LR
schedule, and meters — rebuilt around a single jitted, state-donating train
step that works unchanged on CPU, one TPU chip, or a data-parallel mesh
(cgnn_tpu.parallel).
"""

from cgnn_tpu.train.normalizer import Normalizer
from cgnn_tpu.train.state import TrainState, create_train_state, make_optimizer
from cgnn_tpu.train.step import make_train_step, make_eval_step
from cgnn_tpu.train.metrics import AverageMeter, mae, class_eval
from cgnn_tpu.train.checkpoint import CheckpointManager, CheckpointRestoreError
from cgnn_tpu.train.loop import fit, evaluate

__all__ = [
    "CheckpointRestoreError",
    "Normalizer",
    "TrainState",
    "create_train_state",
    "make_optimizer",
    "make_train_step",
    "make_eval_step",
    "AverageMeter",
    "mae",
    "class_eval",
    "CheckpointManager",
    "fit",
    "evaluate",
]
