"""Crash-safe checkpoint/resume on orbax (SURVEY.md §2 component 10, §5).

Semantics follow the reference's ``save_checkpoint``/``--resume`` —
every epoch saves the full training state (params, BatchNorm stats,
optimizer state, step, Normalizer, RNG) plus metadata (config dict,
epoch, best metric) — but the on-disk protocol is built for processes
that die mid-save (ISSUE 2):

- every save goes to a FRESH versioned directory (``ckpt-00000012``),
  written under a dot-temp name and atomically ``os.replace``-d into
  place. Nothing is ever overwritten, so a kill -9 at any instant
  leaves every previously committed checkpoint intact (the old
  ``force=True`` overwrite of ``latest/`` corrupted the only resume
  point);
- a sidecar integrity manifest (per-leaf shape/dtype/crc32;
  ``resilience.integrity``) is written LAST inside the temp directory —
  it doubles as the commit marker: a directory without one is an
  uncommitted save and is never offered for restore;
- restore walks a fallback chain (newest committed -> older -> best)
  verifying each candidate against its manifest, and reports every
  candidate it skipped and why (``last_restore_report``);
- retention keeps the newest ``keep`` saves plus the best-pointer
  target; ``best`` is an atomically updated pointer file
  (``best.json``), not a second copy of the tree.

Saves stay async: the caller's thread only pays for the device fetch;
an ordered background finalizer does the orbax write, manifest, commit
rename, and retention. Trees are host-localized (numpy) before saving
so checkpoints carry no device-mesh shardings — a state saved from an
8-device run restores in a single-chip predict/resume process.

The pre-ISSUE-2 tag layout (``latest/``/``best/`` dirs +
``meta-<tag>.json``) is still readable, as a last-resort link in the
fallback chain (no manifest, so no verification).
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import re
import shutil
import sys
import threading
from typing import Callable

import jax
import numpy as np
import orbax.checkpoint as ocp

from cgnn_tpu.observe.metrics_io import jsonfinite
from cgnn_tpu.resilience import faultinject
from cgnn_tpu.resilience.integrity import (
    read_manifest,
    tree_manifest,
    verify_tree,
    write_manifest,
)
from cgnn_tpu.train.state import TrainState

_LATEST = "latest"
_BEST = "best"
_PREVIOUS = "previous"
_SAVE_RE = re.compile(r"^ckpt-(\d{8})$")
_TMP_PREFIX = ".tmp-"
_BEST_POINTER = "best.json"

# orbax-barrier scoping for multi-host runs (ISSUE 10): this manager
# implements its OWN atomicity (tmp dir + manifest commit marker +
# os.replace) and its multi-host protocol is process-0-only commits
# coordinated by parallel/dist.py — but a default orbax Checkpointer
# sees jax.process_count() > 1 and inserts ITS OWN cross-process
# barriers around every save/restore. An asymmetric save (only process
# 0 commits) then posts a collective nobody else joins, which lands in
# whatever collective the other hosts issued next — measured in the
# 2-process CPU dryrun as a fatal gloo size-mismatch abort mid-epoch.
# Scoping every barrier to a singleton {this process} keeps orbax a
# local serializer; the counter keeps barrier keys unique across the
# repeated restores a watcher performs.
import itertools

_LOCAL_SCOPE_SEQ = itertools.count()


def _local_mp_options():
    """Singleton-process MultiprocessingOptions (None single-process)."""
    if jax.process_count() <= 1:
        return None
    from orbax.checkpoint import options as ocp_options

    return ocp_options.MultiprocessingOptions(
        primary_host=None,
        active_processes={jax.process_index()},
        barrier_sync_key_prefix=(
            f"cgnn-local-p{jax.process_index()}-{next(_LOCAL_SCOPE_SEQ)}"
        ),
    )


def _standard_checkpointer():
    mp = _local_mp_options()
    if mp is None:
        return ocp.StandardCheckpointer()
    return ocp.StandardCheckpointer(multiprocessing_options=mp)


def _pytree_checkpointer():
    mp = _local_mp_options()
    if mp is None:
        return ocp.PyTreeCheckpointer()
    # PyTreeCheckpointer's own ctor only exposes primary_host; build the
    # equivalent Checkpointer with the fully scoped options (same
    # ocdbt-on handler defaults, so it reads StandardCheckpointer saves)
    return ocp.Checkpointer(
        ocp.PyTreeCheckpointHandler(use_ocdbt=True),
        multiprocessing_options=mp,
    )


class CheckpointRestoreError(RuntimeError):
    """No candidate in the restore fallback chain was usable."""

    def __init__(self, tag: str, attempts: list[str]):
        self.attempts = attempts
        detail = "; ".join(attempts) if attempts else "no checkpoints found"
        super().__init__(
            f"no restorable {tag!r} checkpoint: {detail}"
        )


def _state_pytree(state: TrainState) -> dict:
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "normalizer": {"mean": state.normalizer.mean, "std": state.normalizer.std},
        "rng": jax.random.key_data(state.rng),
    }


@dataclasses.dataclass(frozen=True)
class _Candidate:
    """One restorable location: a committed versioned save or a legacy
    tag directory (``manifest_dir`` None = legacy, unverifiable)."""

    name: str
    state_path: str
    meta_path: str
    manifest_dir: str | None


class CheckpointManager:
    """Versioned atomic saves + fallback-chain restores (module docstring).

    ``telemetry`` (an ``observe.Telemetry``) wraps the host-side part of
    saves/restores in spans — the save span covers the device fetch +
    finalizer dispatch, which is exactly the part that stalls training.
    ``keep`` bounds retention (newest ``keep`` saves + the best target;
    ``keep=0`` retains everything).
    """

    def __init__(self, directory: str, telemetry=None, keep: int = 3,
                 log_fn: Callable | None = None):
        from cgnn_tpu.observe import Telemetry

        # default log sink is stderr: restore-fallback reports are
        # operator-facing diagnostics, not program output
        self._log = log_fn or (
            lambda msg: print(msg, file=sys.stderr)
        )
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep
        self._ckptr = _standard_checkpointer()
        # Telemetry.span is already a nullcontext at level 'off'
        self._telemetry = telemetry or Telemetry.disabled()
        self._lock = threading.Lock()
        self._jobs: queue.Queue = queue.Queue()
        self._worker: threading.Thread | None = None
        self._errors: list[BaseException] = []
        self.last_restore_report: list[str] = []
        # name of the candidate the most recent restore actually loaded
        # (None until a restore succeeds) — the chain can FALL BACK past
        # the newest save, so consumers labeling what they serve/resume
        # (serve.load_server's param_version) must read this rather than
        # assume newest_committed() is what restored
        self.last_restored: str | None = None
        self._swept_tmp = False
        self._next_seq = 1 + max(
            (int(m.group(1)) for m in map(_SAVE_RE.match,
                                          os.listdir(self.directory)) if m),
            default=-1,
        )

    # ---- directory inventory ----

    def _committed_saves(self) -> list[str]:
        """Committed (manifest-bearing) save names, newest first."""
        names = [
            n for n in os.listdir(self.directory)
            if _SAVE_RE.match(n)
            and read_manifest(os.path.join(self.directory, n)) is not None
        ]
        return sorted(names, reverse=True)

    def newest_committed(self) -> str | None:
        """Name of the newest committed versioned save (None when the
        directory has none) — the polling primitive behind the serving
        hot-reload watcher (serve/reload.py). Read-only: safe to call
        from a process that never saves."""
        saves = self._committed_saves()
        return saves[0] if saves else None

    def is_committed(self, name: str) -> bool:
        """True iff ``name`` is a committed (manifest-bearing) versioned
        save in this directory — the cross-host reload coordinator's
        commit-marker visibility probe (parallel/dist.py): a non-zero
        host polls this until its filesystem view catches up with the
        save process 0 announced. Read-only."""
        return bool(_SAVE_RE.match(name)) and read_manifest(
            os.path.join(self.directory, name)) is not None

    def _best_target(self) -> str | None:
        try:
            with open(os.path.join(self.directory, _BEST_POINTER)) as f:
                name = json.load(f).get("save")
        except (OSError, ValueError):
            return None
        if name and _SAVE_RE.match(name) and os.path.isdir(
            os.path.join(self.directory, name)
        ):
            return name
        return None

    def _save_candidate(self, name: str) -> _Candidate:
        d = os.path.join(self.directory, name)
        return _Candidate(
            name=name,
            state_path=os.path.join(d, "state"),
            meta_path=os.path.join(d, "meta.json"),
            manifest_dir=d,
        )

    def _legacy_candidate(self, tag: str) -> _Candidate | None:
        d = os.path.join(self.directory, tag)
        if not os.path.isdir(d):
            return None
        return _Candidate(
            name=f"legacy:{tag}",
            state_path=d,
            meta_path=os.path.join(self.directory, f"meta-{tag}.json"),
            manifest_dir=None,
        )

    def _candidates(self, tag: str) -> list[_Candidate]:
        """The restore fallback chain for ``tag``, best-first."""
        saves = self._committed_saves()
        best = self._best_target()
        if tag == _BEST:
            ordered = [best] if best else []
        elif tag == _PREVIOUS:
            ordered = saves[1:]
        elif tag == _LATEST:  # newest -> older -> best
            ordered = list(saves)
            if best and best not in ordered:
                ordered.append(best)
        elif _SAVE_RE.match(tag):
            # explicit versioned save name (hot-reload restores a SPECIFIC
            # newly committed save, never "whatever is newest by now"):
            # exactly that candidate, no fallback — the caller decides what
            # a verification failure means (the watcher skips and reports)
            return [self._save_candidate(tag)] if tag in saves else []
        else:
            # arbitrary tag: only ever existed as a legacy tag directory
            # (the old layout saved to <dir>/<tag>); no versioned chain
            ordered = []
        chain = [self._save_candidate(n) for n in ordered]
        # legacy tag dirs only back up their own tag (and 'best' backs up
        # 'latest' as the chain's last resort); 'previous' has no legacy
        # equivalent — the old layout kept a single overwritten 'latest'
        legacy_tags = {
            _LATEST: (_LATEST, _BEST), _BEST: (_BEST,), _PREVIOUS: (),
        }.get(tag, (tag,))
        for t in legacy_tags:
            cand = self._legacy_candidate(t)
            if cand is not None:
                chain.append(cand)
        return chain

    # ---- metadata ----

    def read_meta(self, tag: str = _LATEST) -> dict:
        for cand in self._candidates(tag):
            try:
                with open(cand.meta_path) as f:
                    return json.load(f)
            except (OSError, ValueError):
                continue
        return {}

    def exists(self, tag: str = _LATEST) -> bool:
        return bool(self._candidates(tag))

    # ---- save path ----

    def save(self, state: TrainState, meta: dict, is_best: bool = False):
        """Atomically commit a new versioned save (async finalizer).

        The calling thread pays only for the device fetch; the write,
        manifest, commit rename, best-pointer update, and retention run
        on the ordered background finalizer. Failures surface at the
        next ``wait()``/``restore()``/``close()``.
        """
        with self._telemetry.span("checkpoint_save", is_best=is_best):
            # graftcheck: disable=GC-ALIAS -- audited: the CPU branch
            # below is the np.array snapshot (THE incident site this
            # rule encodes); real accelerators materialize fresh host
            # memory on device_get, so copying there would double the
            # blocking save cost for nothing
            tree = jax.device_get(_state_pytree(state))
            if jax.default_backend() == "cpu":
                # CPU device_get is NOT a snapshot: it returns numpy
                # views ALIASING the device buffers, which the donated
                # train steps then mutate while the finalizer is still
                # serializing — silent checkpoint corruption (caught by
                # the integrity manifest: the crc of the written bytes
                # diverged from the re-read ones under load). Real
                # accelerators already materialize fresh host memory;
                # copying there would double the blocking save cost.
                tree = jax.tree_util.tree_map(lambda x: np.array(x), tree)
            with self._lock:
                seq = self._next_seq
                self._next_seq += 1
            self._sweep_stale_tmp()
            self._ensure_worker()
            self._jobs.put((seq, tree, dict(meta), is_best))

    def _sweep_stale_tmp(self):
        """Remove uncommitted temp dirs a crashed predecessor left —
        garbage by construction (never offered for restore). Called from
        the first SAVE only: a manager that merely reads (predict.py, a
        resume probe) must not delete a concurrently-running trainer's
        in-progress save out from under its finalizer."""
        if self._swept_tmp:
            return
        self._swept_tmp = True
        for entry in os.listdir(self.directory):
            if entry.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.directory, entry),
                              ignore_errors=True)

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._drain_jobs, daemon=True, name="ckpt-finalizer"
            )
            self._worker.start()

    def _drain_jobs(self):
        while True:
            job = self._jobs.get()
            try:
                if job is None:
                    return
                self._finalize(*job)
            except BaseException as e:  # noqa: BLE001 — surfaced at wait()
                self._errors.append(e)
                print(f"checkpoint save failed: {e!r}", file=sys.stderr)
            finally:
                self._jobs.task_done()

    def _finalize(self, seq: int, tree: dict, meta: dict, is_best: bool):
        name = f"ckpt-{seq:08d}"
        final = os.path.join(self.directory, name)
        tmp = os.path.join(self.directory, f"{_TMP_PREFIX}{name}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        # a failure anywhere before the os.replace leaves the temp dir
        # behind, exactly as a crash would — it is invisible to restore
        # either way, and the next manager on this directory sweeps it
        self._ckptr.save(os.path.join(tmp, "state"), tree)
        self._ckptr.wait_until_finished()
        faultinject.crash_point("after_write")
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            # meta carries losses — NaN-able on a diverging run, and
            # the save must stay restorable (graftcheck GC-JSONFINITE)
            json.dump(jsonfinite(meta), f, indent=1)
        # manifest LAST: it is the commit marker (see integrity)
        write_manifest(tmp, tree_manifest(tree))
        faultinject.crash_point("before_commit")
        os.replace(tmp, final)
        faultinject.crash_point("after_commit")
        if is_best:
            self._point_best(name, meta)
        self._apply_retention()

    def _point_best(self, name: str, meta: dict):
        pointer = os.path.join(self.directory, _BEST_POINTER)
        tmp = pointer + ".tmp"
        with open(tmp, "w") as f:
            json.dump(jsonfinite({"save": name, "meta": meta}), f,
                      indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, pointer)

    def _apply_retention(self):
        if self.keep <= 0:
            return
        saves = self._committed_saves()
        protected = set(saves[: self.keep])
        best = self._best_target()
        if best:
            protected.add(best)
        for name in saves[self.keep:]:
            if name not in protected:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def wait(self):
        """Block until every dispatched save committed; raise the first
        finalizer failure (the rest are dropped — they are almost always
        the same root cause repeating)."""
        # queue.join() implies the worker finished its per-save orbax
        # wait_until_finished too — no cross-thread orbax call needed here
        self._jobs.join()
        if self._errors:
            err = self._errors[0]
            self._errors.clear()
            raise err

    # ---- restore path ----

    def _verified_restore(self, cand: _Candidate, restore_fn: Callable):
        """restore_fn(state_path) -> tree, manifest-verified, with meta."""
        tree = restore_fn(cand.state_path)
        if cand.manifest_dir is not None:
            manifest = read_manifest(cand.manifest_dir)
            if manifest is None:
                raise RuntimeError(
                    "integrity manifest missing (uncommitted save?)"
                )
            # graftcheck: disable=GC-ALIAS -- audited: read-only crc
            # verification consumed synchronously, before control
            # returns to anything that could dispatch a donated step
            verify_tree(jax.device_get(tree), manifest)
        try:
            with open(cand.meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            raise RuntimeError(
                f"checkpoint meta unreadable ({cand.meta_path}): {e} — "
                f"refusing to resume blind (a silent epoch-0 restart "
                f"would retrain over the checkpoint)"
            ) from None
        if not isinstance(meta, dict) or not meta:
            raise RuntimeError(
                f"checkpoint meta empty ({cand.meta_path}) — refusing to "
                f"resume blind"
            )
        return tree, meta

    def _restore_chain(self, tag: str, restore_fn: Callable):
        """Walk the fallback chain; -> (candidate, tree, meta)."""
        self.wait()
        self.last_restore_report = []
        chain = self._candidates(tag)
        for i, cand in enumerate(chain):
            try:
                tree, meta = self._verified_restore(cand, restore_fn)
            except Exception as e:  # noqa: BLE001 — chain to next candidate
                msg = f"{cand.name}: {type(e).__name__}: {e}"
                self.last_restore_report.append(msg)
                self._log(f"checkpoint restore: skipping {msg}")
                continue
            if i > 0:
                self._log(
                    f"checkpoint restore: fell back to {cand.name} "
                    f"({i} newer candidate(s) skipped — see above)"
                )
            self.last_restored = cand.name
            return cand, tree, meta
        raise CheckpointRestoreError(tag, self.last_restore_report)

    def restore(self, state: TrainState, tag: str = _LATEST) -> tuple[TrainState, dict]:
        """Restore into the structure of ``state`` -> (state, meta).

        Falls back newest -> older -> best, verifying each candidate's
        integrity manifest; raises ``CheckpointRestoreError`` when the
        whole chain is exhausted.
        """
        with self._telemetry.span("checkpoint_restore", tag=tag):
            template = _state_pytree(state)
            cand, tree, meta = self._restore_chain(
                tag, lambda path: self._ckptr.restore(path, template)
            )
        from cgnn_tpu.train.normalizer import Normalizer

        restored = state.replace(
            step=tree["step"],
            params=tree["params"],
            batch_stats=tree["batch_stats"],
            opt_state=tree["opt_state"],
            normalizer=Normalizer.from_state_dict(tree["normalizer"]),
            rng=jax.random.wrap_key_data(tree["rng"]),
        )
        return restored, meta

    def restore_for_inference(self, state: TrainState, tag: str = _LATEST):
        """Restore params/stats/normalizer only (no optimizer template)."""
        with _pytree_checkpointer() as ckptr:
            _, raw, _ = self._restore_chain(tag, ckptr.restore)
        from cgnn_tpu.train.normalizer import Normalizer

        return state.replace(
            params=raw["params"],
            batch_stats=raw["batch_stats"],
            normalizer=Normalizer.from_state_dict(raw["normalizer"]),
        )

    def close(self):
        try:
            self.wait()
        finally:
            if self._worker is not None and self._worker.is_alive():
                self._jobs.put(None)
                self._worker.join(timeout=30)
            self._ckptr.close()
