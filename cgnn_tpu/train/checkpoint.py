"""Checkpoint/resume on orbax (SURVEY.md §2 component 10, §5).

Same semantics as the reference's ``save_checkpoint``/``--resume``: every
epoch saves the full training state (params, BatchNorm stats, optimizer
state, step, Normalizer, RNG) plus metadata (config dict, epoch, best
metric); the best-so-far checkpoint is retained alongside the latest
(``model_best.pth.tar`` equivalent). Saves are async — orbax writes in a
background thread while training continues.
"""

from __future__ import annotations

import json
import os

import jax
import orbax.checkpoint as ocp

from cgnn_tpu.train.state import TrainState

_LATEST = "latest"
_BEST = "best"


def _state_pytree(state: TrainState) -> dict:
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "normalizer": {"mean": state.normalizer.mean, "std": state.normalizer.std},
        "rng": jax.random.key_data(state.rng),
    }


class CheckpointManager:
    """Latest + best checkpoint pair with JSON metadata, async saves.

    ``telemetry`` (an ``observe.Telemetry``) wraps the host-side part of
    saves/restores in spans — saves are async (orbax writes in a
    background thread), so the span covers the device_get + dispatch,
    which is exactly the part that stalls training.
    """

    def __init__(self, directory: str, telemetry=None):
        from cgnn_tpu.observe import Telemetry

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ckptr = ocp.StandardCheckpointer()
        # Telemetry.span is already a nullcontext at level 'off'
        self._telemetry = telemetry or Telemetry.disabled()

    def _path(self, tag: str) -> str:
        return os.path.join(self.directory, tag)

    def _meta_path(self, tag: str) -> str:
        return os.path.join(self.directory, f"meta-{tag}.json")

    def read_meta(self, tag: str = _LATEST) -> dict:
        if not os.path.exists(self._meta_path(tag)):
            return {}
        with open(self._meta_path(tag)) as f:
            return json.load(f)

    def save(self, state: TrainState, meta: dict, is_best: bool = False):
        """Save 'latest' (and 'best' when ``is_best``); meta rides alongside
        as JSON (orbax pytrees are arrays-only; config strings go to JSON,
        mirroring the reference's checkpoint-embedded ``args``).

        The tree is host-localized (numpy) first so checkpoints carry no
        device-mesh shardings: a state saved from an 8-device DP/graph-
        sharded run must restore in a single-chip predict/resume process
        (orbax would otherwise bake the save-time sharding into the
        checkpoint and refuse topology-less restores)."""
        with self._telemetry.span("checkpoint_save", is_best=is_best):
            tree = jax.device_get(_state_pytree(state))
            for tag in [_LATEST] + ([_BEST] if is_best else []):
                self._ckptr.save(self._path(tag), tree, force=True)
                with open(self._meta_path(tag), "w") as f:
                    json.dump(meta, f, indent=1)

    def wait(self):
        self._ckptr.wait_until_finished()

    def exists(self, tag: str = _LATEST) -> bool:
        return os.path.isdir(self._path(tag))

    def restore(self, state: TrainState, tag: str = _LATEST) -> tuple[TrainState, dict]:
        """Restore into the structure of ``state`` -> (state, meta)."""
        self.wait()
        with self._telemetry.span("checkpoint_restore", tag=tag):
            tree = self._ckptr.restore(self._path(tag), _state_pytree(state))
        from cgnn_tpu.train.normalizer import Normalizer

        restored = state.replace(
            step=tree["step"],
            params=tree["params"],
            batch_stats=tree["batch_stats"],
            opt_state=tree["opt_state"],
            normalizer=Normalizer.from_state_dict(tree["normalizer"]),
            rng=jax.random.wrap_key_data(tree["rng"]),
        )
        return restored, self.read_meta(tag)

    def restore_for_inference(self, state: TrainState, tag: str = _LATEST):
        """Restore params/stats/normalizer only (no optimizer template)."""
        self.wait()
        with ocp.PyTreeCheckpointer() as ckptr:
            raw = ckptr.restore(self._path(tag))
        from cgnn_tpu.train.normalizer import Normalizer

        return state.replace(
            params=raw["params"],
            batch_stats=raw["batch_stats"],
            normalizer=Normalizer.from_state_dict(raw["normalizer"]),
        )

    def close(self):
        self.wait()
        self._ckptr.close()
